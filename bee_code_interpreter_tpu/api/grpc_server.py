"""gRPC server: the 3 CodeInterpreterService RPCs over grpc.aio.

Equivalent surface to the reference's gRPC layer (grpc_server.py:22-71 +
code_interpreter_servicer.py:33-135): async servicer, optional mTLS, oneof
success/error responses for the tool RPCs, per-RPC request-id correlation.

grpc_python_plugin isn't available here, so instead of generated ``_pb2_grpc``
stubs the service is registered through ``grpc.method_handlers_generic_handler``
with explicit (de)serializers — structurally the same trick as the reference's
reflection-based generic registrar (grpc_server.py:42-69), minus the generated
class it reflected over. ``service_stubs()`` builds the matching client-side
multicallables for health checks and tests.
"""

from __future__ import annotations

import asyncio
import json
import logging
import textwrap
import time
from contextlib import asynccontextmanager, nullcontext

import grpc
import grpc.aio
from google.protobuf import descriptor_pb2, descriptor_pool
from pydantic import ValidationError

from bee_code_interpreter_tpu.analysis import stash_predicted_deps
from bee_code_interpreter_tpu.api import models as api_models
from bee_code_interpreter_tpu.observability import (
    FleetJournal,
    FlightRecorder,
    Tracer,
    build_debug_bundle,
    current_trace,
    empty_slo_snapshot,
    find_journal,
    parse_traceparent,
    record_sli,
    record_usage_at_edge,
    register_stream_metrics,
    register_usage_metrics,
    task_inventory,
    thread_inventory,
)
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
from bee_code_interpreter_tpu.proto import health_pb2, reflection_pb2
from bee_code_interpreter_tpu.resilience import (
    AdmissionController,
    AdmissionRejected,
    BreakerOpenError,
    Deadline,
    DeadlineExceeded,
    SandboxTransientError,
)
from bee_code_interpreter_tpu.sessions import (
    CheckpointNotFound,
    InvalidSessionRequest,
    SessionLimitExceeded,
    SessionNotFound,
    streamed_events,
)
from bee_code_interpreter_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecuteError,
    CustomToolExecutor,
    CustomToolParseError,
)
from bee_code_interpreter_tpu.tenancy import (
    TENANT_METADATA_KEY,
    bearer_token,
    build_tenants_snapshot,
    tenant_scope,
)
from bee_code_interpreter_tpu.utils.metrics import Registry
from bee_code_interpreter_tpu.utils.request_id import new_request_id

logger = logging.getLogger(__name__)

SERVICE_NAME = "code_interpreter.v1.CodeInterpreterService"

# grpc.aio's context.abort unwinds the handler by raising this; an empty
# tuple (older grpcio without the symbol) simply catches nothing and aborts
# from run() fall through to the catch-all.
_ABORT_ERRORS = tuple(
    t for t in (getattr(grpc.aio, "AbortError", None),) if t is not None
)

# Abort codes that are the SERVER's fault for SLI purposes: an explicit
# INTERNAL abort is the gRPC spelling of the HTTP edge's 500 and must burn
# availability budget exactly like one (docs/observability.md "SLOs").
_SERVER_FAULT_CODES = frozenset(
    {grpc.StatusCode.INTERNAL, grpc.StatusCode.UNKNOWN, grpc.StatusCode.DATA_LOSS}
)

class _SliSample:
    """Mutable outcome holder for one RPC's SLI sample. ``ok`` None at scope
    exit means "not a sample" (shed, drain, client cancel)."""

    __slots__ = ("ok",)

    def __init__(self) -> None:
        self.ok: bool | None = None


_METHODS: dict[str, tuple[type, type]] = {
    "Execute": (pb.ExecuteRequest, pb.ExecuteResponse),
    "ParseCustomTool": (pb.ParseCustomToolRequest, pb.ParseCustomToolResponse),
    "ExecuteCustomTool": (pb.ExecuteCustomToolRequest, pb.ExecuteCustomToolResponse),
}


def _annotate_outcome(label: str, ok: bool | None) -> None:
    """Stamp the resilience ladder's verdict on the RPC's root span so the
    flight recorder's wide event (a tracer sink) carries the outcome and
    SLO classification — the exact mirror of the HTTP edge's annotation."""
    trace = current_trace()
    if trace is not None:
        trace.root.attributes["outcome"] = label
        if ok is not None:
            trace.root.attributes["sli"] = "good" if ok else "bad"


def _violation_text(error: ValidationError) -> str:
    """Render pydantic errors the way protovalidate renders violations: a
    field path plus the constraint message (reference
    code_interpreter_servicer.py:44-53 aborts with the violation list)."""
    return "; ".join(
        f"{'.'.join(str(part) for part in err['loc']) or 'request'}: {err['msg']}"
        for err in error.errors()
    )


async def _validated(context: grpc.aio.ServicerContext, model_cls, **fields):
    """Run the SAME pydantic model the HTTP transport uses (api/models.py) so
    the two transports accept/reject identical requests; abort
    INVALID_ARGUMENT with the violation text on failure."""
    try:
        return model_cls(**fields)
    except ValidationError as e:
        await context.abort(grpc.StatusCode.INVALID_ARGUMENT, _violation_text(e))


class CodeInterpreterServicer:
    """RPC implementations (reference code_interpreter_servicer.py:33-135).

    Resilience contract (docs/resilience.md): sandbox-bound RPCs get a
    ``Deadline`` — the service budget capped by the client's own gRPC
    deadline when one is attached — propagated through the executor; a blown
    deadline aborts DEADLINE_EXCEEDED. When an ``AdmissionController`` is
    wired in, overload sheds as RESOURCE_EXHAUSTED with a ``retry-after-s``
    hint in the trailing metadata.
    """

    def __init__(
        self,
        code_executor: CodeExecutor,
        custom_tool_executor: CustomToolExecutor,
        admission: AdmissionController | None = None,
        request_deadline_s: float | None = None,
        metrics: Registry | None = None,
        tracer: Tracer | None = None,
        drain=None,  # resilience.DrainController
        slo=None,  # observability.SloEngine (shared with the HTTP edge)
        analyzer=None,  # analysis.WorkloadAnalyzer (shared with the HTTP edge)
        sessions=None,  # sessions.SessionManager (shared with the HTTP edge)
        tenancy=None,  # tenancy.TenantRegistry (shared with the HTTP edge)
    ) -> None:
        self._code_executor = code_executor
        self._custom_tool_executor = custom_tool_executor
        self._admission = admission
        self._request_deadline_s = request_deadline_s
        self._drain = drain
        self._slo = slo
        self._analyzer = analyzer
        self._sessions = sessions
        self._tenancy = tenancy
        self._tracer = tracer or Tracer(metrics=metrics)
        self._deadline_exceeded_total = (
            metrics.counter(
                "bci_deadline_exceeded_total",
                "Requests that ran out of their edge deadline",
            )
            if metrics is not None
            else None
        )
        # Execution-cost histograms shared with the HTTP edge (registry
        # dedups by name); the proto ExecuteResponse has no usage field, so
        # gRPC callers read the figures off the trace span / metrics.
        self._execution_cpu_seconds, self._execution_peak_rss = (
            register_usage_metrics(metrics) if metrics is not None else (None, None)
        )
        self._stream_ttfb_seconds, self._stream_chunks_total = (
            register_stream_metrics(metrics) if metrics is not None else (None, None)
        )

    def _sample_client_fault(self, start: float) -> None:
        """A sandbox-bound RPC rejected at validation is the CLIENT's fault:
        sampled as good, mirroring the HTTP edge's 422 — both transports
        must compute identical SLIs for identical workloads."""
        if self._slo is not None:
            self._slo.record(ok=True, duration_s=time.monotonic() - start)

    async def _validated_sampled(
        self, context: grpc.aio.ServicerContext, start: float, model_cls, **fields
    ):
        """:func:`_validated` for the sandbox-bound RPCs: a validation
        failure records its (good) SLI sample before aborting."""
        try:
            return model_cls(**fields)
        except ValidationError as e:
            self._sample_client_fault(start)
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, _violation_text(e)
            )

    def _trace_rpc(self, method: str, context: grpc.aio.ServicerContext, rid: str):
        """Root a trace for one RPC, continuing an inbound ``traceparent``
        when the client attached one as invocation metadata (the gRPC
        spelling of the HTTP header contract)."""
        metadata = {
            k.lower(): v for k, v in (context.invocation_metadata() or ())
        }
        inbound = parse_traceparent(metadata.get("traceparent"))
        return self._tracer.trace(
            f"grpc:{method}",
            trace_id=inbound[0] if inbound else None,
            parent_span_id=inbound[1] if inbound else None,
            request_id=rid,
        )

    def _resolve_tenant(self, context: grpc.aio.ServicerContext):
        """The gRPC spelling of tenant resolution (docs/tenancy.md):
        ``x-tenant-id`` invocation metadata, or an ``authorization: Bearer``
        API key from the tenant table; None when no registry is wired."""
        if self._tenancy is None:
            return None
        metadata = {
            k.lower(): v for k, v in (context.invocation_metadata() or ())
        }
        tctx = self._tenancy.resolve(
            metadata.get(TENANT_METADATA_KEY),
            bearer_token(metadata.get("authorization")),
        )
        if self._admission is not None and tctx.retry_budget is None:
            tctx.retry_budget = self._admission.tenant_retry_budget(tctx)
        return tctx

    def _new_deadline(self, context: grpc.aio.ServicerContext) -> Deadline | None:
        budget = self._request_deadline_s
        client_remaining = context.time_remaining()
        if client_remaining is not None:
            # `is not None`, not truthiness: an already-expired client
            # deadline reads 0.0, which must become an immediately-expired
            # Deadline (abort DEADLINE_EXCEEDED), not "no deadline at all".
            budget = (
                min(budget, client_remaining)
                if budget is not None
                else client_remaining
            )
        return Deadline.after(budget) if budget is not None else None

    @asynccontextmanager
    async def _resilience_scope(
        self,
        context: grpc.aio.ServicerContext,
        allow_draining: bool = False,
    ):
        """The shared resilience ladder for sandbox-bound RPCs — drain check,
        edge deadline, admission gate, the shed/deadline abort contract
        (docs/resilience.md), and SLI recording — the one place it is spelled
        for gRPC. Yields ``(deadline, sample)``; unary bodies run inside it
        via :meth:`_with_resilience`, the streaming generator (which cannot
        call a plain wrapper because it must yield) enters it directly and
        sets ``sample.ok`` per terminal event.

        SLI recording mirrors the HTTP edge (docs/observability.md "SLOs"):
        server-side failures (blown deadline, open breaker, internal error)
        burn availability budget; client-fault aborts raised by the body
        (INVALID_ARGUMENT) count good; shed/drain/cancel are excluded."""
        # Tenant identity resolves HERE from the invocation metadata — the
        # gRPC twin of the HTTP middleware (docs/tenancy.md): its quotas
        # apply at the admission gate, its SLO slice gets the sample, its
        # usage meter gets the outcome.
        tctx = self._resolve_tenant(context)
        with tenant_scope(tctx):
            if tctx is not None:
                trace = current_trace()
                if trace is not None:
                    trace.root.attributes["tenant"] = tctx.label
            # Drain check BEFORE admission (mirror of the HTTP edge): a
            # draining replica rejects new work retryably while in-flight
            # RPCs (tracked below) run to completion. Health answers
            # NOT_SERVING. Evacuation ops (``allow_draining``: session
            # checkpoint — the lease-handoff path, docs/fleet.md) are
            # exempt on BOTH transports.
            if (
                self._drain is not None
                and self._drain.draining
                and not allow_draining
            ):
                context.set_trailing_metadata(
                    (("retry-after-s", f"{self._drain.retry_after_s:g}"),)
                )
                _annotate_outcome("drained", None)
                await context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    "service draining; retry against another replica",
                )
            deadline = self._new_deadline(context)
            slo_start = time.monotonic()
            sample = _SliSample()
            label = "cancelled"  # only a CancelledError leaves it unassigned
            try:
                try:
                    # track() covers the admission wait too (mirror of the
                    # HTTP edge): a queued waiter was admitted past the
                    # drain check and WILL execute — teardown must wait
                    # for it.
                    with (
                        self._drain.track()
                        if self._drain is not None
                        else nullcontext()
                    ):
                        async with (
                            self._admission.admit(deadline, tenant=tctx)
                            if self._admission is not None
                            else nullcontext()
                        ):
                            yield deadline, sample
                    if sample.ok is None:
                        sample.ok = True
                    label = "ok" if sample.ok else "error"
                except AdmissionRejected as e:
                    label = "shed"
                    context.set_trailing_metadata(
                        (("retry-after-s", f"{e.retry_after_s:g}"),)
                    )
                    await context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"service overloaded ({e.reason}); "
                        f"retry in {e.retry_after_s:g}s",
                    )
                except DeadlineExceeded:
                    sample.ok = False
                    label = "deadline"
                    if self._deadline_exceeded_total is not None:
                        self._deadline_exceeded_total.inc(transport="grpc")
                    await context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        "request deadline exceeded",
                    )
                except BreakerOpenError as e:
                    # Open breaker, no fallback: retryable overload, not an
                    # internal error — UNAVAILABLE with the breaker's retry
                    # hint.
                    sample.ok = False
                    label = "breaker_open"
                    context.set_trailing_metadata(
                        (("retry-after-s", f"{e.retry_after_s:g}"),)
                    )
                    await context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        "backend temporarily unavailable; "
                        f"retry in {e.retry_after_s:g}s",
                    )
                except asyncio.CancelledError:
                    raise  # client went away: sample.ok untouched (not a sample)
                except _ABORT_ERRORS:
                    # The body aborted with an explicit status. Client-fault
                    # codes (INVALID_ARGUMENT/NOT_FOUND/…) sample good — the
                    # twin of the HTTP edge's 4xx — while an INTERNAL abort
                    # (the 500 twin: sandbox died, execution failed) must
                    # burn budget like the 500 it mirrors. The context's
                    # code is the verdict; a body that already set
                    # sample.ok (ExecuteStream terminal events) wins.
                    if sample.ok is None:
                        sample.ok = context.code() not in _SERVER_FAULT_CODES
                    label = "client_error" if sample.ok else "error"
                    raise
                except BaseException:
                    sample.ok = False  # unhandled → gRPC UNKNOWN
                    label = "error"
                    raise
            finally:
                if self._slo is not None and sample.ok is not None:
                    record_sli(
                        self._slo,
                        ok=sample.ok,
                        duration_s=time.monotonic() - slo_start,
                        tenant=tctx.label if tctx is not None else None,
                    )
                if tctx is not None:
                    # Mirror of the HTTP edge: every resolved RPC lands in
                    # the tenant's usage meter with its outcome.
                    tctx.record_request(label)
                _annotate_outcome(label, sample.ok)

    async def _with_resilience(
        self,
        context: grpc.aio.ServicerContext,
        run,
        allow_draining: bool = False,
    ):
        """Run a unary sandbox-bound RPC body under :meth:`_resilience_scope`;
        ``run(deadline)`` returns the success response."""
        async with self._resilience_scope(
            context, allow_draining=allow_draining
        ) as (deadline, _sample):
            return await run(deadline)

    async def Execute(
        self, request: pb.ExecuteRequest, context: grpc.aio.ServicerContext
    ) -> pb.ExecuteResponse:
        rid = new_request_id()
        rpc_start = time.monotonic()
        if not request.source_code:
            self._sample_client_fault(rpc_start)
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "source_code is required")
        validated = await self._validated_sampled(
            context,
            rpc_start,
            api_models.ExecuteRequest,
            source_code=request.source_code,
            files=dict(request.files),
            env=dict(request.env),
            timeout=request.timeout or None,  # proto default 0 = unset
        )
        logger.info("Executing code: %s", validated.source_code)

        async def run(deadline):
            # Per-request reset (mirror of the HTTP edge): never let a
            # prediction stashed earlier in this task's context describe
            # THIS source.
            stash_predicted_deps(None)
            verdict = None
            if self._analyzer is not None:
                # The gate mirrors the HTTP edge exactly (docs/analysis.md):
                # syntax errors answer as a normal exit_code=1 response with
                # zero sandbox checkouts; policy denies abort
                # INVALID_ARGUMENT (a client fault, SLI-good via the abort
                # handling in _with_resilience); warn findings and the
                # cost_class hint ride the trailing metadata (the proto
                # response has no field for them) and the dep prediction
                # ships with the data plane.
                verdict = self._analyzer.analyze(validated.source_code)
                if verdict.syntax_error is not None:
                    return pb.ExecuteResponse(
                        stdout="",
                        stderr=verdict.syntax_error,
                        exit_code=1,
                    )
                if verdict.denials:
                    await context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        f"denied by execution policy: {verdict.denial_detail()}",
                    )
                trailers = []
                if verdict.warnings:
                    trailers.append(
                        (
                            "bci-analysis-warnings",
                            "; ".join(f.rule for f in verdict.warnings),
                        )
                    )
                if verdict.cost_class is not None:
                    trailers.append(
                        ("bci-analysis-cost-class", verdict.cost_class)
                    )
                if trailers:
                    context.set_trailing_metadata(tuple(trailers))
                stash_predicted_deps(verdict.predicted_deps)
            # Cost-aware admission (opt-in; mirror of the HTTP edge): a
            # heavy-lane shed aborts RESOURCE_EXHAUSTED via the shared
            # AdmissionRejected handling in _resilience_scope.
            async with (
                self._admission.heavy_lane(verdict.cost_class)
                if self._admission is not None and verdict is not None
                else nullcontext()
            ):
                try:
                    result = await self._code_executor.execute(
                        source_code=validated.source_code,
                        files=validated.files,
                        env=validated.env,  # env forwarded, unlike reference (:67-70)
                        timeout_s=validated.timeout,
                        deadline=deadline,
                    )
                except (DeadlineExceeded, BreakerOpenError):
                    raise  # shared resilience contract (DEADLINE_EXCEEDED/UNAVAILABLE)
                except Exception:
                    # The HTTP twin answers 500 "Execution failed" here; an
                    # unhandled escape would surface as UNKNOWN — INTERNAL
                    # is the canonical 500 mapping (docs/analysis.md
                    # "Contract lint"), and the abort arm samples it bad.
                    logger.exception("Execution failed")
                    await context.abort(
                        grpc.StatusCode.INTERNAL, "execution failed"
                    )
            record_usage_at_edge(
                result.usage,
                current_trace(),
                self._execution_cpu_seconds,
                self._execution_peak_rss,
            )
            return pb.ExecuteResponse(
                stdout=result.stdout,
                stderr=result.stderr,
                exit_code=result.exit_code,
                files=result.files,
            )

        with self._trace_rpc("Execute", context, rid):
            return await self._with_resilience(context, run)

    async def ExecuteStream(self, request: bytes, context: grpc.aio.ServicerContext):
        """Server-streaming execute over JSON message bytes (the checked-in
        ``*_pb2`` descriptors cannot grow new message types without protoc —
        same trick as ``FleetService``). Request:

            {"source_code": ..., "files": {...}, "env": {...},
             "timeout": N, "session_id": "sess-..."?}

        With ``session_id`` the execution runs inside that lease
        (docs/sessions.md); without it, on a single-use sandbox. Responses
        are the shared streaming event vocabulary: ``{"stream": "stdout"|
        "stderr", "data": ...}`` chunks, then exactly one terminal
        ``{"event": "result", ...envelope...}`` or ``{"event": "error",
        "detail": ...}``. Failures after the first chunk are in-band
        terminal events (chunks cannot be un-delivered), mirroring SSE."""
        rid = new_request_id()
        rpc_start = time.monotonic()
        try:
            body = json.loads(request.decode() or "{}")
            if not isinstance(body, dict):
                raise ValueError("request must be a JSON object")
        except (ValueError, UnicodeDecodeError):
            self._sample_client_fault(rpc_start)
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                'request must be JSON like {"source_code": "print(1)"}',
            )
        session_id = body.get("session_id")
        validated = await self._validated_sampled(
            context,
            rpc_start,
            api_models.ExecuteRequest,
            source_code=body.get("source_code") or "",
            files=body.get("files") or {},
            env=body.get("env") or {},
            timeout=body.get("timeout") or None,
        )
        if not validated.source_code:
            self._sample_client_fault(rpc_start)
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "source_code is required"
            )
        with self._trace_rpc("ExecuteStream", context, rid):
            # The generator cannot run inside _with_resilience (it must
            # yield), so it enters the shared ladder directly; terminal
            # events set sample.ok the way a unary body's return would.
            async with self._resilience_scope(context) as (deadline, sample):
                stream_start = time.monotonic()
                chunks = 0
                first_chunk_s: float | None = None

                def _annotate_stream() -> None:
                    # Stream context onto the root span (→ the wide event)
                    # and the production streaming metrics, mirroring SSE.
                    if self._stream_chunks_total is not None:
                        self._stream_chunks_total.inc(chunks, transport="grpc")
                    trace = current_trace()
                    if trace is not None:
                        trace.root.attributes["stream.chunks"] = str(chunks)
                        if first_chunk_s is not None:
                            trace.root.attributes["stream.ttfb_ms"] = (
                                f"{first_chunk_s * 1000:.3f}"
                            )

                stash_predicted_deps(None)
                verdict = (
                    self._analyzer.analyze(validated.source_code)
                    if self._analyzer is not None
                    else None
                )
                # finally, not per-terminal-event calls: a client that
                # cancels mid-stream unwinds the generator before any
                # terminal event, and its delivered chunks must still be
                # counted and stamped on the wide event (SSE twin agrees).
                try:
                    if verdict is not None:
                        if verdict.syntax_error is not None:
                            # Fail-fast terminal event, zero checkouts.
                            sample.ok = True
                            yield json.dumps(
                                {
                                    "event": "result",
                                    "stdout": "",
                                    "stderr": verdict.syntax_error,
                                    "exit_code": 1,
                                }
                            ).encode()
                            return
                        if verdict.denials:
                            await context.abort(
                                grpc.StatusCode.INVALID_ARGUMENT,
                                "denied by execution policy: "
                                f"{verdict.denial_detail()}",
                            )
                        stash_predicted_deps(verdict.predicted_deps)
                    async for event in self._stream_events(
                        session_id, validated, deadline, context
                    ):
                        if event.get("event") == "error":
                            sample.ok = event.pop("_client_fault", False)
                        elif event.get("event") == "result":
                            sample.ok = True
                        else:
                            if chunks == 0:
                                first_chunk_s = (
                                    time.monotonic() - stream_start
                                )
                                if self._stream_ttfb_seconds is not None:
                                    self._stream_ttfb_seconds.observe(
                                        first_chunk_s, transport="grpc"
                                    )
                            chunks += 1
                        yield json.dumps(event).encode()
                finally:
                    _annotate_stream()

    async def _stream_events(self, session_id, validated, deadline, context):
        """The shared chunk/terminal event pump for ``ExecuteStream``,
        sessionful or stateless. Terminal errors carry ``_client_fault``
        (stripped before the wire) so the caller samples the SLI right."""
        if session_id is not None:
            if self._sessions is None:
                await context.abort(
                    grpc.StatusCode.UNIMPLEMENTED,
                    "no session manager wired into this server",
                )
            trace = current_trace()
            if trace is not None:
                trace.root.attributes["session"] = str(session_id)

            def run(on_event):
                return self._sessions.execute(
                    session_id,
                    validated.source_code,
                    files=validated.files,
                    env=validated.env,
                    timeout_s=validated.timeout,
                    deadline=deadline,
                    on_event=on_event,
                )

        else:
            from bee_code_interpreter_tpu.observability import unwrap_executor

            backend = unwrap_executor(self._code_executor)
            if not hasattr(backend, "execute_stream"):
                await context.abort(
                    grpc.StatusCode.UNIMPLEMENTED,
                    "this backend cannot stream output",
                )

            def run(on_event):
                return backend.execute_stream(
                    validated.source_code,
                    files=validated.files,
                    env=validated.env,
                    timeout_s=validated.timeout,
                    on_event=on_event,
                    deadline=deadline,
                )

        async for item in streamed_events(run):
            if item.get("event") == "error":
                error = item["error"]
                if isinstance(error, asyncio.CancelledError):
                    raise error
                logger.warning("Streaming execution failed: %r", error)
                if isinstance(error, DeadlineExceeded):
                    yield {"event": "error", "detail": "deadline exceeded"}
                elif isinstance(error, SessionNotFound):
                    yield {
                        "event": "error",
                        "detail": str(error),
                        "_client_fault": True,
                    }
                else:
                    yield {"event": "error", "detail": "execution failed"}
            elif item.get("event") == "result":
                result = item["result"]
                trace = current_trace()
                if session_id is not None:
                    session, outcome = result
                    record_usage_at_edge(
                        outcome.usage,
                        trace,
                        self._execution_cpu_seconds,
                        self._execution_peak_rss,
                    )
                    yield {
                        "event": "result",
                        "stdout": outcome.stdout,
                        "stderr": outcome.stderr,
                        "exit_code": outcome.exit_code,
                        "changed_paths": outcome.changed_paths,
                        "session_id": session.session_id,
                        "execution": session.executions,
                        "trace_id": (
                            trace.trace_id if trace is not None else None
                        ),
                        "usage": outcome.usage,
                    }
                else:
                    record_usage_at_edge(
                        result.usage,
                        trace,
                        self._execution_cpu_seconds,
                        self._execution_peak_rss,
                    )
                    yield {
                        "event": "result",
                        "stdout": result.stdout,
                        "stderr": result.stderr,
                        "exit_code": result.exit_code,
                        "files": result.files,
                        "trace_id": (
                            trace.trace_id if trace is not None else None
                        ),
                        "usage": result.usage,
                    }
            else:
                yield item

    async def ParseCustomTool(
        self, request: pb.ParseCustomToolRequest, context: grpc.aio.ServicerContext
    ) -> pb.ParseCustomToolResponse:
        new_request_id()
        validated = await _validated(
            context,
            api_models.ParseCustomToolRequest,
            tool_source_code=request.tool_source_code,
        )
        try:
            tool = self._custom_tool_executor.parse(validated.tool_source_code)
        except CustomToolParseError as e:
            return pb.ParseCustomToolResponse(
                error=pb.ParseCustomToolResponse.ErrorResponse(
                    error_messages=e.error_messages
                )
            )
        import json

        return pb.ParseCustomToolResponse(
            success=pb.ParseCustomToolResponse.SuccessResponse(
                tool_name=tool.name,
                tool_input_schema_json=json.dumps(tool.input_schema),
                tool_description=tool.description,
            )
        )

    async def ExecuteCustomTool(
        self, request: pb.ExecuteCustomToolRequest, context: grpc.aio.ServicerContext
    ) -> pb.ExecuteCustomToolResponse:
        rid = new_request_id()
        rpc_start = time.monotonic()
        import json

        validated = await self._validated_sampled(
            context,
            rpc_start,
            api_models.ExecuteCustomToolRequest,
            tool_source_code=request.tool_source_code,
            tool_input_json=request.tool_input_json,
            env=dict(request.env),
        )
        async def run(deadline):
            stash_predicted_deps(None)  # per-request reset, see Execute
            if self._analyzer is not None:
                # Policy half only, analyzed DEDENTED like the parser does
                # (mirror of the HTTP edge): a syntax error in tool source
                # keeps the parser's oneof-error contract, and no dep
                # prediction is stashed — the sandbox runs the generated
                # wrapper, whose imports the tool source doesn't mention.
                verdict = self._analyzer.analyze(
                    textwrap.dedent(validated.tool_source_code)
                )
                if verdict.syntax_error is None and verdict.denials:
                    await context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "denied by execution policy: "
                        f"{verdict.denial_detail()}",
                    )
            try:
                output = await self._custom_tool_executor.execute(
                    tool_source_code=validated.tool_source_code,
                    tool_input_json=validated.tool_input_json,
                    env=validated.env,
                    deadline=deadline,
                )
            except CustomToolParseError as e:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, "; ".join(e.error_messages)
                )
            except CustomToolExecuteError as e:
                return pb.ExecuteCustomToolResponse(
                    error=pb.ExecuteCustomToolResponse.ErrorResponse(stderr=e.stderr)
                )
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (DEADLINE_EXCEEDED/UNAVAILABLE)
            except Exception:
                # Mirror of the HTTP twin's 500 (a raw sandbox failure must
                # not escape as UNKNOWN); sampled bad via the abort arm.
                logger.exception("Custom tool execution failed")
                await context.abort(
                    grpc.StatusCode.INTERNAL, "execution failed"
                )
            return pb.ExecuteCustomToolResponse(
                success=pb.ExecuteCustomToolResponse.SuccessResponse(
                    tool_output_json=json.dumps(output)
                )
            )

        with self._trace_rpc("ExecuteCustomTool", context, rid):
            return await self._with_resilience(context, run)


SESSION_SERVICE_NAME = "code_interpreter.v1.SessionService"


class SessionServicer:
    """The session-lease API over gRPC (docs/sessions.md): JSON message
    bytes through a generic handler, the transport mirror of the
    ``/v1/sessions`` HTTP routes (same manager, same semantics; protoc is
    unavailable so no generated messages — the ``FleetService`` trick).

    Wraps the main :class:`CodeInterpreterServicer` to reuse its
    resilience/SLO/trace/analyzer plumbing — per-execute admission,
    deadline, analysis, and SLI sampling match the stateless path."""

    def __init__(self, servicer: CodeInterpreterServicer) -> None:
        self._s = servicer

    async def _manager(self, context):
        manager = self._s._sessions
        if manager is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no session manager wired into this server",
            )
        return manager

    @staticmethod
    async def _body(request: bytes, context) -> dict:
        if not request:
            return {}
        try:
            body = json.loads(request.decode())
            if not isinstance(body, dict):
                raise ValueError("not an object")
        except (ValueError, UnicodeDecodeError):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "request must be a JSON object",
            )
        return body

    async def CreateSession(self, request: bytes, context) -> bytes:
        s = self._s
        manager = await self._manager(context)
        body = await self._body(request, context)
        rid = new_request_id()

        async def run(deadline):
            stash_predicted_deps(None)
            try:
                session = await manager.create(
                    files=body.get("files") or {},
                    ttl_s=body.get("ttl_s"),
                    idle_s=body.get("idle_s"),
                    deadline=deadline,
                )
            except InvalidSessionRequest as e:
                # The JSON-bytes edge has no generated message to validate
                # with; the manager is the backstop (its docstring) and the
                # fault is the client's — INVALID_ARGUMENT, SLI-good, the
                # exact twin of the HTTP edge's pydantic 422.
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except SessionLimitExceeded as e:
                context.set_trailing_metadata(
                    (("retry-after-s", f"{e.retry_after_s:g}"),)
                )
                await context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED, str(e)
                )
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (DEADLINE_EXCEEDED/UNAVAILABLE)
            except Exception:
                # HTTP twin: 500 "Session create failed". An unhandled
                # escape would be UNKNOWN; INTERNAL is the canonical 500
                # mapping and the abort arm samples it bad.
                logger.exception("Session create failed")
                await context.abort(
                    grpc.StatusCode.INTERNAL, "session create failed"
                )
            return json.dumps(
                {
                    "session_id": session.session_id,
                    "expires_at": session.expires_unix,
                    "ttl_s": session.ttl_s,
                    "idle_timeout_s": session.idle_s,
                    "sandbox": session.lease.name,
                }
            ).encode()

        with s._trace_rpc("CreateSession", context, rid):
            return await s._with_resilience(context, run)

    async def ExecuteInSession(self, request: bytes, context) -> bytes:
        s = self._s
        manager = await self._manager(context)
        body = await self._body(request, context)
        session_id = str(body.get("session_id") or "")
        rid = new_request_id()
        rpc_start = time.monotonic()
        validated = await s._validated_sampled(
            context,
            rpc_start,
            api_models.SessionExecuteRequest,
            source_code=body.get("source_code") or "",
            files=body.get("files") or {},
            env=body.get("env") or {},
            timeout=body.get("timeout") or None,
        )

        async def run(deadline):
            stash_predicted_deps(None)
            trace = current_trace()
            if trace is not None:
                trace.root.attributes["session"] = session_id
            verdict = (
                s._analyzer.analyze(validated.source_code)
                if s._analyzer is not None
                else None
            )
            if verdict is not None:
                if verdict.syntax_error is not None:
                    try:
                        session = manager.get(session_id)
                    except SessionNotFound as e:
                        await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
                    return json.dumps(
                        {
                            "stdout": "",
                            "stderr": verdict.syntax_error,
                            "exit_code": 1,
                            "changed_paths": [],
                            "session_id": session.session_id,
                            "execution": session.executions,
                        }
                    ).encode()
                if verdict.denials:
                    await context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "denied by execution policy: "
                        f"{verdict.denial_detail()}",
                    )
                stash_predicted_deps(verdict.predicted_deps)
            try:
                session, outcome = await manager.execute(
                    session_id,
                    validated.source_code,
                    files=validated.files,
                    env=validated.env,
                    timeout_s=validated.timeout,
                    deadline=deadline,
                )
            except SessionNotFound as e:
                await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (DEADLINE_EXCEEDED/UNAVAILABLE)
            except SandboxTransientError:
                # The leased sandbox died mid-execute: the HTTP twin's 500
                # "Session sandbox died; lease ended" — INTERNAL, sampled
                # bad via the abort arm, never an UNKNOWN escape.
                logger.exception("Leased sandbox died mid-execute")
                await context.abort(
                    grpc.StatusCode.INTERNAL, "session sandbox died; lease ended"
                )
            except Exception:
                logger.exception("Session execution failed")
                await context.abort(
                    grpc.StatusCode.INTERNAL, "execution failed"
                )
            record_usage_at_edge(
                outcome.usage,
                current_trace(),
                s._execution_cpu_seconds,
                s._execution_peak_rss,
            )
            return json.dumps(
                {
                    "stdout": outcome.stdout,
                    "stderr": outcome.stderr,
                    "exit_code": outcome.exit_code,
                    "changed_paths": outcome.changed_paths,
                    "session_id": session.session_id,
                    "execution": session.executions,
                    "expires_at": session.expires_unix,
                    "usage": outcome.usage,
                }
            ).encode()

        with s._trace_rpc("ExecuteInSession", context, rid):
            return await s._with_resilience(context, run)

    async def Checkpoint(self, request: bytes, context) -> bytes:
        s = self._s
        manager = await self._manager(context)
        body = await self._body(request, context)
        session_id = str(body.get("session_id") or "")
        rid = new_request_id()

        async def run(deadline):
            stash_predicted_deps(None)
            try:
                session, checkpoint = await manager.checkpoint(
                    session_id, deadline=deadline
                )
            except SessionNotFound as e:
                await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (DEADLINE_EXCEEDED/UNAVAILABLE)
            except Exception:
                # HTTP twin: 500 "Checkpoint failed" — INTERNAL, not UNKNOWN.
                logger.exception("Session checkpoint failed")
                await context.abort(
                    grpc.StatusCode.INTERNAL, "checkpoint failed"
                )
            return json.dumps(
                {
                    "session_id": session.session_id,
                    "checkpoint_id": checkpoint.checkpoint_id,
                    "files": checkpoint.files,
                }
            ).encode()

        with s._trace_rpc("Checkpoint", context, rid):
            # allow_draining: lease handoff checkpoints THROUGH the drain
            # window (docs/fleet.md), matching the HTTP edge.
            return await s._with_resilience(context, run, allow_draining=True)

    async def Rollback(self, request: bytes, context) -> bytes:
        s = self._s
        manager = await self._manager(context)
        body = await self._body(request, context)
        session_id = str(body.get("session_id") or "")
        rid = new_request_id()

        async def run(deadline):
            stash_predicted_deps(None)
            try:
                session, checkpoint = await manager.rollback(
                    session_id,
                    str(body.get("checkpoint_id") or ""),
                    deadline=deadline,
                )
            except (SessionNotFound, CheckpointNotFound) as e:
                await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (DEADLINE_EXCEEDED/UNAVAILABLE)
            except Exception:
                # HTTP twin: 500 "Rollback failed" — INTERNAL, not UNKNOWN.
                logger.exception("Session rollback failed")
                await context.abort(
                    grpc.StatusCode.INTERNAL, "rollback failed"
                )
            return json.dumps(
                {
                    "session_id": session.session_id,
                    "checkpoint_id": checkpoint.checkpoint_id,
                    "files": checkpoint.files,
                }
            ).encode()

        with s._trace_rpc("Rollback", context, rid):
            return await s._with_resilience(context, run)

    async def DeleteSession(self, request: bytes, context) -> bytes:
        manager = await self._manager(context)
        body = await self._body(request, context)
        new_request_id()
        try:
            session = await manager.release(str(body.get("session_id") or ""))
        except SessionNotFound as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return json.dumps(
            {
                "session_id": session.session_id,
                "released": True,
                "executions": session.executions,
            }
        ).encode()

    async def ListSessions(self, request: bytes, context) -> bytes:
        manager = await self._manager(context)
        return json.dumps(manager.snapshot()).encode()


_SESSION_METHODS = (
    "CreateSession",
    "ExecuteInSession",
    "Checkpoint",
    "Rollback",
    "DeleteSession",
    "ListSessions",
)


def _session_handler(servicer: SessionServicer) -> grpc.GenericRpcHandler:
    passthrough = bytes  # JSON bytes in/out; no generated messages
    return grpc.method_handlers_generic_handler(
        SESSION_SERVICE_NAME,
        {
            name: grpc.unary_unary_rpc_method_handler(
                getattr(servicer, name),
                request_deserializer=passthrough,
                response_serializer=passthrough,
            )
            for name in _SESSION_METHODS
        },
    )


def session_stubs(channel: grpc.aio.Channel | grpc.Channel) -> dict[str, object]:
    """Client-side multicallables for the session RPCs (tooling/tests);
    send JSON bytes and json.loads the reply."""
    return {
        name: channel.unary_unary(f"/{SESSION_SERVICE_NAME}/{name}")
        for name in _SESSION_METHODS
    }


def execute_stream_stub(channel: grpc.aio.Channel | grpc.Channel):
    """Client-side ``ExecuteStream`` multicallable: send JSON request
    bytes, iterate JSON event bytes (docs/sessions.md wire format)."""
    return channel.unary_stream(f"/{SERVICE_NAME}/ExecuteStream")


FLEET_SERVICE_NAME = "code_interpreter.v1.FleetService"


class FleetServicer:
    """The fleet lifecycle journal over gRPC (docs/observability.md): the
    same snapshot/events payloads ``GET /v1/fleet[/events]`` serves, as
    JSON-encoded message bytes through a generic handler — the checked-in
    ``*_pb2`` descriptors cannot grow new message types without protoc,
    which this environment doesn't have. ``GetFleetEvents`` accepts an
    optional JSON request body ``{"limit": N}``."""

    def __init__(self, journal: FleetJournal) -> None:
        self._journal = journal

    async def GetFleet(self, request: bytes, context) -> bytes:
        return json.dumps(self._journal.snapshot()).encode()

    async def GetFleetEvents(self, request: bytes, context) -> bytes:
        limit = 100
        if request:
            try:
                # TypeError covers {"limit": null} / {"limit": [1]} — every
                # malformed shape must be INVALID_ARGUMENT, never UNKNOWN.
                limit = int(json.loads(request.decode()).get("limit", limit))
                if limit < 0:
                    # the HTTP twin (GET /v1/fleet/events) 400s negative
                    # limits; the old max(0, …) clamp silently diverged
                    raise ValueError("limit must be >= 0")
            except (ValueError, TypeError, AttributeError, OverflowError):
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    'request must be JSON like {"limit": 50} (limit >= 0)',
                )
        return json.dumps(
            {"events": self._journal.events(limit=limit)}
        ).encode()


_FLEET_METHODS = ("GetFleet", "GetFleetEvents")


def _fleet_handler(servicer: FleetServicer) -> grpc.GenericRpcHandler:
    passthrough = bytes  # JSON bytes in/out; no generated messages
    return grpc.method_handlers_generic_handler(
        FLEET_SERVICE_NAME,
        {
            name: grpc.unary_unary_rpc_method_handler(
                getattr(servicer, name),
                request_deserializer=passthrough,
                response_serializer=passthrough,
            )
            for name in _FLEET_METHODS
        },
    )


def fleet_stubs(channel: grpc.aio.Channel | grpc.Channel) -> dict[str, object]:
    """Client-side multicallables for the fleet RPCs (tooling/tests); send
    b"" (or JSON bytes) and json.loads the reply."""
    return {
        name: channel.unary_unary(f"/{FLEET_SERVICE_NAME}/{name}")
        for name in _FLEET_METHODS
    }


OBSERVABILITY_SERVICE_NAME = "code_interpreter.v1.ObservabilityService"


class ObservabilityServicer:
    """SLO state, the one-call debug bundle, the flight recorder's wide
    events, the live task inventory, the continuous profiler, and the
    serving engine's telemetry over gRPC — the transport mirror of
    ``GET /v1/slo`` / ``/v1/debug/bundle`` / ``/v1/events`` /
    ``/v1/debug/tasks`` / ``/v1/debug/pprof`` / ``/v1/serving`` (+
    ``/requests``), as JSON message bytes through a generic handler (same
    protoc-less trick as ``FleetService``)."""

    def __init__(
        self,
        slo=None,
        debug_bundle=None,
        recorder=None,  # observability.FlightRecorder
        loopmon=None,  # observability.LoopMonitor
        contprof=None,  # observability.ContinuousProfiler
        serving=None,  # observability.ServingMonitor
        device=None,  # observability.DeviceMonitor
        autoscale=None,  # callable -> dict (resilience.autoscale_snapshot)
        tenants=None,  # callable -> dict (tenancy.build_tenants_snapshot)
    ) -> None:
        self._slo = slo
        self._debug_bundle = debug_bundle
        self._recorder = recorder
        self._loopmon = loopmon
        self._contprof = contprof
        self._serving = serving
        self._device = device
        self._autoscale = autoscale
        self._tenants = tenants

    async def GetSlo(self, request: bytes, context) -> bytes:
        """``GET /v1/slo`` twin; an optional JSON request ``{"tenant":
        "alpha"}`` selects that tenant's SLO slice (docs/tenancy.md)."""
        if self._slo is None:
            return json.dumps(empty_slo_snapshot()).encode()
        body = await self._parse_json_request(request, context)
        tenant = body.get("tenant")
        if tenant is not None:
            return json.dumps(self._slo.tenant_snapshot(str(tenant))).encode()
        return json.dumps(self._slo.snapshot()).encode()

    async def GetTenants(self, request: bytes, context) -> bytes:
        """Per-tenant isolation + billing view — the gRPC spelling of
        ``GET /v1/tenants`` (docs/tenancy.md)."""
        if self._tenants is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no tenant registry wired into this server",
            )
        return json.dumps(self._tenants()).encode()

    async def GetAutoscale(self, request: bytes, context) -> bytes:
        """Capacity observability (docs/autoscaling.md) — the gRPC spelling
        of ``GET /v1/autoscale``: demand snapshot, forecast, current/target
        pool size, and the bounded scaling-decision log."""
        if self._autoscale is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no capacity tracker wired into this server",
            )
        return json.dumps(self._autoscale()).encode()

    async def GetDebugBundle(self, request: bytes, context) -> bytes:
        if self._debug_bundle is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no debug-bundle builder wired into this server",
            )
        return json.dumps(self._debug_bundle()).encode()

    async def GetEvents(self, request: bytes, context) -> bytes:
        """Wide events, filtered like ``GET /v1/events``: optional JSON
        request ``{"kind"|"outcome"|"session": str, "limit"|
        "min_duration_ms"|"since": number}`` (no streaming mirror — live
        tails are the SSE endpoint's job)."""
        if self._recorder is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no flight recorder wired into this server",
            )
        body = await self._parse_json_request(request, context)
        try:
            limit = int(body["limit"]) if body.get("limit") is not None else None
            if limit is not None and limit < 0:
                # the HTTP twin 400s negative limits; accepting them here
                # was the bool("0")-class coercion drift
                raise ValueError("limit must be >= 0")
            events = self._recorder.events(
                kind=body.get("kind"),
                outcome=body.get("outcome"),
                session=body.get("session"),
                tenant=body.get("tenant"),
                min_duration_ms=(
                    float(body["min_duration_ms"])
                    if body.get("min_duration_ms") is not None
                    else None
                ),
                since=(
                    float(body["since"])
                    if body.get("since") is not None
                    else None
                ),
                limit=limit,
            )
        except (TypeError, ValueError):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "limit, min_duration_ms and since must be numeric "
                "(limit >= 0)",
            )
        return json.dumps({"events": events}).encode()

    async def GetServing(self, request: bytes, context) -> bytes:
        """The serving engine's deep-observability snapshot — the gRPC
        spelling of ``GET /v1/serving``. Optional JSON request
        ``{"steps": N}`` bounds how many recent step records ride along
        (default 32)."""
        if self._serving is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no serving monitor wired into this server",
            )
        body = await self._parse_json_request(request, context)
        try:
            steps = int(body.get("steps", 32))
            if steps < 0:
                raise ValueError("steps must be >= 0")
        except (TypeError, ValueError):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "steps must be a non-negative integer",
            )
        return json.dumps(self._serving.snapshot(steps=steps)).encode()

    async def GetServingRequests(self, request: bytes, context) -> bytes:
        """Per-request serving lifecycle records, filtered like
        ``GET /v1/serving/requests``: optional JSON request with
        ``outcome``/``finish``/``adapter``/``active``/``min_duration_ms``/
        ``limit``."""
        if self._serving is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no serving monitor wired into this server",
            )
        body = await self._parse_json_request(request, context)
        active = body.get("active")
        if active is not None and not isinstance(active, bool):
            # accept the HTTP edge's ?active=1/0 string forms with the
            # SAME truthiness (bool("0") would invert them)
            active = str(active).lower() in ("1", "true", "yes", "on")
        try:
            limit = int(body["limit"]) if body.get("limit") is not None else None
            if limit is not None and limit < 0:
                raise ValueError("limit must be >= 0")
            records = self._serving.requests(
                outcome=body.get("outcome"),
                finish=body.get("finish"),
                adapter=(
                    int(body["adapter"])
                    if body.get("adapter") is not None
                    else None
                ),
                active=active,
                min_duration_ms=(
                    float(body["min_duration_ms"])
                    if body.get("min_duration_ms") is not None
                    else None
                ),
                limit=limit,
            )
        except (TypeError, ValueError):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "limit, adapter and min_duration_ms must be numeric "
                "(limit >= 0)",
            )
        return json.dumps({"requests": records}).encode()

    async def GetAccelerator(self, request: bytes, context) -> bytes:
        """The accelerator observability snapshot — the gRPC spelling of
        ``GET /v1/accelerator`` (docs/observability.md "Accelerator
        observability"): compile/retrace totals, device-memory sample,
        per-mesh-shape step timing. Optional JSON request ``{"recent": N}``
        bounds the compile-record tail (default 16)."""
        if self._device is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no device monitor wired into this server",
            )
        body = await self._parse_json_request(request, context)
        try:
            recent = int(body.get("recent", 16))
            if recent < 0:
                raise ValueError("recent must be >= 0")
        except (TypeError, ValueError):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "recent must be a non-negative integer",
            )
        return json.dumps(self._device.snapshot(recent=recent)).encode()

    async def _parse_json_request(self, request: bytes, context) -> dict:
        """Empty request bytes mean defaults; anything else must be a JSON
        object (the convention GetEvents established)."""
        if not request:
            return {}
        try:
            body = json.loads(request.decode())
            if not isinstance(body, dict):
                raise ValueError("not an object")
        except (ValueError, UnicodeDecodeError):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                'request must be a JSON object like {"limit": 50}',
            )
        return body

    async def GetTasks(self, request: bytes, context) -> bytes:
        body = task_inventory()
        body["threads"] = thread_inventory()
        if self._loopmon is not None:
            body["monitor"] = self._loopmon.snapshot()
        return json.dumps(body).encode()

    async def GetPprof(self, request: bytes, context) -> bytes:
        if self._contprof is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "no continuous profiler wired into this server",
            )
        return json.dumps(
            {
                **self._contprof.snapshot(),
                "collapsed": self._contprof.collapsed(),
            }
        ).encode()


_OBSERVABILITY_METHODS = (
    "GetSlo",
    "GetAutoscale",
    "GetDebugBundle",
    "GetEvents",
    "GetTasks",
    "GetPprof",
    "GetServing",
    "GetServingRequests",
    "GetAccelerator",
    "GetTenants",
)


def _observability_handler(servicer: ObservabilityServicer) -> grpc.GenericRpcHandler:
    passthrough = bytes
    return grpc.method_handlers_generic_handler(
        OBSERVABILITY_SERVICE_NAME,
        {
            name: grpc.unary_unary_rpc_method_handler(
                getattr(servicer, name),
                request_deserializer=passthrough,
                response_serializer=passthrough,
            )
            for name in _OBSERVABILITY_METHODS
        },
    )


def observability_stubs(
    channel: grpc.aio.Channel | grpc.Channel,
) -> dict[str, object]:
    """Client-side multicallables for the SLO/debug-bundle RPCs; send b""
    and json.loads the reply."""
    return {
        name: channel.unary_unary(f"/{OBSERVABILITY_SERVICE_NAME}/{name}")
        for name in _OBSERVABILITY_METHODS
    }


HEALTH_SERVICE_NAME = "grpc.health.v1.Health"


class HealthServicer:
    """The standard gRPC health protocol (proto/health.proto) — the reference
    left this as a TODO (reference grpc_server.py:71). The empty service name
    tracks overall server health; ``set_status`` flips per-service status and
    wakes any Watch streams."""

    def __init__(self) -> None:
        self._statuses: dict[str, int] = {
            "": health_pb2.HealthCheckResponse.SERVING,
            SERVICE_NAME: health_pb2.HealthCheckResponse.SERVING,
        }
        self._changed: "asyncio.Event" = asyncio.Event()

    def set_status(self, service: str, status: int) -> None:
        self._statuses[service] = status
        self._changed.set()
        self._changed = asyncio.Event()

    def _status_of(self, service: str) -> int | None:
        return self._statuses.get(service)

    async def Check(
        self, request: health_pb2.HealthCheckRequest, context: grpc.aio.ServicerContext
    ) -> health_pb2.HealthCheckResponse:
        status = self._status_of(request.service)
        if status is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "unknown service")
        return health_pb2.HealthCheckResponse(status=status)

    async def Watch(
        self, request: health_pb2.HealthCheckRequest, context: grpc.aio.ServicerContext
    ):
        last: int | None = object()  # type: ignore[assignment] # force first send
        while True:
            # capture the event BEFORE reading the status: a set_status racing
            # with the yield below then fires this (already-captured) event and
            # the next loop iteration re-reads, so no transition is lost
            event = self._changed
            status = self._status_of(request.service)
            if status is None:
                status = health_pb2.HealthCheckResponse.SERVICE_UNKNOWN
            if status != last:
                yield health_pb2.HealthCheckResponse(status=status)
                last = status
            await event.wait()


REFLECTION_SERVICE_NAME = "grpc.reflection.v1alpha.ServerReflection"


class ReflectionServicer:
    """The standard gRPC server-reflection protocol, hand-implemented over the
    default descriptor pool (the checked-in ``*_pb2`` modules register their
    FileDescriptorProtos there at import). Equivalent surface to the
    reference's ``grpc_reflection.enable_server_reflection`` (reference
    grpc_server.py:67-69) — that package isn't available in this environment.
    grpcurl's ``list``/``describe`` drive ``list_services`` +
    ``file_containing_symbol``; clients get the transitive descriptor closure
    per file so they can build a local pool."""

    def __init__(self, service_names: tuple[str, ...]) -> None:
        self._service_names = service_names
        self._pool = descriptor_pool.Default()

    def _file_closure_bytes(self, file_descriptor) -> list[bytes]:
        """Serialized FileDescriptorProto for the file + transitive imports."""
        out: list[bytes] = []
        seen: set[str] = set()
        stack = [file_descriptor]
        while stack:
            fd = stack.pop()
            if fd.name in seen:
                continue
            seen.add(fd.name)
            proto = descriptor_pb2.FileDescriptorProto()
            fd.CopyToProto(proto)
            out.append(proto.SerializeToString())
            stack.extend(fd.dependencies)
        return out

    def _handle(
        self, request: reflection_pb2.ServerReflectionRequest
    ) -> reflection_pb2.ServerReflectionResponse:
        response = reflection_pb2.ServerReflectionResponse(
            valid_host=request.host, original_request=request
        )
        kind = request.WhichOneof("message_request")
        try:
            if kind == "list_services":
                response.list_services_response.service.extend(
                    reflection_pb2.ServiceResponse(name=name)
                    for name in self._service_names
                )
            elif kind == "file_by_filename":
                fd = self._pool.FindFileByName(request.file_by_filename)
                response.file_descriptor_response.file_descriptor_proto.extend(
                    self._file_closure_bytes(fd)
                )
            elif kind == "file_containing_symbol":
                fd = self._pool.FindFileContainingSymbol(
                    request.file_containing_symbol
                )
                response.file_descriptor_response.file_descriptor_proto.extend(
                    self._file_closure_bytes(fd)
                )
            elif kind == "all_extension_numbers_of_type":
                # proto3 services here declare no extensions; confirm the type
                # exists, then report an empty number list.
                self._pool.FindMessageTypeByName(
                    request.all_extension_numbers_of_type
                )
                response.all_extension_numbers_response.base_type_name = (
                    request.all_extension_numbers_of_type
                )
            elif kind == "file_containing_extension":
                response.error_response.error_code = (
                    grpc.StatusCode.NOT_FOUND.value[0]
                )
                response.error_response.error_message = "extensions not supported"
            else:
                response.error_response.error_code = (
                    grpc.StatusCode.INVALID_ARGUMENT.value[0]
                )
                response.error_response.error_message = "empty message_request"
        except KeyError:
            response.error_response.error_code = grpc.StatusCode.NOT_FOUND.value[0]
            response.error_response.error_message = "not found"
        return response

    async def ServerReflectionInfo(self, request_iterator, context):
        async for request in request_iterator:
            yield self._handle(request)


def _reflection_handler(servicer: ReflectionServicer) -> grpc.GenericRpcHandler:
    return grpc.method_handlers_generic_handler(
        REFLECTION_SERVICE_NAME,
        {
            "ServerReflectionInfo": grpc.stream_stream_rpc_method_handler(
                servicer.ServerReflectionInfo,
                request_deserializer=(
                    reflection_pb2.ServerReflectionRequest.FromString
                ),
                response_serializer=(
                    reflection_pb2.ServerReflectionResponse.SerializeToString
                ),
            )
        },
    )


def reflection_stub(channel: grpc.aio.Channel):
    """Client-side ServerReflectionInfo multicallable (tests/tooling)."""
    return channel.stream_stream(
        f"/{REFLECTION_SERVICE_NAME}/ServerReflectionInfo",
        request_serializer=(
            reflection_pb2.ServerReflectionRequest.SerializeToString
        ),
        response_deserializer=(
            reflection_pb2.ServerReflectionResponse.FromString
        ),
    )


def _health_handler(servicer: HealthServicer) -> grpc.GenericRpcHandler:
    return grpc.method_handlers_generic_handler(
        HEALTH_SERVICE_NAME,
        {
            "Check": grpc.unary_unary_rpc_method_handler(
                servicer.Check,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
            "Watch": grpc.unary_stream_rpc_method_handler(
                servicer.Watch,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
        },
    )


def _generic_handler(servicer: CodeInterpreterServicer) -> grpc.GenericRpcHandler:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
        for name, (req_cls, resp_cls) in _METHODS.items()
    }
    # Server-streaming execute rides the same service as JSON message bytes
    # (new proto messages are impossible without protoc; see FleetService).
    handlers["ExecuteStream"] = grpc.unary_stream_rpc_method_handler(
        servicer.ExecuteStream,
        request_deserializer=bytes,
        response_serializer=bytes,
    )
    return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


def service_stubs(channel: grpc.aio.Channel | grpc.Channel) -> dict[str, object]:
    """Client-side multicallables for the 3 RPCs (health_check + tests)."""
    return {
        name: channel.unary_unary(
            f"/{SERVICE_NAME}/{name}",
            request_serializer=req_cls.SerializeToString,
            response_deserializer=resp_cls.FromString,
        )
        for name, (req_cls, resp_cls) in _METHODS.items()
    }


def health_stub(channel: grpc.aio.Channel | grpc.Channel):
    """Client-side Check multicallable for the standard health protocol."""
    return channel.unary_unary(
        f"/{HEALTH_SERVICE_NAME}/Check",
        request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
        response_deserializer=health_pb2.HealthCheckResponse.FromString,
    )


class GrpcServer:
    def __init__(
        self,
        code_executor: CodeExecutor,
        custom_tool_executor: CustomToolExecutor,
        tls_cert: bytes | None = None,
        tls_cert_key: bytes | None = None,
        tls_ca_cert: bytes | None = None,
        admission: AdmissionController | None = None,
        request_deadline_s: float | None = None,
        metrics: Registry | None = None,
        tracer: Tracer | None = None,
        fleet: FleetJournal | None = None,
        drain=None,  # resilience.DrainController
        slo=None,  # observability.SloEngine shared with the HTTP edge
        debug_bundle=None,  # callable -> dict (ApplicationContext builder)
        analyzer=None,  # analysis.WorkloadAnalyzer shared with the HTTP edge
        sessions=None,  # sessions.SessionManager shared with the HTTP edge
        recorder=None,  # observability.FlightRecorder shared with the HTTP edge
        loopmon=None,  # observability.LoopMonitor shared with the HTTP edge
        contprof=None,  # observability.ContinuousProfiler, likewise
        serving=None,  # observability.ServingMonitor, likewise
        device=None,  # observability.DeviceMonitor, likewise
        autoscale=None,  # callable -> dict for GetAutoscale (docs/autoscaling.md)
        tenancy=None,  # tenancy.TenantRegistry shared with the HTTP edge
    ) -> None:
        # Mirror create_http_server's standalone wiring: a tracer exists
        # always, and when no FlightRecorder was handed in (tests,
        # standalone servers) one is built here and wired as a tracer sink
        # — the composition root passes one already wired, and wiring it
        # again would double every event. Before this, a standalone gRPC
        # server had NO events API (GetEvents aborted UNIMPLEMENTED) while
        # its HTTP twin always answered.
        tracer = tracer or Tracer(metrics=metrics)
        if recorder is None:
            recorder = FlightRecorder(metrics=metrics)
            tracer.add_sink(recorder.record_trace)
        # Warm the bundle's `surface` section off-loop (see
        # create_http_server: the scan must not stall the first pull).
        from bee_code_interpreter_tpu.analysis import contractlint

        contractlint.warm_surface_cache()
        self._servicer = CodeInterpreterServicer(
            code_executor,
            custom_tool_executor,
            admission=admission,
            request_deadline_s=request_deadline_s,
            metrics=metrics,
            tracer=tracer,
            drain=drain,
            slo=slo,
            analyzer=analyzer,
            sessions=sessions,
            tenancy=tenancy,
        )
        # GetTenants closure: built here so the HTTP and gRPC documents can
        # never disagree (both call tenancy.build_tenants_snapshot).
        self._tenants_snapshot = (
            (
                lambda: build_tenants_snapshot(
                    tenancy, admission=admission, slo=slo, sessions=sessions
                )
            )
            if tenancy is not None
            else None
        )
        self._slo = slo
        self._debug_bundle = debug_bundle
        self._recorder = recorder
        self._loopmon = loopmon
        self._contprof = contprof
        self._serving = serving
        self._device = device
        self._autoscale = autoscale
        # Mirror the HTTP edge: use the executor backend's own journal when
        # one exists (find_journal is the one shared discovery rule), else
        # an (honestly empty) standalone journal. Explicit None checks: an
        # empty journal is len()==0, hence falsy.
        if fleet is None:
            fleet = find_journal(code_executor)
        self._fleet = fleet if fleet is not None else FleetJournal()
        if self._debug_bundle is None:
            # Standalone fallback, the HTTP edge's exact shape: assemble
            # the bundle from what this server was handed instead of
            # aborting UNIMPLEMENTED — the transports must answer the same
            # question the same way (docs/analysis.md "Contract lint").
            self._debug_bundle = lambda: build_debug_bundle(
                tracer=tracer,
                fleet=self._fleet,
                slo=slo,
                metrics=metrics,
                executor=code_executor,
                drain=drain,
                recorder=recorder,
                loopmon=loopmon,
                contprof=contprof,
                serving=serving,
                device=device,
                autoscale=autoscale,
                tenancy=tenancy,
            )
        self.health = HealthServicer()
        self._tls_cert = tls_cert
        self._tls_cert_key = tls_cert_key
        self._tls_ca_cert = tls_ca_cert
        self._server: grpc.aio.Server | None = None
        if drain is not None:
            # The drain's first visible effect on this transport: standard
            # health probers see NOT_SERVING and stop routing traffic here.
            drain.on_drain(self.enter_drain)

    def enter_drain(self) -> None:
        """Flip gRPC health to NOT_SERVING (probers stop routing new traffic
        here) while in-flight RPCs keep running."""
        for service in ("", SERVICE_NAME):
            self.health.set_status(
                service, health_pb2.HealthCheckResponse.NOT_SERVING
            )

    async def start(self, listen_addr: str) -> int:
        """Start serving; returns the bound port (useful with ':0')."""
        self._server = grpc.aio.server()
        reflection = ReflectionServicer(
            (
                SERVICE_NAME,
                SESSION_SERVICE_NAME,
                FLEET_SERVICE_NAME,
                OBSERVABILITY_SERVICE_NAME,
                HEALTH_SERVICE_NAME,
                REFLECTION_SERVICE_NAME,
            )
        )
        self._server.add_generic_rpc_handlers(
            (
                _generic_handler(self._servicer),
                _session_handler(SessionServicer(self._servicer)),
                _fleet_handler(FleetServicer(self._fleet)),
                _observability_handler(
                    ObservabilityServicer(
                        slo=self._slo,
                        debug_bundle=self._debug_bundle,
                        recorder=self._recorder,
                        loopmon=self._loopmon,
                        contprof=self._contprof,
                        serving=self._serving,
                        device=self._device,
                        autoscale=self._autoscale,
                        tenants=self._tenants_snapshot,
                    )
                ),
                _health_handler(self.health),
                _reflection_handler(reflection),
            )
        )
        if self._tls_cert and self._tls_cert_key:
            # mTLS when a CA is provided (reference application_context.py:102-110).
            creds = grpc.ssl_server_credentials(
                [(self._tls_cert_key, self._tls_cert)],
                root_certificates=self._tls_ca_cert,
                require_client_auth=self._tls_ca_cert is not None,
            )
            port = self._server.add_secure_port(listen_addr, creds)
        else:
            port = self._server.add_insecure_port(listen_addr)
        await self._server.start()
        return port

    async def stop(self, grace: float = 5.0) -> None:
        if self._server is not None:
            # Flip health to NOT_SERVING before the stop so probers stop
            # routing new traffic here while in-flight RPCs finish.
            self.enter_drain()
            await self._server.stop(grace)

    async def wait_for_termination(self) -> None:
        if self._server is not None:
            await self._server.wait_for_termination()
