"""HTTP API server (aiohttp).

Same route surface and status-code contract as the reference's FastAPI app
(http_server.py:77-162): ``POST /v1/execute`` (500 on executor failure),
``POST /v1/parse-custom-tool`` (400 + ``{error_messages}`` on parse error),
``POST /v1/execute-custom-tool`` (400 + ``{stderr}`` on tool failure), plus
``GET /healthz``. FastAPI/uvicorn are not available in this environment;
aiohttp is the asyncio-native equivalent and shares the event loop with the
gRPC server exactly as the reference's uvicorn does (reference __main__.py:24-34).

Request validation errors (pydantic) return 422 like FastAPI would.

Resilience contract (docs/resilience.md): each sandbox-bound request gets a
``Deadline`` (``APP_REQUEST_DEADLINE_S``) propagated to the executor — a
blown deadline is 504. When an ``AdmissionController`` is wired in, requests
past the in-flight + queue bounds are shed as 429 with a ``Retry-After``
header instead of queueing unboundedly.

Observability contract (docs/observability.md): every ``/v1`` POST roots a
trace next to its request id (continuing an inbound ``traceparent`` when the
caller sent one); finished traces are retained in a bounded store and served
from ``GET /v1/traces`` (with ``?limit=``/``?min_duration_ms=`` filtering) +
``GET /v1/traces/{trace_id}``; ``/v1/execute`` responses carry the
``trace_id``, a per-stage ``timings_ms`` breakdown, and a per-execution
``usage`` resource-accounting block. Fleet state (the sandbox pool's
lifecycle journal) is served at ``GET /v1/fleet`` + ``GET /v1/fleet/events``,
``GET /healthz?verbose=1`` adds pool/breaker/fleet deep health (plus SLO
state when objectives are declared), and ``POST /v1/profile`` captures an
on-demand ``jax.profiler`` trace of a sandbox execution or of N
serving-engine steps. ``GET /v1/slo`` reports error-budget burn rates,
``GET /v1/debug/bundle`` is the one-call incident snapshot, and
``GET /metrics`` serves OpenMetrics-with-exemplars when the scraper's
``Accept`` header asks for it. ``GET /v1/events`` serves the flight
recorder's wide-event journal (filterable; ``?follow=1`` is a live SSE
tail), ``GET /v1/debug/tasks`` the live asyncio task inventory + loop-lag
state, and ``GET /v1/debug/pprof`` the continuous profiler's latest
collapsed-stack window. ``GET /v1/serving`` serves the serving engine's
step/KV-cache telemetry and ``GET /v1/serving/requests`` its per-request
lifecycle records (docs/observability.md "Serving observability").

Edge static analysis (docs/analysis.md): when a ``WorkloadAnalyzer`` is
wired in, every submission is parsed ONCE before any sandbox is touched —
syntax errors return a normal ``ExecuteResponse`` (exit_code=1, stderr in
the in-sandbox traceback shape) with ZERO sandbox checkouts, policy
``deny`` findings reject as 422 (a client fault, SLI-good), ``warn``
findings annotate the response, and the same pass pre-resolves deps for
the sandbox to skip its own scan.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import textwrap
import time
from contextlib import nullcontext

import pydantic
from aiohttp import web

from bee_code_interpreter_tpu.analysis import stash_predicted_deps
from bee_code_interpreter_tpu.api import models
from bee_code_interpreter_tpu.observability import (
    PROFILE_DIR_ENV,
    REQUEST_ID_HEADER,
    FleetJournal,
    FlightRecorder,
    ProfilerUnavailable,
    Tracer,
    build_debug_bundle,
    current_trace,
    empty_slo_snapshot,
    executor_health,
    find_journal,
    inject_profile_env,
    parse_traceparent,
    profile_artifacts,
    record_sli,
    record_usage_at_edge,
    register_stream_metrics,
    register_usage_metrics,
    task_inventory,
    thread_inventory,
    unwrap_executor,
)
from bee_code_interpreter_tpu.resilience import (
    AdmissionController,
    AdmissionRejected,
    BreakerOpenError,
    Deadline,
    DeadlineExceeded,
    SandboxTransientError,
)
from bee_code_interpreter_tpu.sessions import (
    CheckpointNotFound,
    SessionLimitExceeded,
    SessionNotFound,
    streamed_events,
)
from bee_code_interpreter_tpu.services.code_executor import CodeExecutor
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecuteError,
    CustomToolExecutor,
    CustomToolParseError,
)
from bee_code_interpreter_tpu.tenancy import (
    TENANT_HEADER,
    bearer_token,
    build_tenants_snapshot,
    current_tenant_context,
    tenant_scope,
)
from bee_code_interpreter_tpu.utils.metrics import (
    OPENMETRICS_CONTENT_TYPE,
    PROMETHEUS_CONTENT_TYPE,
    Registry,
    accepts_openmetrics,
)
from bee_code_interpreter_tpu.utils.request_id import new_request_id

logger = logging.getLogger(__name__)


def _retry_after_header(e: AdmissionRejected | BreakerOpenError) -> dict[str, str]:
    return {"Retry-After": str(max(1, math.ceil(e.retry_after_s)))}


def create_http_server(
    code_executor: CodeExecutor,
    custom_tool_executor: CustomToolExecutor,
    metrics: Registry | None = None,
    admission: AdmissionController | None = None,
    request_deadline_s: float | None = None,
    tracer: Tracer | None = None,
    fleet: FleetJournal | None = None,
    profiler=None,  # observability.ServingProfiler for POST /v1/profile
    drain=None,  # resilience.DrainController for graceful shutdown
    supervisor=None,  # resilience.PoolSupervisor, surfaced on /v1/fleet
    slo=None,  # observability.SloEngine for GET /v1/slo + SLI recording
    debug_bundle=None,  # callable -> dict (ApplicationContext.build_debug_bundle)
    analyzer=None,  # analysis.WorkloadAnalyzer for the pre-flight code gate
    sessions=None,  # sessions.SessionManager for the /v1/sessions lease API
    recorder=None,  # observability.FlightRecorder for GET /v1/events
    loopmon=None,  # observability.LoopMonitor for GET /v1/debug/tasks
    contprof=None,  # observability.ContinuousProfiler for GET /v1/debug/pprof
    serving=None,  # observability.ServingMonitor for GET /v1/serving
    device=None,  # observability.DeviceMonitor for GET /v1/accelerator
    device_profiler=None,  # observability.DeviceProfiler for target=device
    autoscale=None,  # callable -> dict for GET /v1/autoscale (docs/autoscaling.md)
    tenancy=None,  # tenancy.TenantRegistry: identity + GET /v1/tenants
) -> web.Application:
    app = web.Application(client_max_size=1 << 30)
    metrics = metrics or Registry()
    tracer = tracer or Tracer(metrics=metrics)
    # Warm the debug bundle's `surface` section off-loop at build time:
    # the contract-lint scan is hundreds of milliseconds of synchronous
    # AST work that must not run on the event loop during the first
    # (usually mid-incident) bundle pull.
    from bee_code_interpreter_tpu.analysis import contractlint

    contractlint.warm_surface_cache()
    if recorder is None:
        # Standalone servers (tests) get their own recorder; the
        # composition root passes one already wired as a tracer sink —
        # wiring it again here would double every event.
        recorder = FlightRecorder(metrics=metrics)
        tracer.add_sink(recorder.record_trace)
    # The executor backend's own journal when it has one (pool executors
    # attach it at construction); an empty journal otherwise so /v1/fleet is
    # always mounted and answers honestly. Explicit None checks: an empty
    # journal is len()==0 and must not be replaced for being falsy.
    if fleet is None:
        fleet = find_journal(code_executor)
    if fleet is None:
        fleet = FleetJournal()
    requests_total = metrics.counter(
        "bci_http_requests_total", "HTTP requests by route and status"
    )
    request_seconds = metrics.histogram(
        "bci_http_request_seconds", "HTTP request latency by route"
    )
    deadline_exceeded_total = metrics.counter(
        "bci_deadline_exceeded_total",
        "Requests that ran out of their edge deadline",
    )
    execution_cpu_seconds, execution_peak_rss = register_usage_metrics(metrics)
    stream_ttfb_seconds, stream_chunks_total = register_stream_metrics(metrics)

    def _annotate_outcome(outcome: str, sli: bool | None) -> None:
        """Stamp the resilience ladder's verdict on the request's root span
        so the flight recorder's wide event (a tracer sink — it fires when
        the trace closes) carries the outcome and SLO classification."""
        trace = current_trace()
        if trace is not None:
            trace.root.attributes["outcome"] = outcome
            if sli is not None:
                trace.root.attributes["sli"] = "good" if sli else "bad"

    async def with_resilience(run, allow_draining: bool = False):
        """Run a sandbox-bound handler body under the edge deadline and the
        admission gate, mapping the shared shed/deadline response contract
        (docs/resilience.md) — the one place it is spelled for HTTP.
        ``run(deadline)`` returns the success response. The admission gate
        traces its own acquire as the ``admission`` stage span.

        Every request that gets past the drain check is also an SLI sample
        (docs/observability.md "SLOs"): server-side failures (5xx) burn
        availability budget, client faults (4xx) count good, and deliberate
        load management (429 shed, drain 503, client cancel) is excluded —
        ``outcome`` None means "not a sample"."""
        # Drain check BEFORE admission: a draining replica must not queue
        # new work it has promised to finish — 503 + Retry-After tells the
        # client (or the balancer) to go elsewhere, while requests already
        # in flight (tracked below) run to completion. Evacuation ops
        # (``allow_draining``: session checkpoint — the lease-handoff path,
        # docs/fleet.md) are exempt: moving existing state OUT is part of
        # finishing up, not new work.
        if drain is not None and drain.draining and not allow_draining:
            _annotate_outcome("drained", None)
            return web.json_response(
                {"detail": "Service draining; retry against another replica"},
                status=503,
                headers={"Retry-After": str(max(1, math.ceil(drain.retry_after_s)))},
            )
        deadline = Deadline.after(request_deadline_s) if request_deadline_s else None
        slo_start = time.monotonic()
        outcome: bool | None = None
        label = "cancelled"  # only a CancelledError leaves it unassigned
        # The tenant the middleware resolved (docs/tenancy.md): its quotas
        # apply at the admission gate, its SLO slice gets the sample, its
        # usage meter gets the outcome.
        tctx = current_tenant_context()
        try:
            try:
                # track() covers the admission wait too: a request already
                # granted (or queued for) a slot when the drain begins was
                # admitted past the drain check and WILL execute — teardown
                # must wait for it, not just for bodies already running.
                with drain.track() if drain is not None else nullcontext():
                    async with (
                        admission.admit(deadline, tenant=tctx)
                        if admission is not None
                        else nullcontext()
                    ):
                        response = await run(deadline)
                # bci_sli_bad: an SSE run whose terminal event reported a
                # server-side failure after the 200 status was already spent
                # (_run_sse) — the sample must burn budget like the buffered
                # path's 500 would.
                outcome = response.status < 500 and not getattr(
                    response, "bci_sli_bad", False
                )
                label = (
                    "error"
                    if not outcome
                    else ("ok" if response.status < 400 else "client_error")
                )
                return response
            except AdmissionRejected as e:
                label = "shed"
                logger.warning("Request shed: %s", e)
                # The reason in the body makes the verdict legible per
                # tenant: "tenant_quota" is YOUR quota, "queue_full" is
                # global overload (docs/tenancy.md).
                return web.json_response(
                    {
                        "detail": f"Service overloaded ({e.reason}); retry later",
                        "reason": e.reason,
                    },
                    status=429,
                    headers=_retry_after_header(e),
                )
            except DeadlineExceeded as e:
                outcome = False
                label = "deadline"
                deadline_exceeded_total.inc(transport="http")
                logger.warning("Request deadline exceeded: %s", e)
                return web.json_response({"detail": "Deadline exceeded"}, status=504)
            except BreakerOpenError as e:
                # Open breaker and no fallback configured: this is retryable
                # overload (the breaker knows when it will probe again), not a
                # server bug — 503 + Retry-After, never a generic 500.
                outcome = False
                label = "breaker_open"
                logger.warning("Request rejected by open breaker: %s", e)
                return web.json_response(
                    {"detail": "Backend temporarily unavailable; retry later"},
                    status=503,
                    headers=_retry_after_header(e),
                )
            except asyncio.CancelledError:
                raise  # client went away: not an SLI sample
            except web.HTTPException as e:
                outcome = e.status < 500  # 422 body-validation etc.
                label = "client_error" if outcome else "error"
                raise
            except BaseException:
                outcome = False  # unhandled → aiohttp's 500
                label = "error"
                raise
        finally:
            if slo is not None and outcome is not None:
                record_sli(
                    slo,
                    ok=outcome,
                    duration_s=time.monotonic() - slo_start,
                    tenant=tctx.label if tctx is not None else None,
                )
            if tctx is not None:
                # Every resolved request lands in the tenant's usage meter
                # with its outcome — sheds included, so /v1/tenants and the
                # shed counters agree by construction.
                tctx.record_request(label)
            _annotate_outcome(label, outcome)

    @web.middleware
    async def request_id_middleware(request: web.Request, handler):
        rid = new_request_id()
        # label by the *matched* route template, never the raw path: raw paths
        # are attacker-controlled (unbounded label cardinality + exposition
        # injection via percent-decoded quotes)
        # match_info is a dict subclass (empty — falsy — for static routes), so
        # test identity, not truthiness
        match_info = request.match_info
        resource = match_info.route.resource if match_info is not None else None
        route = resource.canonical if resource is not None else "unmatched"
        # Trace the sandbox-bound POSTs only: GET /metrics, /healthz and the
        # trace-inspection API itself would drown the store in self-traffic.
        traced = request.method == "POST" and route.startswith("/v1/")
        inbound = (
            parse_traceparent(request.headers.get("traceparent"))
            if traced
            else None
        )
        trace_ctx = (
            tracer.trace(
                route,
                trace_id=inbound[0] if inbound else None,
                parent_span_id=inbound[1] if inbound else None,
                request_id=rid,
            )
            if traced
            else nullcontext()
        )
        # Tenant identity resolves HERE — once, for every route — into the
        # ambient context every downstream layer reads (docs/tenancy.md).
        # tenant_scope(None) when no registry is wired still clears any
        # context a previous request on this keep-alive connection left.
        tctx = None
        if tenancy is not None:
            tctx = tenancy.resolve(
                request.headers.get(TENANT_HEADER),
                bearer_token(request.headers.get("Authorization")),
            )
            if admission is not None and tctx.retry_budget is None:
                tctx.retry_budget = admission.tenant_retry_budget(tctx)
        with tenant_scope(tctx):
            with trace_ctx:
                if traced and tctx is not None:
                    trace = current_trace()
                    if trace is not None:
                        # The root-span attribute the wide event lifts into
                        # its first-class `tenant` field.
                        trace.root.attributes["tenant"] = tctx.label
                with request_seconds.time(route=route):
                    try:
                        response = await handler(request)
                    except web.HTTPException as e:
                        requests_total.inc(route=route, status=str(e.status))
                        e.headers.setdefault(REQUEST_ID_HEADER, rid)
                        raise
                    except Exception:
                        requests_total.inc(route=route, status="500")
                        raise
        requests_total.inc(route=route, status=str(response.status))
        response.headers.setdefault(REQUEST_ID_HEADER, rid)
        return response

    app.middlewares.append(request_id_middleware)

    async def parse_body(request: web.Request, model: type[pydantic.BaseModel]):
        try:
            # pydantic v2 handles malformed JSON itself (json_invalid → 422).
            return model.model_validate_json(await request.read())
        except pydantic.ValidationError as e:
            raise web.HTTPUnprocessableEntity(
                text=e.json(), content_type="application/json"
            ) from e

    def _truthy_query(request: web.Request, name: str) -> bool:
        return request.query.get(name, "").lower() in ("1", "true", "yes", "on")

    def _stream_backend():
        """The pool/local backend implementing ``execute_stream`` behind the
        resilience fronts. Streaming deliberately bypasses retry/replay/
        hedging: chunks already delivered to a client cannot be
        un-delivered, so a mid-stream failure is a terminal error event,
        never a silent re-run."""
        backend = unwrap_executor(code_executor)
        return backend if hasattr(backend, "execute_stream") else None

    async def _sse_prepare(request: web.Request) -> web.StreamResponse:
        response = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-store",
                "X-Accel-Buffering": "no",  # proxies must not re-buffer SSE
            }
        )
        response.enable_chunked_encoding()
        await response.prepare(request)
        return response

    async def _sse_event(response, event: str, data: dict) -> None:
        await response.write(
            f"event: {event}\ndata: {json.dumps(data)}\n\n".encode()
        )

    async def _run_sse(request, verdict, execute_call, envelope):
        """Drive one streaming execution as SSE (docs/sessions.md
        "Streaming wire format"): ``stdout``/``stderr`` events per chunk,
        exactly one terminal ``result`` (the usual envelope, trace_id
        included) or ``error`` event. Once the stream is prepared the HTTP
        status is spent, so failures are in-band terminal events."""
        start = time.monotonic()
        chunks = 0
        first_chunk_s: float | None = None

        def _annotate_stream() -> None:
            """Stream context onto the root span (→ the wide event) and the
            production streaming metrics the bench numbers graduated into."""
            stream_chunks_total.inc(chunks, transport="http")
            trace = current_trace()
            if trace is not None:
                trace.root.attributes["stream.chunks"] = str(chunks)
                if first_chunk_s is not None:
                    trace.root.attributes["stream.ttfb_ms"] = (
                        f"{first_chunk_s * 1000:.3f}"
                    )

        response = await _sse_prepare(request)
        # finally, not a tail call: a client that vanishes mid-stream (write
        # raises / handler cancelled) must still count its delivered chunks
        # and leave stream context on the wide event — abnormal streams are
        # exactly the ones an operator queries for.
        try:
            if verdict is not None and verdict.syntax_error is not None:
                # Fail-fast mirrors the buffered path: zero sandbox
                # checkouts, the terminal event IS the whole stream.
                trace = current_trace()
                await _sse_event(
                    response,
                    "result",
                    models.ExecuteResponse(
                        stdout="",
                        stderr=verdict.syntax_error,
                        exit_code=1,
                        files={},
                        trace_id=trace.trace_id if trace is not None else None,
                        timings_ms=(
                            trace.stage_ms() if trace is not None else None
                        ),
                    ).model_dump(),
                )
                await response.write_eof()
                return response
            async for item in streamed_events(execute_call):
                if item.get("event") == "error":
                    error = item["error"]
                    if isinstance(error, asyncio.CancelledError):
                        raise error  # our own unwind (client gone); don't mask it
                    logger.warning("Streaming execution failed: %r", error)
                    if isinstance(error, DeadlineExceeded):
                        detail = "Deadline exceeded"
                    elif isinstance(error, SessionNotFound):
                        detail = str(error)
                    else:
                        detail = "Execution failed"
                    if not isinstance(error, SessionNotFound):
                        # The 200 status was spent at prepare time, but a
                        # mid-stream server failure must still burn
                        # availability budget — the gRPC twin (ExecuteStream)
                        # samples the identical failure bad, and the
                        # transports must agree. SessionNotFound is the
                        # client's fault (the buffered path's 404), so it
                        # stays good.
                        response.bci_sli_bad = True
                    await _sse_event(response, "error", {"detail": detail})
                elif item.get("event") == "result":
                    await _sse_event(
                        response, "result", envelope(item["result"])
                    )
                else:
                    if chunks == 0:
                        first_chunk_s = time.monotonic() - start
                        stream_ttfb_seconds.observe(
                            first_chunk_s, transport="http"
                        )
                    chunks += 1
                    await _sse_event(
                        response, item["stream"], {"text": item["data"]}
                    )
            await response.write_eof()
            return response
        finally:
            _annotate_stream()

    async def execute(request: web.Request) -> web.Response:
        # Admission runs BEFORE the body is read: a shed request must cost a
        # queue check, not a (up to client_max_size) body read + pydantic
        # parse. The deadline covers the body read too.
        async def run(deadline):
            req = await parse_body(request, models.ExecuteRequest)
            # Clear any prediction left by a previous request: aiohttp serves
            # sequential keep-alive requests on ONE connection task, so the
            # contextvar would otherwise leak across requests.
            stash_predicted_deps(None)
            streaming = _truthy_query(request, "stream")
            verdict = (
                analyzer.analyze(req.source_code)
                if analyzer is not None
                else None
            )
            if verdict is not None:
                if verdict.syntax_error is not None and not streaming:
                    # Fail-fast: the sandbox would have died at parse with
                    # this exact stderr shape — answer it from the edge
                    # without a pool checkout (the fleet journal stays
                    # untouched; timings_ms carries only `analysis`).
                    trace = current_trace()
                    return web.json_response(
                        models.ExecuteResponse(
                            stdout="",
                            stderr=verdict.syntax_error,
                            exit_code=1,
                            files={},
                            trace_id=(
                                trace.trace_id if trace is not None else None
                            ),
                            timings_ms=(
                                trace.stage_ms() if trace is not None else None
                            ),
                        ).model_dump()
                    )
                if verdict.denials:
                    logger.warning(
                        "Request denied by policy: %s", verdict.denial_detail()
                    )
                    return web.json_response(
                        {
                            "detail": "Denied by execution policy",
                            "violations": [
                                f.to_dict() for f in verdict.denials
                            ],
                        },
                        status=422,
                    )
                # The edge already scanned: ship the prediction with the
                # data-plane call so the pod skips its own scan.
                stash_predicted_deps(verdict.predicted_deps)
            # Cost-aware admission (opt-in, docs/analysis.md "Cost
            # classes"): heavy-classified work passes the bounded heavy
            # lane; a shed here surfaces as the ordinary 429 contract.
            heavy_lane = (
                admission.heavy_lane(verdict.cost_class)
                if admission is not None and verdict is not None
                else nullcontext()
            )
            async with heavy_lane:
                if streaming:
                    backend = _stream_backend()
                    if backend is None:
                        return web.json_response(
                            {"detail": "this backend cannot stream output"},
                            status=501,
                        )

                    def envelope(result) -> dict:
                        trace = current_trace()
                        record_usage_at_edge(
                            result.usage,
                            trace,
                            execution_cpu_seconds,
                            execution_peak_rss,
                        )
                        return models.ExecuteResponse(
                            **result.model_dump(),
                            trace_id=trace.trace_id if trace is not None else None,
                            timings_ms=(
                                trace.stage_ms() if trace is not None else None
                            ),
                            analysis=(
                                verdict.annotation() if verdict is not None else None
                            ),
                        ).model_dump()

                    return await _run_sse(
                        request,
                        verdict,
                        lambda on_event: backend.execute_stream(
                            req.source_code,
                            files=req.files,
                            env=req.env,
                            timeout_s=req.timeout,
                            on_event=on_event,
                            deadline=deadline,
                        ),
                        envelope,
                    )
                logger.info("Executing code: %s", req.source_code)
                try:
                    result = await code_executor.execute(
                        source_code=req.source_code,
                        files=req.files,
                        env=req.env,
                        timeout_s=req.timeout,
                        deadline=deadline,
                    )
                except (DeadlineExceeded, BreakerOpenError):
                    raise  # handled by the shared resilience contract (504/503)
                except Exception:
                    logger.exception("Execution failed")
                    return web.json_response(
                        {"detail": "Execution failed"}, status=500
                    )
                logger.info("Execution result: exit_code=%s", result.exit_code)
                # Per-stage timing breakdown off the request's own trace: the
                # stage spans have all finished by now (the root closes with
                # the middleware), so agents/benchmarks can self-report where
                # the time went without a second round-trip to /v1/traces.
                trace = current_trace()
                # Execution-cost accounting lands at the edge: histograms +
                # usage.* attributes on the root span, mirroring the response.
                record_usage_at_edge(
                    result.usage, trace, execution_cpu_seconds, execution_peak_rss
                )
                return web.json_response(
                    models.ExecuteResponse(
                        **result.model_dump(),
                        trace_id=trace.trace_id if trace is not None else None,
                        timings_ms=trace.stage_ms() if trace is not None else None,
                        analysis=(
                            verdict.annotation() if verdict is not None else None
                        ),
                    ).model_dump()
                )

        return await with_resilience(run)

    async def profile(request: web.Request) -> web.Response:
        """On-demand jax.profiler capture (docs/observability.md): drill
        into a slow request found via /v1/traces without redeploying."""

        async def run(deadline):
            req = await parse_body(request, models.ProfileRequest)
            # Profiled executions are not analyzed; clear any prediction a
            # previous request on this connection task stashed so the pod
            # scans THIS source itself.
            stash_predicted_deps(None)
            if req.target == "serving":
                # 501 both when no profiler was wired AND when one exists
                # but its stepper has no engine attached yet (the
                # composition root wires the profiler unconditionally; the
                # engine arrives via ApplicationContext.attach_serving_engine)
                if profiler is None or not getattr(
                    profiler, "available", True
                ):
                    return web.json_response(
                        {"detail": "no serving engine attached to /v1/profile"},
                        status=501,
                    )
                try:
                    # Off-loop: a capture steps the batcher N times, which
                    # is device-bound work the event loop must not eat.
                    captured = await asyncio.to_thread(
                        profiler.capture, req.steps
                    )
                except ProfilerUnavailable as e:
                    return web.json_response({"detail": str(e)}, status=503)
                return web.json_response({"target": "serving", **captured})

            if req.target == "device":
                # Raw device-runtime capture (docs/observability.md
                # "Accelerator observability"): serving steps when an
                # engine is attached, a probe computation otherwise. 501
                # with the concrete reason when the runtime cannot trace
                # (no profiler wired, jax.profiler missing, or start_trace
                # rejected by this backend); 503 only for the transient
                # capture-already-running case.
                if device_profiler is None or not getattr(
                    device_profiler, "available", True
                ):
                    return web.json_response(
                        {
                            "detail": "device profiling unavailable: no "
                            "jax.profiler on this runtime"
                        },
                        status=501,
                    )
                try:
                    captured = await asyncio.to_thread(
                        device_profiler.capture, req.steps
                    )
                except ProfilerUnavailable as e:
                    busy = device_profiler.capturing
                    return web.json_response(
                        {"detail": str(e)}, status=503 if busy else 501
                    )
                return web.json_response({"target": "device", **captured})

            if not req.source_code:
                return web.json_response(
                    {"detail": "source_code is required for target=sandbox"},
                    status=422,
                )
            env = inject_profile_env(req.env)
            profile_dir = env[PROFILE_DIR_ENV]
            try:
                result = await code_executor.execute(
                    source_code=req.source_code,
                    files=req.files,
                    env=env,
                    timeout_s=req.timeout,
                    deadline=deadline,
                )
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (504/503)
            except Exception:
                logger.exception("Profiled execution failed")
                return web.json_response({"detail": "Execution failed"}, status=500)
            trace = current_trace()
            record_usage_at_edge(
                result.usage, trace, execution_cpu_seconds, execution_peak_rss
            )
            return web.json_response(
                {
                    "target": "sandbox",
                    **models.ExecuteResponse(
                        **result.model_dump(),
                        trace_id=trace.trace_id if trace is not None else None,
                        timings_ms=(
                            trace.stage_ms() if trace is not None else None
                        ),
                    ).model_dump(),
                    "profile_dir": profile_dir,
                    "profile_files": profile_artifacts(
                        result.files, profile_dir
                    ),
                }
            )

        return await with_resilience(run)

    async def parse_custom_tool(request: web.Request) -> web.Response:
        req = await parse_body(request, models.ParseCustomToolRequest)
        try:
            tool = custom_tool_executor.parse(req.tool_source_code)
        except CustomToolParseError as e:
            return web.json_response({"error_messages": e.error_messages}, status=400)
        return web.json_response(
            models.ParseCustomToolResponse(
                tool_name=tool.name,
                tool_input_schema_json=json.dumps(tool.input_schema),
                tool_description=tool.description,
            ).model_dump()
        )

    async def execute_custom_tool(request: web.Request) -> web.Response:
        async def run(deadline):
            req = await parse_body(request, models.ExecuteCustomToolRequest)
            stash_predicted_deps(None)  # see execute(): per-request reset
            if analyzer is not None:
                # Tool sources get the policy half only, analyzed DEDENTED —
                # the same preprocessing the parser applies, so a uniformly
                # indented tool can't slip past the policy as a "syntax
                # error". A real syntax error keeps the parser's 400 +
                # error_messages contract (fail-fast skipped), and no dep
                # prediction is stashed: the sandbox runs the generated
                # wrapper (whose own imports, e.g. pydantic, the tool source
                # doesn't mention), so the in-pod scan must still run.
                verdict = analyzer.analyze(
                    textwrap.dedent(req.tool_source_code)
                )
                if verdict.syntax_error is None and verdict.denials:
                    logger.warning(
                        "Tool denied by policy: %s", verdict.denial_detail()
                    )
                    return web.json_response(
                        {
                            "detail": "Denied by execution policy",
                            "violations": [
                                f.to_dict() for f in verdict.denials
                            ],
                        },
                        status=422,
                    )
            try:
                output = await custom_tool_executor.execute(
                    tool_source_code=req.tool_source_code,
                    tool_input_json=req.tool_input_json,
                    env=req.env,
                    deadline=deadline,
                )
            except CustomToolParseError as e:
                return web.json_response(
                    {"error_messages": e.error_messages}, status=400
                )
            except CustomToolExecuteError as e:
                return web.json_response({"stderr": e.stderr}, status=400)
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (504/503)
            except Exception:
                # Without this arm a raw sandbox failure escaped as
                # aiohttp's default text/plain 500 (no detail, no JSON)
                # while /v1/execute answered a JSON 500 — and the gRPC
                # twin aborts INTERNAL "execution failed".
                logger.exception("Custom tool execution failed")
                return web.json_response(
                    {"detail": "Execution failed"}, status=500
                )
            return web.json_response(
                models.ExecuteCustomToolResponse(
                    tool_output_json=json.dumps(output)
                ).model_dump()
            )

        return await with_resilience(run)

    # ------------------------------------------------------------- sessions

    def _sessions_unwired() -> web.Response:
        return web.json_response(
            {"detail": "no session manager wired into this server"}, status=501
        )

    def _session_trace_attr(session_id: str) -> None:
        """Thread the session id through tracing: a ``session`` attribute on
        the request's root span, visible in /v1/traces and the OTLP export."""
        trace = current_trace()
        if trace is not None:
            trace.root.attributes["session"] = session_id

    def _session_execute_envelope(
        session, outcome, verdict=None
    ) -> dict:
        trace = current_trace()
        record_usage_at_edge(
            outcome.usage, trace, execution_cpu_seconds, execution_peak_rss
        )
        return models.SessionExecuteResponse(
            stdout=outcome.stdout,
            stderr=outcome.stderr,
            exit_code=outcome.exit_code,
            changed_paths=outcome.changed_paths,
            session_id=session.session_id,
            execution=session.executions,
            expires_at=session.expires_unix,
            trace_id=trace.trace_id if trace is not None else None,
            timings_ms=trace.stage_ms() if trace is not None else None,
            usage=outcome.usage,
            analysis=verdict.annotation() if verdict is not None else None,
        ).model_dump()

    async def session_create(request: web.Request) -> web.Response:
        if sessions is None:
            return _sessions_unwired()

        async def run(deadline):
            req = await parse_body(request, models.SessionCreateRequest)
            stash_predicted_deps(None)
            try:
                session = await sessions.create(
                    files=req.files,
                    ttl_s=req.ttl_s,
                    idle_s=req.idle_s,
                    deadline=deadline,
                )
            except SessionLimitExceeded as e:
                return web.json_response(
                    {"detail": str(e)},
                    status=429,
                    headers={
                        "Retry-After": str(max(1, math.ceil(e.retry_after_s)))
                    },
                )
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (504/503)
            except Exception:
                logger.exception("Session create failed")
                return web.json_response(
                    {"detail": "Session create failed"}, status=500
                )
            _session_trace_attr(session.session_id)
            return web.json_response(
                models.SessionCreateResponse(
                    session_id=session.session_id,
                    expires_at=session.expires_unix,
                    ttl_s=session.ttl_s,
                    idle_timeout_s=session.idle_s,
                    sandbox=session.lease.name,
                ).model_dump()
            )

        return await with_resilience(run)

    async def session_execute(request: web.Request) -> web.Response:
        if sessions is None:
            return _sessions_unwired()
        session_id = request.match_info["session_id"]

        async def run(deadline):
            req = await parse_body(request, models.SessionExecuteRequest)
            stash_predicted_deps(None)
            _session_trace_attr(session_id)
            streaming = _truthy_query(request, "stream")
            # Admission/deadline/analysis/SLO apply per-execute exactly as
            # on the stateless path (docs/sessions.md): the analyzer gate
            # runs BEFORE the leased sandbox is touched.
            verdict = (
                analyzer.analyze(req.source_code)
                if analyzer is not None
                else None
            )
            try:
                session = sessions.get(session_id)
            except SessionNotFound as e:
                return web.json_response({"detail": str(e)}, status=404)
            if verdict is not None:
                if verdict.syntax_error is not None and not streaming:
                    # Fail-fast without touching the lease (it stays warm,
                    # its idle clock untouched by a doomed submission).
                    return web.json_response(
                        _session_execute_envelope(
                            session,
                            _syntax_outcome(verdict.syntax_error),
                        )
                    )
                if verdict.denials:
                    logger.warning(
                        "Session execute denied by policy: %s",
                        verdict.denial_detail(),
                    )
                    return web.json_response(
                        {
                            "detail": "Denied by execution policy",
                            "violations": [
                                f.to_dict() for f in verdict.denials
                            ],
                        },
                        status=422,
                    )
                stash_predicted_deps(verdict.predicted_deps)
            if streaming:
                return await _run_sse(
                    request,
                    verdict,
                    lambda on_event: sessions.execute(
                        session_id,
                        req.source_code,
                        files=req.files,
                        env=req.env,
                        timeout_s=req.timeout,
                        deadline=deadline,
                        on_event=on_event,
                    ),
                    lambda pair: _session_execute_envelope(
                        pair[0], pair[1], verdict
                    ),
                )
            try:
                session, outcome = await sessions.execute(
                    session_id,
                    req.source_code,
                    files=req.files,
                    env=req.env,
                    timeout_s=req.timeout,
                    deadline=deadline,
                )
            except SessionNotFound as e:
                return web.json_response({"detail": str(e)}, status=404)
            except (DeadlineExceeded, BreakerOpenError):
                raise  # shared resilience contract (504/503)
            except SandboxTransientError:
                logger.exception("Leased sandbox died mid-execute")
                return web.json_response(
                    {"detail": "Session sandbox died; lease ended"},
                    status=500,
                )
            except Exception:
                logger.exception("Session execution failed")
                return web.json_response(
                    {"detail": "Execution failed"}, status=500
                )
            return web.json_response(
                _session_execute_envelope(session, outcome, verdict)
            )

        return await with_resilience(run)

    def _syntax_outcome(stderr: str):
        from bee_code_interpreter_tpu.sessions import LeaseOutcome

        return LeaseOutcome(stdout="", stderr=stderr, exit_code=1)

    async def session_checkpoint(request: web.Request) -> web.Response:
        if sessions is None:
            return _sessions_unwired()
        session_id = request.match_info["session_id"]

        async def run(deadline):
            stash_predicted_deps(None)
            _session_trace_attr(session_id)
            try:
                session, checkpoint = await sessions.checkpoint(
                    session_id, deadline=deadline
                )
            except SessionNotFound as e:
                return web.json_response({"detail": str(e)}, status=404)
            except (DeadlineExceeded, BreakerOpenError):
                raise
            except Exception:
                logger.exception("Session checkpoint failed")
                return web.json_response(
                    {"detail": "Checkpoint failed"}, status=500
                )
            return web.json_response(
                models.SessionCheckpointResponse(
                    session_id=session.session_id,
                    checkpoint_id=checkpoint.checkpoint_id,
                    files=checkpoint.files,
                ).model_dump()
            )

        # allow_draining: a fleet router evacuating this replica's leases
        # checkpoints them THROUGH the drain window (docs/fleet.md).
        return await with_resilience(run, allow_draining=True)

    async def session_rollback(request: web.Request) -> web.Response:
        if sessions is None:
            return _sessions_unwired()
        session_id = request.match_info["session_id"]

        async def run(deadline):
            req = await parse_body(request, models.SessionRollbackRequest)
            stash_predicted_deps(None)
            _session_trace_attr(session_id)
            try:
                session, checkpoint = await sessions.rollback(
                    session_id, req.checkpoint_id, deadline=deadline
                )
            except (SessionNotFound, CheckpointNotFound) as e:
                return web.json_response({"detail": str(e)}, status=404)
            except (DeadlineExceeded, BreakerOpenError):
                raise
            except Exception:
                logger.exception("Session rollback failed")
                return web.json_response(
                    {"detail": "Rollback failed"}, status=500
                )
            return web.json_response(
                models.SessionCheckpointResponse(
                    session_id=session.session_id,
                    checkpoint_id=checkpoint.checkpoint_id,
                    files=checkpoint.files,
                ).model_dump()
            )

        return await with_resilience(run)

    async def session_delete(request: web.Request) -> web.Response:
        if sessions is None:
            return _sessions_unwired()
        session_id = request.match_info["session_id"]
        try:
            session = await sessions.release(session_id)
        except SessionNotFound as e:
            return web.json_response({"detail": str(e)}, status=404)
        return web.json_response(
            {
                "session_id": session.session_id,
                "released": True,
                "executions": session.executions,
            }
        )

    async def session_list(_request: web.Request) -> web.Response:
        if sessions is None:
            return _sessions_unwired()
        return web.json_response(sessions.snapshot())

    async def healthz(request: web.Request) -> web.Response:
        # "draining" is a distinct liveness answer (still HTTP 200: the
        # process is healthy, just finishing up) so preStop hooks and
        # health_check.py can tell a draining replica from a dead one.
        draining = drain is not None and drain.draining
        body: dict = {"status": "draining" if draining else "ok"}
        # explicit truthy values only: ?verbose=0 / =false must stay terse
        if request.query.get("verbose", "").lower() in ("1", "true", "yes", "on"):
            # Deep health: pool occupancy, breaker states, fleet aggregates
            # — the "why is it unhealthy" view a bare 200 can't carry.
            body.update(executor_health(code_executor))
            if draining:
                body["drain_inflight"] = drain.in_flight
            if supervisor is not None:
                body["supervisor"] = supervisor.snapshot()
            snapshot = fleet.snapshot()
            body["fleet"] = {
                "live": snapshot["live"],
                "by_state": snapshot["by_state"],
                "utilization": snapshot["utilization"],
                "executions_total": snapshot["executions_total"],
            }
            if slo is not None and slo.objectives:
                # Budget exhaustion is a *health* fact: health_check.py's
                # --verbose warning exit keys off fast_burn_alerting here.
                body["slo"] = slo.snapshot()
            if loopmon is not None:
                # Loop health next to pool health: a stalled loop makes
                # every other number here lie by omission.
                body["loop"] = loopmon.snapshot()
        return web.json_response(body)

    async def metrics_endpoint(request: web.Request) -> web.Response:
        # Content negotiation: OpenMetrics (exemplars + `# EOF`) when the
        # scraper asks for it (q-values honored), the classic Prometheus
        # text format (version parameter included, so scrapers pick the
        # parser) by default.
        openmetrics = accepts_openmetrics(request.headers.get("Accept", ""))
        return web.Response(
            body=metrics.expose(openmetrics=openmetrics).encode("utf-8"),
            headers={
                "Content-Type": (
                    OPENMETRICS_CONTENT_TYPE
                    if openmetrics
                    else PROMETHEUS_CONTENT_TYPE
                )
            },
        )

    async def slo_endpoint(request: web.Request) -> web.Response:
        if slo is None:
            return web.json_response(empty_slo_snapshot())
        tenant = request.query.get("tenant")
        if tenant is not None:
            # One tenant's SLO slice (docs/tenancy.md "SLO slices").
            return web.json_response(slo.tenant_snapshot(tenant))
        return web.json_response(slo.snapshot())

    async def tenants_endpoint(_request: web.Request) -> web.Response:
        """Per-tenant isolation + billing view (docs/tenancy.md): declared
        quotas, live admission state, usage metering, SLO-slice burn, and
        session counts — the blast-radius accounting surface."""
        if tenancy is None:
            return web.json_response(
                {"detail": "no tenant registry wired into this server"},
                status=501,
            )
        return web.json_response(
            build_tenants_snapshot(
                tenancy, admission=admission, slo=slo, sessions=sessions
            )
        )

    async def autoscale_endpoint(_request: web.Request) -> web.Response:
        """Capacity observability (docs/autoscaling.md): the demand
        snapshot, the forecast, the current/target pool size, and the
        bounded scaling-decision log with reasons."""
        if autoscale is None:
            return web.json_response(
                {"detail": "no capacity tracker wired into this server"},
                status=501,
            )
        return web.json_response(autoscale())

    async def debug_bundle_endpoint(_request: web.Request) -> web.Response:
        # One-call incident snapshot (docs/observability.md "Debug bundle").
        # The composition root's builder when wired; otherwise assembled
        # from what this server was handed (standalone/test apps).
        bundle = (
            debug_bundle()
            if debug_bundle is not None
            else build_debug_bundle(
                tracer=tracer,
                fleet=fleet,
                slo=slo,
                metrics=metrics,
                executor=code_executor,
                supervisor=supervisor,
                drain=drain,
                recorder=recorder,
                loopmon=loopmon,
                contprof=contprof,
                serving=serving,
                autoscale=autoscale,
                tenancy=tenancy,
            )
        )
        return web.json_response(bundle)

    async def list_traces(request: web.Request) -> web.Response:
        # ?limit=N caps the response (newest first); ?min_duration_ms=X
        # keeps only the slow outliers — the query an operator actually
        # runs, instead of dumping the whole ring every time.
        try:
            limit = (
                int(request.query["limit"])
                if "limit" in request.query
                else None
            )
            min_duration_ms = (
                float(request.query["min_duration_ms"])
                if "min_duration_ms" in request.query
                else None
            )
        except ValueError:
            return web.json_response(
                {"detail": "limit and min_duration_ms must be numeric"},
                status=400,
            )
        if limit is not None and limit < 0:
            return web.json_response(
                {"detail": "limit must be >= 0"}, status=400
            )
        traces = tracer.store.traces()
        if min_duration_ms is not None:
            traces = [
                t for t in traces if t.duration_s * 1000.0 >= min_duration_ms
            ]
        if limit is not None:
            traces = traces[:limit]
        return web.json_response({"traces": [t.summary() for t in traces]})

    async def get_trace(request: web.Request) -> web.Response:
        trace = tracer.store.get(request.match_info["trace_id"])
        if trace is None:
            return web.json_response(
                {"detail": "unknown or evicted trace"}, status=404
            )
        return web.json_response(trace.to_dict())

    async def list_events(request: web.Request) -> web.StreamResponse:
        """The flight recorder's wide-event journal (docs/observability.md
        "Flight recorder"): filterable snapshot by default, a live SSE tail
        with ``?follow=1`` (same filters; ``backlog=N`` replays the last N
        matching events first)."""
        from bee_code_interpreter_tpu.observability import event_matches

        query = request.query
        try:
            limit = int(query["limit"]) if "limit" in query else None
            backlog = int(query.get("backlog", "0"))
            min_duration_ms = (
                float(query["min_duration_ms"])
                if "min_duration_ms" in query
                else None
            )
            since = float(query["since"]) if "since" in query else None
        except ValueError:
            return web.json_response(
                {
                    "detail": "limit, backlog, min_duration_ms and since "
                    "must be numeric"
                },
                status=400,
            )
        if (limit is not None and limit < 0) or backlog < 0:
            return web.json_response(
                {"detail": "limit and backlog must be >= 0"}, status=400
            )
        filters = {
            "kind": query.get("kind"),
            "outcome": query.get("outcome"),
            "session": query.get("session"),
            "tenant": query.get("tenant"),
            "min_duration_ms": min_duration_ms,
            "since": since,
        }
        if not _truthy_query(request, "follow"):
            return web.json_response(
                {"events": recorder.events(limit=limit, **filters)}
            )
        response = await _sse_prepare(request)
        # Subscribe BEFORE replaying the backlog: an event recorded between
        # the two is delivered (possibly twice at the seam — consumers
        # dedupe on `seq`), never lost.
        queue = recorder.subscribe()
        try:
            for event in reversed(recorder.events(limit=backlog, **filters)):
                await _sse_event(response, "wide_event", event)
            while True:
                try:
                    event = await asyncio.wait_for(queue.get(), timeout=15.0)
                except asyncio.TimeoutError:
                    # SSE comment as keep-alive so idle tails survive
                    # proxies with read timeouts.
                    await response.write(b": keep-alive\n\n")
                    continue
                if event_matches(event, **filters):
                    await _sse_event(response, "wide_event", event)
        except (ConnectionResetError, ConnectionAbortedError):
            return response  # tail client went away: a normal ending
        finally:
            recorder.unsubscribe(queue)

    async def debug_tasks(_request: web.Request) -> web.Response:
        """Live task/thread inventory + the loop monitor's lag state (and
        its last captured stall, stacks included)."""
        body = task_inventory()
        body["threads"] = thread_inventory()
        if loopmon is not None:
            body["monitor"] = loopmon.snapshot()
        return web.json_response(body)

    async def debug_pprof(request: web.Request) -> web.Response:
        """The continuous profiler's latest window: collapsed-stack text
        (feed it straight to flamegraph tooling) or ``?format=json`` for
        the structured view incl. the trace ids active during sampling."""
        if contprof is None:
            return web.json_response(
                {"detail": "no continuous profiler wired into this server"},
                status=501,
            )
        if request.query.get("format", "").lower() == "json":
            return web.json_response(contprof.snapshot())
        return web.Response(
            text=contprof.collapsed() + "\n", content_type="text/plain"
        )

    async def serving_snapshot(request: web.Request) -> web.Response:
        """The serving engine's deep-observability view (docs/observability.md
        "Serving observability"): batcher/queue aggregates, KV-cache
        telemetry, lifetime totals, and the last ``?steps=N`` step records
        (default 32). 501 when no ServingMonitor is wired (standalone
        servers); with one wired but no engine attached the body answers
        honestly (``attached: false``)."""
        if serving is None:
            return web.json_response(
                {"detail": "no serving monitor wired into this server"},
                status=501,
            )
        try:
            steps = int(request.query.get("steps", "32"))
        except ValueError:
            return web.json_response(
                {"detail": "steps must be an integer"}, status=400
            )
        if steps < 0:
            return web.json_response(
                {"detail": "steps must be >= 0"}, status=400
            )
        return web.json_response(serving.snapshot(steps=steps))

    async def serving_requests(request: web.Request) -> web.Response:
        """Per-request lifecycle records, newest first, with filters:
        ``outcome`` (ok/error/cancelled/preempted), ``finish`` (the batcher
        done reason), ``adapter``, ``active`` (1/0), ``min_duration_ms``,
        ``limit``."""
        if serving is None:
            return web.json_response(
                {"detail": "no serving monitor wired into this server"},
                status=501,
            )
        query = request.query
        try:
            limit = int(query["limit"]) if "limit" in query else None
            adapter = int(query["adapter"]) if "adapter" in query else None
            min_duration_ms = (
                float(query["min_duration_ms"])
                if "min_duration_ms" in query
                else None
            )
        except ValueError:
            return web.json_response(
                {
                    "detail": "limit, adapter and min_duration_ms must be "
                    "numeric"
                },
                status=400,
            )
        if limit is not None and limit < 0:
            return web.json_response(
                {"detail": "limit must be >= 0"}, status=400
            )
        active = (
            _truthy_query(request, "active") if "active" in query else None
        )
        return web.json_response(
            {
                "requests": serving.requests(
                    outcome=query.get("outcome"),
                    finish=query.get("finish"),
                    adapter=adapter,
                    active=active,
                    min_duration_ms=min_duration_ms,
                    limit=limit,
                )
            }
        )

    async def accelerator_snapshot(request: web.Request) -> web.Response:
        """The accelerator observability view (docs/observability.md
        "Accelerator observability"): compile/retrace totals + per-function
        signature sets, the latest device-memory sample (estimated on
        CPU-only runtimes), per-mesh-shape step timing, and KV-pool
        occupancy. ``?recent=N`` bounds the compile-record tail (default
        16). 501 when no DeviceMonitor is wired (standalone servers); with
        one wired but no engine attached the body answers honestly
        (``attached: false``)."""
        if device is None:
            return web.json_response(
                {"detail": "no device monitor wired into this server"},
                status=501,
            )
        try:
            recent = int(request.query.get("recent", "16"))
        except ValueError:
            return web.json_response(
                {"detail": "recent must be an integer"}, status=400
            )
        if recent < 0:
            return web.json_response(
                {"detail": "recent must be >= 0"}, status=400
            )
        return web.json_response(device.snapshot(recent=recent))

    async def fleet_snapshot(_request: web.Request) -> web.Response:
        snap = fleet.snapshot()
        # Supervisor + drain state ride on the fleet view: "is anything
        # healing or draining right now" belongs next to "what is the pool
        # doing" (scripts/fleet-top.py renders both).
        if supervisor is not None:
            snap["supervisor"] = supervisor.snapshot()
        snap["draining"] = bool(drain is not None and drain.draining)
        if sessions is not None:
            # Lease table next to the pool view: leased pods in `pods`
            # already carry owner session + lease age; this is the summary
            # (active/max, how leases have been ending).
            snap["sessions"] = sessions.snapshot()
        if analyzer is not None:
            # The analyzer's running cost-class mix (docs/analysis.md "Cost
            # classes"): exported here so the fleet router's refresh loop
            # sees what KIND of work each replica has been absorbing, not
            # just how much.
            snap["cost_classes"] = dict(analyzer.cost_class_counts)
        if tenancy is not None:
            # Tenant mix (docs/tenancy.md): per-tenant request totals, so
            # a fleet router can place by WHO is sending, not just how
            # much is arriving.
            snap["tenants"] = tenancy.mix()
        if device is not None:
            # Accelerator summary (docs/observability.md "Accelerator
            # observability"): compile/retrace totals + HBM headroom, so
            # a fleet router can steer load away from replicas that are
            # retracing or memory-tight.
            snap["accelerator"] = device.fleet_summary()
        return web.json_response(snap)

    async def fleet_events(request: web.Request) -> web.Response:
        try:
            limit = int(request.query.get("limit", "100"))
        except ValueError:
            return web.json_response(
                {"detail": "limit must be an integer"}, status=400
            )
        if limit < 0:
            return web.json_response(
                {"detail": "limit must be >= 0"}, status=400
            )
        return web.json_response({"events": fleet.events(limit=limit)})

    app.router.add_post("/v1/execute", execute)
    app.router.add_post("/v1/sessions", session_create)
    app.router.add_get("/v1/sessions", session_list)
    app.router.add_post("/v1/sessions/{session_id}/execute", session_execute)
    app.router.add_post("/v1/sessions/{session_id}/checkpoint", session_checkpoint)
    app.router.add_post("/v1/sessions/{session_id}/rollback", session_rollback)
    app.router.add_delete("/v1/sessions/{session_id}", session_delete)
    app.router.add_post("/v1/profile", profile)
    app.router.add_post("/v1/parse-custom-tool", parse_custom_tool)
    app.router.add_post("/v1/execute-custom-tool", execute_custom_tool)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics_endpoint)
    app.router.add_get("/v1/traces", list_traces)
    app.router.add_get("/v1/traces/{trace_id}", get_trace)
    app.router.add_get("/v1/fleet", fleet_snapshot)
    app.router.add_get("/v1/fleet/events", fleet_events)
    app.router.add_get("/v1/slo", slo_endpoint)
    app.router.add_get("/v1/tenants", tenants_endpoint)
    app.router.add_get("/v1/autoscale", autoscale_endpoint)
    app.router.add_get("/v1/serving", serving_snapshot)
    app.router.add_get("/v1/serving/requests", serving_requests)
    app.router.add_get("/v1/accelerator", accelerator_snapshot)
    app.router.add_get("/v1/events", list_events)
    app.router.add_get("/v1/debug/bundle", debug_bundle_endpoint)
    app.router.add_get("/v1/debug/tasks", debug_tasks)
    app.router.add_get("/v1/debug/pprof", debug_pprof)
    return app
