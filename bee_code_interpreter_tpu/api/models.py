"""HTTP API request/response models (reference http_server.py:36-74)."""

from __future__ import annotations

from typing import Literal

from pydantic import BaseModel, Field

from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash


class ExecuteRequest(BaseModel):
    source_code: str
    files: dict[AbsolutePath, Hash] = Field(default_factory=dict)
    env: dict[str, str] = Field(default_factory=dict)
    # Optional per-request deadline in seconds; clamped to the service's
    # configured execution_timeout_s (a request may shorten, never extend).
    # The reference's executor had this field but never exposed it
    # (server.rs:32; omitted by kubernetes_code_executor.py:117-123).
    timeout: float | None = Field(default=None, gt=0)


class ExecuteResponse(BaseModel):
    stdout: str
    stderr: str
    exit_code: int
    files: dict[AbsolutePath, Hash]
    # Observability additions (docs/observability.md): the request's trace id
    # (retrievable at GET /v1/traces/{trace_id} while retained) and the
    # per-stage timing breakdown (stage name → milliseconds) off the same
    # trace, so clients/benchmarks can attribute latency without scraping.
    trace_id: str | None = None
    timings_ms: dict[str, float] | None = None
    # Per-execution resource accounting: sandbox cpu/wall/rss + workspace and
    # data-plane byte deltas (schema in docs/observability.md). The same
    # figures appear as usage.* attributes on the request's root trace span.
    usage: dict | None = None
    # Edge static-analysis annotation (docs/analysis.md): policy `warn`
    # findings and the dep prediction shipped to the sandbox. Absent when
    # the analyzer had nothing to say, so the common path's wire shape is
    # unchanged.
    analysis: dict | None = None


class ProfileRequest(BaseModel):
    """``POST /v1/profile`` (docs/observability.md "Profiling workflow").

    ``target="sandbox"`` runs ``source_code`` like ``/v1/execute`` but with
    the shim's ``BCI_PROFILE_DIR`` injected, so the jax.profiler trace comes
    back through the ordinary changed-file map (listed in
    ``profile_files``). ``target="serving"`` captures ``steps`` serving-engine
    batcher steps into a control-plane-local trace directory.
    """

    target: Literal["sandbox", "serving"] = "sandbox"
    # sandbox mode (same semantics as ExecuteRequest)
    source_code: str | None = None
    files: dict[AbsolutePath, Hash] = Field(default_factory=dict)
    env: dict[str, str] = Field(default_factory=dict)
    timeout: float | None = Field(default=None, gt=0)
    # serving mode
    steps: int = Field(default=10, ge=1, le=1000)


class ParseCustomToolRequest(BaseModel):
    tool_source_code: str


class ParseCustomToolResponse(BaseModel):
    tool_name: str
    tool_input_schema_json: str
    tool_description: str


class ParseCustomToolErrorResponse(BaseModel):
    error_messages: list[str]


class ExecuteCustomToolRequest(BaseModel):
    tool_source_code: str
    tool_input_json: str
    env: dict[str, str] = Field(default_factory=dict)


class ExecuteCustomToolResponse(BaseModel):
    tool_output_json: str


class ExecuteCustomToolErrorResponse(BaseModel):
    stderr: str
