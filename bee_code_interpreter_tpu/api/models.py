"""HTTP API request/response models (reference http_server.py:36-74)."""

from __future__ import annotations

from typing import Literal

from pydantic import BaseModel, Field

from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash


class ExecuteRequest(BaseModel):
    source_code: str
    files: dict[AbsolutePath, Hash] = Field(default_factory=dict)
    env: dict[str, str] = Field(default_factory=dict)
    # Optional per-request deadline in seconds; clamped to the service's
    # configured execution_timeout_s (a request may shorten, never extend).
    # The reference's executor had this field but never exposed it
    # (server.rs:32; omitted by kubernetes_code_executor.py:117-123).
    timeout: float | None = Field(default=None, gt=0)


class ExecuteResponse(BaseModel):
    stdout: str
    stderr: str
    exit_code: int
    files: dict[AbsolutePath, Hash]
    # Observability additions (docs/observability.md): the request's trace id
    # (retrievable at GET /v1/traces/{trace_id} while retained) and the
    # per-stage timing breakdown (stage name → milliseconds) off the same
    # trace, so clients/benchmarks can attribute latency without scraping.
    trace_id: str | None = None
    timings_ms: dict[str, float] | None = None
    # Per-execution resource accounting: sandbox cpu/wall/rss + workspace and
    # data-plane byte deltas (schema in docs/observability.md). The same
    # figures appear as usage.* attributes on the request's root trace span.
    usage: dict | None = None
    # Edge static-analysis annotation (docs/analysis.md): policy `warn`
    # findings and the dep prediction shipped to the sandbox. Absent when
    # the analyzer had nothing to say, so the common path's wire shape is
    # unchanged.
    analysis: dict | None = None


class SessionCreateRequest(BaseModel):
    """``POST /v1/sessions`` (docs/sessions.md): lease one warm sandbox.

    ``files`` restores an initial workspace snapshot into the lease (the
    same {path: object id} map ``/v1/execute`` takes). ``ttl_s``/``idle_s``
    may shorten the configured bounds, never extend them."""

    files: dict[AbsolutePath, Hash] = Field(default_factory=dict)
    ttl_s: float | None = Field(default=None, gt=0)
    idle_s: float | None = Field(default=None, gt=0)


class SessionCreateResponse(BaseModel):
    session_id: str
    # Unix seconds after which the lease is expired regardless of activity;
    # idle_timeout_s is the bound between executions.
    expires_at: float
    ttl_s: float
    idle_timeout_s: float
    sandbox: str


class SessionExecuteRequest(BaseModel):
    """``POST /v1/sessions/{id}/execute``: one REPL turn. ``files`` are
    *delta* uploads into the live workspace — there is no per-execute
    restore; the sandbox keeps its state."""

    source_code: str
    files: dict[AbsolutePath, Hash] = Field(default_factory=dict)
    env: dict[str, str] = Field(default_factory=dict)
    timeout: float | None = Field(default=None, gt=0)


class SessionExecuteResponse(BaseModel):
    """Leased-execute envelope: like ``ExecuteResponse`` but the snapshot is
    deferred — ``changed_paths`` lists what the run touched; object ids
    exist only after ``POST /v1/sessions/{id}/checkpoint``."""

    stdout: str
    stderr: str
    exit_code: int
    changed_paths: list[str]
    session_id: str
    execution: int  # 1-based index of this execute within the lease
    expires_at: float
    trace_id: str | None = None
    timings_ms: dict[str, float] | None = None
    usage: dict | None = None
    analysis: dict | None = None


class SessionCheckpointResponse(BaseModel):
    session_id: str
    checkpoint_id: str
    # The snapshot: the same {path: object id} map the stateless path
    # returns — feedable back into /v1/execute or a new session.
    files: dict[AbsolutePath, Hash]


class SessionRollbackRequest(BaseModel):
    checkpoint_id: str


class ProfileRequest(BaseModel):
    """``POST /v1/profile`` (docs/observability.md "Profiling workflow").

    ``target="sandbox"`` runs ``source_code`` like ``/v1/execute`` but with
    the shim's ``BCI_PROFILE_DIR`` injected, so the jax.profiler trace comes
    back through the ordinary changed-file map (listed in
    ``profile_files``). ``target="serving"`` captures ``steps`` serving-engine
    batcher steps into a control-plane-local trace directory.
    ``target="device"`` captures a raw device-runtime trace via
    ``jax.profiler`` (serving steps when an engine is attached, a probe
    computation otherwise); 501 with the concrete reason when the runtime
    cannot trace.
    """

    target: Literal["sandbox", "serving", "device"] = "sandbox"
    # sandbox mode (same semantics as ExecuteRequest)
    source_code: str | None = None
    files: dict[AbsolutePath, Hash] = Field(default_factory=dict)
    env: dict[str, str] = Field(default_factory=dict)
    timeout: float | None = Field(default=None, gt=0)
    # serving mode
    steps: int = Field(default=10, ge=1, le=1000)


class ParseCustomToolRequest(BaseModel):
    tool_source_code: str


class ParseCustomToolResponse(BaseModel):
    tool_name: str
    tool_input_schema_json: str
    tool_description: str


class ParseCustomToolErrorResponse(BaseModel):
    error_messages: list[str]


class ExecuteCustomToolRequest(BaseModel):
    tool_source_code: str
    tool_input_json: str
    env: dict[str, str] = Field(default_factory=dict)


class ExecuteCustomToolResponse(BaseModel):
    tool_output_json: str


class ExecuteCustomToolErrorResponse(BaseModel):
    stderr: str
