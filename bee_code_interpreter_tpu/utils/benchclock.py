"""The chained-clock arithmetic every benchmark in this repo shares.

Through an accelerator tunnel, a device→host readback round-trip measured
~70 ms this session (BASELINE.md round-3 timing note) and
``block_until_ready`` is not a barrier at all — so kernels are timed as N
data-dependent applications chained inside one jit with a single readback,
and the per-call time is the difference of an N-long and a 1-long chain:
``(t_N - t_1) / (N - 1)`` cancels the fixed cost (RTT + dispatch) exactly.

``chain_diff`` is THE single copy of that difference plus its sanity guard:
if jitter swamps the chain (t_N not meaningfully above t_1), the measurement
must fail loudly — a floored difference silently prints absurd TFLOPS as
evidence. Used by scripts/bench-flash-attention.py, scripts/bench-decode.py,
and bench.py's in-sandbox flash payload.
"""

from __future__ import annotations

MARGIN = 1.2  # t_N must exceed t_1 by at least this factor


def chain_diff(t_n: float, t_1: float, n: int, what: str = "chain") -> float:
    """Per-call seconds from an n-long vs 1-long chain measurement."""
    if not t_n > t_1 * MARGIN:
        raise AssertionError(
            f"clock failed ({what}): {n}-chain {t_n * 1e3:.1f} ms not "
            f"meaningfully above 1-chain {t_1 * 1e3:.1f} ms — readback-RTT "
            "jitter swamped the kernel; raise the chain length or the shape"
        )
    return (t_n - t_1) / (n - 1)
