"""Hardware-evidence ledger: ``TPU_EVIDENCE.jsonl`` at the repo root.

Three rounds of driver benchmarks raced a TPU tunnel that flips between
healthy and wedged within a session (BASELINE.md rounds 1-3): numbers
captured while healthy kept vanishing from the record because the
end-of-round driver run happened to land on a wedged window. The fix is to
stop treating hardware numbers as point-in-time measurements: every
hardware-touching script appends its successful measurements HERE the
moment they are captured — timestamped, git-attributed, machine-readable —
and bench.py embeds the latest ledger entries in its output, so even a
driver run that finds the tunnel wedged carries dated hardware evidence.

Append is a single ``O_APPEND`` write (atomic on POSIX for our line sizes),
so concurrent scripts can record without a lock. Reads tolerate a torn or
hand-edited line by skipping it.

The reference has no analogue (it publishes no numbers at all — SURVEY §6);
this subsystem exists because the rebuild's own bar is *measured* evidence.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from datetime import datetime, timezone
from pathlib import Path
from typing import Any

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_LEDGER = REPO_ROOT / "TPU_EVIDENCE.jsonl"


def _git_sha(cwd: Path) -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=cwd, capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def ledger_path() -> Path:
    """Ledger location; ``BCI_EVIDENCE_PATH`` overrides (tests point it at
    a tmpdir so they never dirty the real ledger)."""
    override = os.environ.get("BCI_EVIDENCE_PATH")
    return Path(override) if override else DEFAULT_LEDGER


def record(case: str, payload: dict[str, Any], *, script: str,
           path: Path | None = None) -> dict[str, Any]:
    """Append one measurement to the ledger; returns the full entry.

    ``case`` names the measurement (stable across rounds, e.g.
    ``dense_matmul``); ``script`` names the producer (e.g. ``bench.py``);
    ``payload`` is the measurement JSON itself, kept verbatim under
    ``data`` so the ledger never loses detail a future reader wants.

    NEVER raises: the ledger is a side channel — a read-only checkout or a
    full disk must not turn an already-successful hardware measurement into
    a failed script (the measurement is on stdout either way). A failed
    append is reported on stderr and in the returned entry.
    """
    target = path or ledger_path()
    entry = {
        "ts": datetime.now(timezone.utc).isoformat(timespec="seconds"),
        "unix_ts": round(time.time(), 1),
        "git_sha": _git_sha(target.parent if target.parent.is_dir() else REPO_ROOT),
        "script": script,
        "case": case,
        "data": payload,
    }
    try:
        line = (json.dumps(entry, separators=(",", ":")) + "\n").encode()
        fd = os.open(target, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line)
        finally:
            os.close(fd)
    except Exception as e:
        print(f"evidence ledger append failed ({target}): {e}",
              file=sys.stderr)
        entry["ledger_error"] = str(e)
    return entry


def emit(case: str, payload: dict[str, Any], *, script: str) -> None:
    """Print the measurement as the script's stdout JSON line AND append it
    to the ledger — the ONE copy of the print-then-record pattern every
    hardware script uses, so stdout and ledger formats cannot drift."""
    print(json.dumps({"case": case, **payload}))
    record(case, payload, script=script)


def read_all(path: Path | None = None) -> list[dict[str, Any]]:
    """All well-formed ledger entries, in file order."""
    target = path or ledger_path()
    if not target.exists():
        return []
    entries: list[dict[str, Any]] = []
    for raw in target.read_text().splitlines():
        raw = raw.strip()
        if not raw:
            continue
        try:
            entry = json.loads(raw)
        except json.JSONDecodeError:
            continue  # torn/hand-edited line: skip, never crash a bench run
        if isinstance(entry, dict) and "case" in entry:
            entries.append(entry)
    return entries


def latest_per_case(path: Path | None = None) -> list[dict[str, Any]]:
    """The newest entry for each distinct ``case``, oldest-case first.

    This is what bench.py embeds: one line per kind of hardware proof
    (dense matmul, flash kernel, decode, shard_map lowering, MFU, ...),
    each carrying its own timestamp and git SHA, compact enough for a
    BENCH_r*.json artifact.
    """
    newest: dict[str, dict[str, Any]] = {}
    for entry in read_all(path):
        newest[entry["case"]] = entry  # file order == append order
    return list(newest.values())
