"""Per-request correlation ids, injected into every log line.

Reference pattern: a ContextVar set at request entry (http_server.py:84-87,
code_interpreter_servicer.py:60) read by a logging filter installed on every
handler (application_context.py:40-53). Propagated onward to the sandbox via
the ``X-Request-Id`` header (services/executor_http_driver.py sends it on
upload/execute/download; runtime/executor_server.py adopts and echoes it) so
pod-side logs correlate too (SURVEY.md §5 "Tracing / profiling").

The same filter also stamps ``trace_id``/``span_id`` from the ambient trace
context (observability/tracing.py), so text and JSON log formats can both
join edge- and pod-side lines on the trace.
"""

from __future__ import annotations

import logging
import uuid
from contextvars import ContextVar

from bee_code_interpreter_tpu.observability.tracing import current_ids

request_id_context_var: ContextVar[str] = ContextVar("request_id", default="-")


def new_request_id() -> str:
    rid = str(uuid.uuid4())
    request_id_context_var.set(rid)
    return rid


class RequestIdLoggingFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = request_id_context_var.get()
        record.trace_id, record.span_id = current_ids()
        return True


def install_request_id_filter() -> None:
    for handler in logging.getLogger().handlers:
        handler.addFilter(RequestIdLoggingFilter())
