"""Per-request correlation id, injected into every log line.

Reference pattern: a ContextVar set at request entry (http_server.py:84-87,
code_interpreter_servicer.py:60) read by a logging filter installed on every
handler (application_context.py:40-53). Propagated onward to the sandbox via
the ``X-Request-Id`` header so pod-side logs correlate too (SURVEY.md §5
"Tracing / profiling").
"""

from __future__ import annotations

import logging
import uuid
from contextvars import ContextVar

request_id_context_var: ContextVar[str] = ContextVar("request_id", default="-")


def new_request_id() -> str:
    rid = str(uuid.uuid4())
    request_id_context_var.set(rid)
    return rid


class RequestIdLoggingFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = request_id_context_var.get()
        return True


def install_request_id_filter() -> None:
    for handler in logging.getLogger().handlers:
        handler.addFilter(RequestIdLoggingFilter())
