"""Dependency-free Prometheus-style metrics.

The reference has no metrics at all (SURVEY.md §5 "No Prometheus/OTel"); this
adds the standard text exposition format (counters, gauges, histograms) without
requiring prometheus_client in the image. One process-global registry, scraped
at ``GET /metrics`` on the HTTP server.

Two exposition formats, negotiated on the ``Accept`` header at the endpoint:
the classic Prometheus text format (``text/plain; version=0.0.4``, the
default) and OpenMetrics 1.0 (``application/openmetrics-text``), which adds
the ``# EOF`` terminator and **exemplars** — each histogram bucket remembers
the ``trace_id``/``span_id`` of the most recent observation made under an
active trace, so Grafana/Prometheus can jump from a ``bci_stage_seconds``
spike straight to ``GET /v1/traces/{id}`` (docs/observability.md).
"""

from __future__ import annotations

import time
from bisect import bisect_left
from collections import defaultdict
from typing import Callable, Iterable

# Latency buckets (seconds) spanning a warm local exec (~50ms) through a cold
# TPU pod spawn (~60s, reference kubernetes_code_executor.py:239-241).
DEFAULT_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

# Token-cadence buckets (seconds) for the serving engine: TTFT and
# inter-token latency live in the 1ms-10s decade, far below request buckets.
TOKEN_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0,
)

# The Prometheus text exposition format scrapers negotiate on; a bare
# ``text/plain`` makes version-aware scrapers fall back to heuristics.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# OpenMetrics 1.0: what a scraper sends in ``Accept`` to opt in, and what the
# endpoint answers with. Only this format carries exemplars.
OPENMETRICS_MEDIA_TYPE = "application/openmetrics-text"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

def accepts_openmetrics(accept_header: str) -> bool:
    """True when the ``Accept`` header asks for the OpenMetrics exposition.
    A bare substring test would serve OpenMetrics to a client that sent
    ``application/openmetrics-text;q=0`` (RFC 9110: q=0 means "not
    acceptable"), so the media-range's q-value is honored."""
    for entry in accept_header.split(","):
        media_type, _, params = entry.strip().partition(";")
        if media_type.strip().lower() != OPENMETRICS_MEDIA_TYPE:
            continue
        q = 1.0
        for param in params.split(";"):
            name, _, value = param.strip().partition("=")
            if name.strip().lower() == "q":
                try:
                    q = float(value.strip())
                except ValueError:
                    q = 0.0  # malformed quality → treat as refused
        if q > 0.0:
            return True
    return False


# Resolved lazily on the first traced observation: utils must not import the
# observability package at module load (observability wires *into* metrics,
# not the other way around), but exemplars need the ambient trace ids.
_exemplar_ids: Callable[[], tuple[str, str]] | None = None


def _active_trace_ids() -> tuple[str, str] | None:
    """(trace_id, span_id) of the ambient trace, or None when no trace is
    active (or tracing is unavailable) — the exemplar hook, shaped to never
    raise on the observation hot path."""
    global _exemplar_ids
    if _exemplar_ids is None:
        try:
            from bee_code_interpreter_tpu.observability.tracing import current_ids
        except Exception:
            return None
        _exemplar_ids = current_ids
    trace_id, span_id = _exemplar_ids()
    if trace_id == "-":
        return None
    return trace_id, span_id


def _escape(value: str) -> str:
    # Prometheus exposition label-value escaping: backslash, quote, newline.
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_num(value: float) -> str:
    # %g rounds to 6 significant digits, visibly corrupting counters past 1e6;
    # emit integers exactly and floats at full precision like prometheus_client.
    if float(value).is_integer() and abs(value) < 2**53:
        return str(int(value))
    return repr(float(value))


class Counter:
    def __init__(self, name: str, help_text: str) -> None:
        self.name, self.help = name, help_text
        self._values: dict[tuple, float] = defaultdict(float)
        # Registry-installed label-cardinality clamp (None for bare metrics).
        self._clamp: Callable[[dict], dict] | None = None

    def inc(self, value: float = 1.0, **labels: str) -> None:
        if self._clamp is not None and labels:
            labels = self._clamp(labels)
        self._values[tuple(sorted(labels.items()))] += value

    def collect(self, openmetrics: bool = False) -> Iterable[str]:
        # OpenMetrics names the counter *family* without the _total suffix;
        # the sample keeps it. The classic format uses the full name both
        # places — scrapers of each format expect exactly their spelling.
        family = (
            self.name[: -len("_total")]
            if openmetrics and self.name.endswith("_total")
            else self.name
        )
        yield f"# HELP {family} {self.help}"
        yield f"# TYPE {family} counter"
        for key, v in sorted(self._values.items()):
            yield f"{self.name}{_fmt_labels(dict(key))} {_fmt_num(v)}"


class Gauge:
    """Gauges read from callbacks at scrape time (pool sizes, queue depths,
    breaker states). One ``Gauge`` object per metric name; each label set
    maps to its own callback (e.g. ``bci_breaker_state{breaker="k8s-spawn"}``).

    A raising callback — a pool property read during executor teardown, say —
    must never abort the whole ``/metrics`` exposition: the failure is
    contained to that one sample, emitted as ``NaN``."""

    def __init__(self, name: str, help_text: str) -> None:
        self.name, self.help = name, help_text
        self._fns: dict[tuple, Callable[[], float]] = {}
        self._clamp: Callable[[dict], dict] | None = None

    def set_fn(self, fn: Callable[[], float], **labels: str) -> None:
        if self._clamp is not None and labels:
            labels = self._clamp(labels)
        self._fns[tuple(sorted(labels.items()))] = fn

    def collect(self, openmetrics: bool = False) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        for key, fn in sorted(self._fns.items()):
            try:
                value = _fmt_num(fn())
            except Exception:
                value = "NaN"
            yield f"{self.name}{_fmt_labels(dict(key))} {value}"


class Histogram:
    def __init__(
        self, name: str, help_text: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS
    ) -> None:
        self.name, self.help = name, help_text
        self._buckets = tuple(sorted(buckets))
        # PER-BUCKET (non-cumulative) counts, one overflow-free list per
        # label set; the Prometheus-cumulative view is produced at collect
        # time. observe() is the serving hot path (called per batcher step
        # and per token-latency sample): a bisect + one increment beats
        # walking every bucket bound per observation ~10x in-loop.
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._totals: dict[tuple, int] = defaultdict(int)
        # label key -> le string -> (value, trace_id, span_id, unix_ts): the
        # most recent traced observation per bucket, exposed as an
        # OpenMetrics exemplar so a dashboard can jump spike -> trace.
        self._exemplars: dict[tuple, dict[str, tuple[float, str, str, float]]] = {}
        self._clamp: Callable[[dict], dict] | None = None

    def observe(self, value: float, **labels: str) -> None:
        if self._clamp is not None and labels:
            labels = self._clamp(labels)
        key = tuple(sorted(labels.items())) if labels else ()
        counts = self._counts.get(key)
        if counts is None:
            counts = self._counts.setdefault(key, [0] * len(self._buckets))
        # first bucket whose bound >= value (le semantics); == len(buckets)
        # means only the implicit +Inf bucket catches it
        idx = bisect_left(self._buckets, value)
        if idx < len(counts):
            counts[idx] += 1
        self._sums[key] += value
        self._totals[key] += 1
        ids = _active_trace_ids()
        if ids is not None:
            exemplar_le = (
                f"{self._buckets[idx]:g}" if idx < len(counts) else "+Inf"
            )
            self._exemplars.setdefault(key, {})[exemplar_le] = (
                value, ids[0], ids[1], time.time(),
            )

    def per_bucket_counts(self, key: tuple) -> list[int]:
        """Non-cumulative per-bucket counts for one label set, with the
        overflow (+Inf) bucket appended — the shape OTLP wants."""
        counts = self._counts.get(key, [0] * len(self._buckets))
        return [*counts, self._totals[key] - sum(counts)]

    def time(self, **labels: str) -> "_Timer":
        return _Timer(self, labels)

    def _exemplar_suffix(self, key: tuple, le: str) -> str:
        ex = self._exemplars.get(key, {}).get(le)
        if ex is None:
            return ""
        value, trace_id, span_id, ts = ex
        return (
            f' # {{trace_id="{trace_id}",span_id="{span_id}"}}'
            f" {_fmt_num(value)} {ts:.3f}"
        )

    def collect(self, openmetrics: bool = False) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        for key in sorted(self._totals):
            base = dict(key)
            counts = self._counts.get(key, [0] * len(self._buckets))
            cumulative = 0
            for bound, c in zip(self._buckets, counts):
                cumulative += c
                le = f"{bound:g}"
                yield (
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**base, 'le': le})} {cumulative}"
                    + (self._exemplar_suffix(key, le) if openmetrics else "")
                )
            yield (
                f"{self.name}_bucket{_fmt_labels({**base, 'le': '+Inf'})} "
                f"{self._totals[key]}"
                + (self._exemplar_suffix(key, "+Inf") if openmetrics else "")
            )
            yield f"{self.name}_sum{_fmt_labels(base)} {_fmt_num(self._sums[key])}"
            yield f"{self.name}_count{_fmt_labels(base)} {self._totals[key]}"


class _Timer:
    def __init__(self, hist: Histogram, labels: dict[str, str]) -> None:
        self._hist, self._labels = hist, labels

    def __enter__(self) -> "_Timer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._hist.observe(time.monotonic() - self._t0, **self._labels)


# Labels whose VALUES are client-influenced get a cardinality bound by
# default in every registry: the tenant label is stamped from (bounded)
# resolved ids, but defense in depth means even a buggy caller passing raw
# ids cannot OOM /metrics.
DEFAULT_LABEL_BOUNDS = {"tenant": 32}


class Registry:
    """Metrics are deduplicated by name: asking twice for the same counter
    (e.g. two components sharing ``bci_breaker_transitions_total``) returns
    the same object, so the exposition never emits duplicate metric blocks.

    Label-cardinality guard (docs/tenancy.md "Cardinality"): labels
    registered via :meth:`bound_label` (the ``tenant`` label by default,
    ``APP_METRICS_MAX_TENANT_LABELS``) admit at most N distinct values;
    further values collapse into ``other`` and every collapsed observation
    is counted in ``bci_metrics_label_overflow_total{label}`` — a
    tenant-id flood can widen one bucket, never the exposition."""

    def __init__(self) -> None:
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._label_bounds: dict[str, int] = dict(DEFAULT_LABEL_BOUNDS)
        self._label_seen: dict[str, set[str]] = {}
        self._label_overflow_total = self.counter(
            "bci_metrics_label_overflow_total",
            "Observations whose bounded label value collapsed into 'other' "
            "(cardinality guard), by label name",
        )

    def bound_label(self, label: str, limit: int) -> None:
        """(Re)bound a label's distinct-value budget; existing seen values
        keep their series, new ones past the limit collapse to 'other'."""
        self._label_bounds[label] = max(1, limit)

    def _clamp_labels(self, labels: dict) -> dict:
        clamped = None
        for name, limit in self._label_bounds.items():
            value = labels.get(name)
            if value is None or value == "other":
                continue
            seen = self._label_seen.setdefault(name, set())
            if value in seen:
                continue
            if len(seen) < limit:
                seen.add(value)
                continue
            if clamped is None:
                clamped = dict(labels)
            clamped[name] = "other"
            self._label_overflow_total.inc(label=name)
        return labels if clamped is None else clamped

    @property
    def metrics(self) -> dict[str, "Counter | Gauge | Histogram"]:
        """Read-only view of registered metrics by name (conventions lint,
        introspection)."""
        return dict(self._metrics)

    def _get_or_create(self, name: str, kind: type, factory):
        existing = self._metrics.get(name)
        if existing is not None:
            if not isinstance(existing, kind):
                # Same name, different type: the exposition would emit one
                # block with the wrong TYPE for half its users — a silent
                # data bug. Fail at registration, where the blame is local.
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(existing).__name__}, requested {kind.__name__}"
                )
            return existing
        m = factory()
        # Registry-owned metrics share the cardinality clamp; the overflow
        # counter itself stays clamp-free (its label values are label
        # NAMES, inherently bounded — and exempting it forecloses any
        # clamp→overflow→clamp recursion).
        if name != "bci_metrics_label_overflow_total":
            m._clamp = self._clamp_labels
        self._metrics[name] = m
        return m

    def counter(self, name: str, help_text: str) -> Counter:
        return self._get_or_create(name, Counter, lambda: Counter(name, help_text))

    def gauge(
        self, name: str, help_text: str, fn: Callable[[], float], **labels: str
    ) -> Gauge:
        m = self._get_or_create(name, Gauge, lambda: Gauge(name, help_text))
        m.set_fn(fn, **labels)
        return m

    def histogram(
        self, name: str, help_text: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            name, Histogram, lambda: Histogram(name, help_text, buckets)
        )

    def expose(self, openmetrics: bool = False) -> str:
        lines: list[str] = []
        for m in self._metrics.values():
            try:
                lines.extend(m.collect(openmetrics=openmetrics))
            except Exception:
                # One misbehaving metric must not take down the whole scrape.
                lines.append(f"# {m.name} failed to collect")
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"
