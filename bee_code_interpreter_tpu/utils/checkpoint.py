"""Sharding-aware training-state checkpoints (orbax-backed).

The reference's only checkpoint/resume mechanism is the workspace file map —
client-held ``{path → storage-id}`` restored before each run (SURVEY.md §5
"Checkpoint / resume"; reference kubernetes_code_executor.py:100-142). That
covers *files*; it cannot resume a half-trained sharded model without the
user hand-rolling serialization of every device-sharded array.

This module is the framework layer on top: save/restore of arbitrary jax
pytrees (params + optimizer state) where every leaf may be sharded over a
``jax.sharding.Mesh``. TPU-first concerns it handles:

- **Sharded I/O**: orbax writes each shard from its owning device/host (no
  gather-to-host-0 — an 8B model's optimizer state would OOM a single host).
- **Cross-topology restore**: the saved tree can be restored onto a
  *different* mesh (e.g. trained on ``{fsdp: 8, tp: 8}``, resumed for
  inference on ``{dp: 2, tp: 4}``) by passing an abstract target tree whose
  leaves carry the new ``NamedSharding``s — orbax reshards on load.
- **Preemption-shaped retention**: v5e pods are preemptible (the scheduler's
  pod groups can vanish mid-run); ``keep_last`` bounds disk while always
  retaining a recent resume point, and ``save`` blocks until the checkpoint
  is durable so a preemption immediately after a reported save cannot lose
  it.

Sandboxed training jobs write under ``/workspace`` so the checkpoint
directory itself rides the existing file snapshot/restore path between
executions (the two mechanisms compose).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp


class TrainCheckpointer:
    """Step-indexed checkpoint store for a training-state pytree.

    >>> ckpt = TrainCheckpointer(workdir / "ckpt")
    >>> ckpt.save(step, {"params": params, "opt_state": opt_state})
    >>> state = ckpt.restore(template=abstract_like(state, mesh, specs))
    """

    def __init__(self, directory: str | Path, keep_last: int = 3) -> None:
        self._mgr = ocp.CheckpointManager(
            Path(directory).resolve(),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_last, create=True
            ),
        )

    def save(self, step: int, state: Any) -> None:
        """Write ``state`` (pytree of jax arrays, sharded or not) as ``step``
        and block until it is durable on disk."""
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        # durability before returning: a preempted pod must not have
        # acknowledged a save that only existed in the async queue
        self._mgr.wait_until_finished()

    def restore(self, step: int | None = None, template: Any = None) -> Any:
        """Load ``step`` (default: latest). ``template`` is a matching pytree
        of ``jax.ShapeDtypeStruct`` (or concrete arrays) whose shardings
        define the target placement — pass shardings for a *different* mesh
        to reshard on load. Without a template, arrays restore unsharded."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self._mgr.directory}"
                )
        args = ocp.args.StandardRestore(template) if template is not None else None
        return self._mgr.restore(step, args=args)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def abstract_like(state: Any, mesh=None, specs: Any = None) -> Any:
    """Abstract (shape/dtype/sharding) template mirroring ``state``.

    With ``mesh`` + ``specs`` (a pytree of PartitionSpec matching ``state``,
    e.g. models.transformer.param_specs), leaves carry
    ``NamedSharding(mesh, spec)`` — the cross-topology restore target.
    Without them, placement metadata is dropped (restore unsharded).
    """
    from jax.sharding import NamedSharding

    if mesh is not None and specs is not None:
        return jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=NamedSharding(mesh, s)
            ),
            state,
            specs,
        )
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
    )
