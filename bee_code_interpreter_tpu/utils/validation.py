"""Validated string types used across the API surface.

Mirrors the reference's pydantic annotated aliases (utils/validation.py:19-22):
``Hash`` for storage object ids and ``AbsolutePath`` for workspace file paths.
Our storage ids are genuinely content-addressed (sha256 hex), so the Hash
pattern is tighter than the reference's ``^[0-9a-zA-Z_-]{1,255}$``, while still
accepting any 1-255 char token-safe id for forward compatibility.
"""

from typing import Annotated

from pydantic import StringConstraints

Hash = Annotated[str, StringConstraints(pattern=r"^[0-9a-zA-Z_-]{1,255}$")]
AbsolutePath = Annotated[str, StringConstraints(pattern=r"^/[^/].*$")]
