"""Compile-visible wrappers over jitted callables.

XLA compilation is the serving engine's biggest hidden latency source: a
decode step that normally takes ~15 ms stalls for seconds when a new
(shape, dtype) signature forces a retrace, and nothing in the process
says so. :class:`TrackedJit` wraps an already-``jax.jit``-ed callable and
reports every compilation to a duck-typed monitor (an
``observability.DeviceMonitor`` in the composed service, anything with an
``on_compile`` hook elsewhere) — function name, the abstract input
signature that triggered it, compile wall time, and whether it was the
function's first compile or a retrace.

Detection is cheap by design: jax's jit wrapper exposes ``_cache_size()``
(the number of compiled executables it holds), so the hot path pays two
integer probes and one clock read per call — the human-readable signature
is only computed on the rare call that actually compiled. When the probe
is missing (older/newer jax), the wrapper falls back to hashing the
abstract signature of every call, which is slower but exact.

This module is stdlib-only (the arrays are duck-typed via
``shape``/``dtype``/``nbytes``) so it imports anywhere ``utils.metrics``
does; ``models/`` uses it without importing ``observability/``.
"""

from __future__ import annotations

import time
from typing import Callable

# Containers with more leaves than this are summarized (leaf count + total
# bytes) instead of spelled out — a params pytree has hundreds of leaves
# and the culprit of a retrace is virtually always a positional array
# argument, not the weights.
_MAX_SPELLED_LEAVES = 4


def _iter_leaves(x):
    if isinstance(x, dict):
        for v in x.values():
            yield from _iter_leaves(v)
    elif isinstance(x, (list, tuple)):
        for v in x:
            yield from _iter_leaves(v)
    else:
        yield x


def _leaf_signature(x) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        dims = ",".join(str(int(d)) for d in shape)
        return f"{getattr(dtype, 'name', dtype)}[{dims}]"
    if x is None or isinstance(x, (bool, int, float, str)):
        # static argument: its VALUE is part of the compiled signature
        return repr(x)
    return type(x).__name__


def _signature(x) -> str:
    if isinstance(x, (dict, list, tuple)):
        leaves = list(_iter_leaves(x))
        if len(leaves) > _MAX_SPELLED_LEAVES:
            nbytes = sum(int(getattr(leaf, "nbytes", 0) or 0) for leaf in leaves)
            return f"{type(x).__name__}[{len(leaves)} leaves, {nbytes}B]"
        inner = ", ".join(_leaf_signature(leaf) for leaf in leaves)
        return f"{type(x).__name__}({inner})"
    return _leaf_signature(x)


def abstract_signature(args: tuple, kwargs: dict | None = None) -> str:
    """The abstract input signature of a call: per-arg ``dtype[shape]`` for
    arrays, ``repr`` for statics, condensed summaries for large pytrees —
    enough to name the shape/dtype that caused a retrace without hashing
    gigabytes of weights."""
    parts = [_signature(a) for a in args]
    if kwargs:
        parts += [f"{k}={_signature(v)}" for k, v in sorted(kwargs.items())]
    return f"({', '.join(parts)})"


class TrackedJit:
    """Wrap a jitted callable so a monitor sees its compilations.

    ``get_monitor`` is a zero-arg callable returning the current monitor
    (or None); resolving it per call keeps the wrapper attach/detach-safe
    and makes the unmonitored path a single callable invocation plus one
    None check. Attribute access (``.lower``, ``._cache_size``) passes
    through to the wrapped jit, so AOT-lowering call sites keep working.
    """

    __slots__ = ("fn", "name", "_get_monitor", "_signatures")

    def __init__(self, fn, name: str, get_monitor: Callable) -> None:
        self.fn = fn
        self.name = name
        self._get_monitor = get_monitor
        # fallback dedupe set, used only when the jit exposes no
        # _cache_size probe (then every call pays a signature render)
        self._signatures: set[str] = set()

    def __getattr__(self, item):
        return getattr(self.fn, item)

    def __call__(self, *args, **kwargs):
        monitor = self._get_monitor()
        if monitor is None:
            return self.fn(*args, **kwargs)
        probe = getattr(self.fn, "_cache_size", None)
        before = probe() if probe is not None else None
        t0 = time.monotonic()
        out = self.fn(*args, **kwargs)
        duration_ms = (time.monotonic() - t0) * 1000.0
        if probe is not None:
            if probe() <= before:
                return out
            trigger = "first_call" if before == 0 else "retrace"
            signature = abstract_signature(args, kwargs)
        else:
            signature = abstract_signature(args, kwargs)
            if signature in self._signatures:
                return out
            trigger = "first_call" if not self._signatures else "retrace"
            self._signatures.add(signature)
        # duration includes the (comparatively negligible) dispatch of the
        # freshly compiled executable — it IS the stall the caller felt
        monitor.on_compile(
            self.name,
            signature=signature,
            duration_ms=duration_ms,
            trigger=trigger,
        )
        return out
