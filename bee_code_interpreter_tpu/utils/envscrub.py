"""Scrub accelerator-tunnel plugin env vars before a CPU-only jax init.

Single source of truth for the PALLAS_*/AXON_* scrub that tests/conftest.py,
bench.py, and __graft_entry__.py all need (round-1 postmortem: these vars make
a TPU tunnel plugin hook jax backend init even under JAX_PLATFORMS=cpu and
block on a single-client tunnel — rc=124 in MULTICHIP_r01.json). One copy
means a newly discovered plugin prefix is added exactly once.

__graft_entry__.py keeps a standalone inline copy by design: the driver may
import it before this package is on sys.path.
"""

from __future__ import annotations

import os
from typing import MutableMapping

# Env prefixes owned by accelerator-tunnel platform plugins (not by jax or
# libtpu themselves): their presence alone activates the plugin's backend
# hook, so a process pinned to CPU must drop them entirely.
TUNNEL_PLUGIN_PREFIXES = ("PALLAS_", "AXON_")


def scrub_tunnel_plugin_vars(
    environ: MutableMapping[str, str] | None = None,
) -> list[str]:
    """Remove tunnel-plugin vars from ``environ`` (default: os.environ).

    Returns the removed keys (useful for logging/tests). Must run before the
    first jax backend touch to have any effect.
    """
    env = os.environ if environ is None else environ
    removed = [k for k in env if k.startswith(TUNNEL_PLUGIN_PREFIXES)]
    for key in removed:
        env.pop(key)
    return removed
