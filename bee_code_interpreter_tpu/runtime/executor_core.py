"""In-sandbox execution engine: the behavior behind ``POST /execute``.

Pure-Python reference implementation of the sandbox executor's core loop,
mirrored by the native C++ server (executor/server.cpp). The reference
implements this in Rust (executor/server.rs:120-179): write script → guess deps
→ pip install new ones → run under xonsh with timeout → scan changed files.

Deliberate TPU-first departures from the reference:

- **Plain python, not xonsh** — the reference notes ~80 ms/exec startup cost of
  xonsh as a TODO (server.rs:152); we never pay it. Shell escapes are not part
  of the capability surface we preserve (LLM code that needs a shell can use
  subprocess).
- **Recursive changed-file scan by (mtime_ns, size) snapshot diff** — the
  reference scans only the workspace top level and compares ctime to a start
  timestamp (server.rs:98-118), missing nested files and files rewritten with
  preserved timestamps. We snapshot before and diff after.
- **TPU env plumbing** — the child process inherits the pod's TPU topology env
  (TPU_WORKER_ID, TPU_WORKER_HOSTNAMES, coordinator address; SURVEY.md §2
  "Parallelism strategies") so ``jax.distributed.initialize()`` works out of the
  box on multi-host slices, and PYTHONPATH is prefixed with the runtime shim dir
  so the sitecustomize display/XLA patches load (reference sitecustomize.py:1-31).
- **Warm interpreter option** — hook point for keeping a preheated XLA client
  (SURVEY.md §7 hard part (c)); see ``warmup()``.
"""

from __future__ import annotations

import asyncio
import codecs
import dataclasses
import os
import signal
import sys
import tempfile
from pathlib import Path
from typing import AsyncIterator

from bee_code_interpreter_tpu.observability.accounting import UsageMeter
from bee_code_interpreter_tpu.runtime import dep_guess

# Env the executor forwards from its own environment into every user process,
# so JAX/libtpu sees the slice topology the scheduler provisioned, by prefix:
# the accelerator stack's vars are open-ended (libtpu TPU_*, jax JAX_*, XLA_*,
# pallas PALLAS_*, platform plugins like the axon dev tunnel AXON_*, plus
# LIBTPU_*/MEGASCALE_* for multi-slice), and missing one silently strands the
# sandbox on host CPU — the exact failure the transparent reroute exists to
# prevent.
TPU_PASSTHROUGH_PREFIXES = (
    "TPU_", "JAX_", "XLA_", "PALLAS_", "AXON_", "LIBTPU_", "MEGASCALE_",
)

# Kubernetes service links (enableServiceLinks) auto-inject FOO_SERVICE_HOST /
# FOO_PORT / FOO_PORT_80_TCP-style vars for every Service in the namespace; a
# Service named tpu-* or jax-* would land inside the prefixes above and leak
# cluster addresses into untrusted user code. But real accelerator topology
# vars share the port-suffix shape (libtpu's TPU_PROCESS_PORT, multi-slice
# MEGASCALE_PORT) — filtering on suffix alone silently strands the sandbox on
# host CPU, the exact failure this passthrough exists to prevent. So port-
# shaped keys are dropped only when the definitive service-link signature is
# present: a sibling FOO_SERVICE_HOST in the same environment (k8s always
# injects the pair together; libtpu never sets *_SERVICE_HOST).


def _is_passthrough_env(key: str, environ=None) -> bool:
    if not key.startswith(TPU_PASSTHROUGH_PREFIXES):
        return False
    if "_SERVICE_" in key:
        return False
    if key.endswith("_PORT"):
        base = key[:-len("_PORT")]
    elif "_PORT_" in key:
        base = key[: key.index("_PORT_")]
    else:
        return True
    env = os.environ if environ is None else environ
    return f"{base}_SERVICE_HOST" not in env

EXECUTION_TIMED_OUT = "Execution timed out"


@dataclasses.dataclass
class ExecutionOutcome:
    """Wire shape of the ``POST /execute`` response (minus serialization)."""

    stdout: str
    stderr: str
    exit_code: int
    files: list[str]  # logical absolute paths, e.g. "/workspace/plot.png"
    # Resource accounting (docs/observability.md): getrusage-children deltas,
    # wall clock, workspace byte deltas, deps installed for THIS execution.
    usage: dict | None = None


def snapshot_workspace(root: Path) -> dict[str, tuple[int, int]]:
    """{relative path: (mtime_ns, size)} for every regular file under root."""
    snap: dict[str, tuple[int, int]] = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            p = Path(dirpath) / name
            try:
                st = p.stat()
            except OSError:
                continue
            snap[str(p.relative_to(root))] = (st.st_mtime_ns, st.st_size)
    return snap


def changed_files(before: dict[str, tuple[int, int]], after: dict[str, tuple[int, int]]) -> list[str]:
    return sorted(rel for rel, sig in after.items() if before.get(rel) != sig)


class ExecutorCore:
    """One sandbox's execution engine, bound to a workspace directory.

    ``logical_prefix`` is the path the *client* sees ("/workspace"); the real
    directory may live anywhere (a tempdir in local mode, /workspace in a pod).
    """

    def __init__(
        self,
        workspace: str | Path,
        logical_prefix: str = "/workspace",
        preinstalled: frozenset[str] = frozenset(),
        disable_dep_install: bool = False,
        default_timeout_s: float = 60.0,
        python_executable: str | None = None,
        shim_dir: str | Path | None = None,
        installed_cache: set[str] | None = None,
    ) -> None:
        self.workspace = Path(workspace)
        self.workspace.mkdir(parents=True, exist_ok=True)
        self.logical_prefix = logical_prefix.rstrip("/")
        self.preinstalled = preinstalled
        self.disable_dep_install = disable_dep_install
        self.default_timeout_s = default_timeout_s
        self.python = python_executable or sys.executable
        self.shim_dir = str(shim_dir) if shim_dir else None
        # May be shared across per-execution cores (LocalCodeExecutor) so a dep
        # installed once isn't re-installed on every request.
        self._installed_this_session: set[str] = (
            installed_cache if installed_cache is not None else set()
        )

    # ---- logical path mapping (PUT/GET /workspace/{path}) ----

    def resolve(self, logical_path: str) -> Path:
        """Map a client path to a real file path, refusing escapes.

        Accepts "/workspace/foo", "workspace/foo", or bare "foo" — the reference
        strips the "/workspace/" prefix on upload (kubernetes_code_executor.py:103)
        and its executor joins paths as-is (server.rs:69-88); we additionally
        reject traversal outside the workspace root.
        """
        p = logical_path
        for prefix in (self.logical_prefix + "/", self.logical_prefix.lstrip("/") + "/"):
            if p.startswith(prefix):
                p = p[len(prefix):]
                break
        p = p.lstrip("/")
        real = (self.workspace / p).resolve()
        if not real.is_relative_to(self.workspace.resolve()):
            raise ValueError(f"path escapes workspace: {logical_path!r}")
        return real

    def logical(self, rel: str) -> str:
        return f"{self.logical_prefix}/{rel}"

    # ---- dependency install ----

    async def ensure_dependencies(
        self, source_code: str, predicted_deps: list[str] | None = None
    ) -> tuple[list[str], str]:
        """Guess + install missing deps. Returns (installed, stderr_notes).

        With an edge prediction attached to the request (docs/analysis.md),
        the sandbox's own AST scan is skipped entirely — the prediction is
        only re-filtered against THIS image's preinstalled/skip sets, which
        the edge cannot know."""
        if predicted_deps is not None:
            deps = dep_guess.filter_predicted(predicted_deps, self.preinstalled)
        else:
            deps = dep_guess.guess_dependencies(source_code, self.preinstalled)
        deps = [d for d in deps if d not in self._installed_this_session]
        if not deps or self.disable_dep_install:
            return [], ""
        proc = await asyncio.create_subprocess_exec(
            self.python, "-m", "pip", "install", "--no-cache-dir", *deps,
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.PIPE,
        )
        _, stderr = await proc.communicate()
        if proc.returncode == 0:
            self._installed_this_session.update(deps)
            return deps, ""
        # Match the reference's behavior of surfacing install failures in-band
        # (server.rs:140-147): execution proceeds; the user import error + pip
        # stderr tell the story.
        return [], stderr.decode(errors="replace")

    # ---- execution ----

    def _child_env(self, request_env: dict[str, str]) -> dict[str, str]:
        env = {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", str(self.workspace)),
            "LANG": "C.UTF-8",
            "PYTHONUNBUFFERED": "1",
        }
        for key, value in os.environ.items():
            if _is_passthrough_env(key):
                env[key] = value
        if self.shim_dir:
            existing = os.environ.get("PYTHONPATH", "")
            env["PYTHONPATH"] = self.shim_dir + (os.pathsep + existing if existing else "")
        elif "PYTHONPATH" in os.environ:
            env["PYTHONPATH"] = os.environ["PYTHONPATH"]
        # Shared persistent XLA compile cache (operator opt-in): single-use
        # sandboxes then pay each unique program's compile once per
        # deployment instead of once per request.
        jax_cache = os.environ.get("APP_JAX_CACHE_DIR")
        if jax_cache and "JAX_COMPILATION_CACHE_DIR" not in env:
            env["JAX_COMPILATION_CACHE_DIR"] = jax_cache
        env.update(request_env)  # request env wins (reference server.rs:154)
        # ...except the shim must survive a request-supplied PYTHONPATH: it is
        # part of the sandbox platform (reroute/display patches), not a
        # default the request replaces. (BCI_XLA_REROUTE=0 is the opt-out.)
        # Component comparison, not substring (/opt/shim vs /opt/shim2).
        if self.shim_dir:
            existing = env.get("PYTHONPATH", "")
            if self.shim_dir not in existing.split(os.pathsep):
                env["PYTHONPATH"] = self.shim_dir + (
                    os.pathsep + existing if existing else ""
                )
        # Hermetic-CPU opt-out: a request env can't REMOVE inherited vars, so
        # BCI_SCRUB_ACCELERATOR=1 asks the sandbox to drop the tunnel-plugin
        # vars whose mere presence hooks jax backend init (even under
        # JAX_PLATFORMS=cpu) — without it, a wedged TPU tunnel turns every
        # CPU-pinned payload into an execution timeout. The host PYTHONPATH
        # is dropped too (keeping the shim + request-supplied entries): a
        # host sitecustomize chain can force-register the tunnel platform
        # independent of any env var.
        if env.get("BCI_SCRUB_ACCELERATOR") == "1":
            from bee_code_interpreter_tpu.utils.envscrub import (
                scrub_tunnel_plugin_vars,
            )

            scrub_tunnel_plugin_vars(env)
            parts = [self.shim_dir] if self.shim_dir else []
            parts += [
                p
                for p in request_env.get("PYTHONPATH", "").split(os.pathsep)
                if p and p not in parts
            ]
            if parts:
                env["PYTHONPATH"] = os.pathsep.join(parts)
            else:
                env.pop("PYTHONPATH", None)
        return env

    async def execute(
        self,
        source_code: str,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        predicted_deps: list[str] | None = None,
    ) -> ExecutionOutcome:
        env = env or {}
        timeout_s = timeout_s or self.default_timeout_s
        # Off-loop walk: the workspace scan is sync filesystem I/O, and in the
        # pod server it would otherwise stall every concurrent data-plane
        # request for the duration of the walk.
        before = await asyncio.to_thread(snapshot_workspace, self.workspace)
        # The meter opens before the dep install on purpose: pip time/CPU is
        # part of what this execution cost the sandbox.
        meter = UsageMeter()

        installed, pip_notes = await self.ensure_dependencies(
            source_code, predicted_deps
        )

        with tempfile.TemporaryDirectory(prefix="exec-") as td:
            script = Path(td) / "script.py"
            script.write_text(source_code)
            # start_new_session puts the script in its own process group so a
            # timeout kill reaps grandchildren too — user code is allowed to
            # spawn subprocesses, and a surviving orphan would keep writing into
            # a torn-down workspace (or hold the pod's TPU via libtpu).
            proc = await asyncio.create_subprocess_exec(
                self.python, str(script),
                cwd=self.workspace,
                env=self._child_env(env),
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
                start_new_session=True,
            )
            try:
                stdout_b, stderr_b = await asyncio.wait_for(
                    proc.communicate(), timeout=timeout_s
                )
                exit_code = proc.returncode
                stdout = stdout_b.decode(errors="replace")
                stderr = stderr_b.decode(errors="replace")
            except asyncio.TimeoutError:
                # Reference behavior: kill, exit_code -1, fixed stderr message
                # (server.rs:151-169); the kill targets the whole group.
                self._kill_process_group(proc)
                await proc.wait()
                stdout, stderr, exit_code = "", EXECUTION_TIMED_OUT, -1
            finally:
                if proc.returncode is None:
                    # Cancelled mid-run (vanished client, watchdog kill): the
                    # user process must not outlive the execute that owns it.
                    # Under a lease the workspace survives this call, so an
                    # orphan would keep mutating state the next REPL turn (or
                    # a checkpoint) reads; the streaming twin already kills in
                    # its finally for the same reason.
                    self._kill_process_group(proc)
                    await proc.wait()

        if pip_notes:
            stderr = pip_notes + ("\n" + stderr if stderr else "")

        after = await asyncio.to_thread(snapshot_workspace, self.workspace)
        changed = changed_files(before, after)
        usage = meter.finish(
            workspace_bytes_written=sum(after[rel][1] for rel in changed),
            files_changed=len(changed),
            deps_installed=installed,
        )
        files = [self.logical(rel) for rel in changed]
        return ExecutionOutcome(
            stdout=stdout, stderr=stderr, exit_code=exit_code, files=files,
            usage=usage,
        )

    @staticmethod
    def _kill_process_group(proc) -> None:
        """Kill the whole process group (user code may spawn subprocesses; a
        surviving orphan would keep writing into a torn-down workspace)."""
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()

    async def execute_stream(
        self,
        source_code: str,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        predicted_deps: list[str] | None = None,
    ) -> AsyncIterator[tuple[str, object]]:
        """Streaming twin of :meth:`execute`: an async generator yielding
        ``("stdout"|"stderr", text_chunk)`` as the child produces output,
        then a final ``("end", ExecutionOutcome)`` with the same envelope
        the non-streaming path returns.

        Contract notes:

        - Chunk boundaries are whatever the pipe delivered; multi-byte UTF-8
          sequences split across reads are held by an incremental decoder so
          chunks are always valid text.
        - On timeout the process group is killed and the final outcome
          mirrors :meth:`execute` exactly (stdout "", stderr
          ``EXECUTION_TIMED_OUT``, exit_code -1) — chunks already delivered
          stay delivered; the envelope is authoritative.
        - An abandoned generator (consumer gone mid-stream) kills the
          process group in its ``finally`` — a vanished client must never
          leave user code running against a workspace nothing will snapshot.
        """
        env = env or {}
        timeout_s = timeout_s or self.default_timeout_s
        before = await asyncio.to_thread(snapshot_workspace, self.workspace)
        meter = UsageMeter()

        installed, pip_notes = await self.ensure_dependencies(
            source_code, predicted_deps
        )
        if pip_notes:
            # Surfaced in-band ahead of user output, matching execute()'s
            # prepend; the final envelope re-prepends so both views agree.
            yield ("stderr", pip_notes + "\n")

        proc = None
        pumps: list[asyncio.Task] = []
        timed_out = False
        stdout = stderr = ""
        exit_code: int = -1
        try:
            with tempfile.TemporaryDirectory(prefix="exec-") as td:
                script = Path(td) / "script.py"
                script.write_text(source_code)
                proc = await asyncio.create_subprocess_exec(
                    self.python, str(script),
                    cwd=self.workspace,
                    env=self._child_env(env),
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.PIPE,
                    start_new_session=True,
                )
                loop = asyncio.get_running_loop()
                hard_deadline = loop.time() + timeout_s
                queue: asyncio.Queue[tuple[str, str | None]] = asyncio.Queue()

                async def pump(stream, kind: str) -> None:
                    decoder = codecs.getincrementaldecoder("utf-8")("replace")
                    while True:
                        chunk = await stream.read(1 << 16)
                        if not chunk:
                            tail = decoder.decode(b"", True)
                            if tail:
                                await queue.put((kind, tail))
                            break
                        text = decoder.decode(chunk)
                        if text:
                            await queue.put((kind, text))
                    await queue.put((kind, None))  # EOF marker

                pumps = [
                    asyncio.ensure_future(pump(proc.stdout, "stdout")),
                    asyncio.ensure_future(pump(proc.stderr, "stderr")),
                ]
                parts: dict[str, list[str]] = {"stdout": [], "stderr": []}
                eofs = 0
                while eofs < 2:
                    remaining = hard_deadline - loop.time()
                    if remaining <= 0:
                        timed_out = True
                        break
                    try:
                        kind, text = await asyncio.wait_for(
                            queue.get(), timeout=remaining
                        )
                    except asyncio.TimeoutError:
                        timed_out = True
                        break
                    if text is None:
                        eofs += 1
                        continue
                    parts[kind].append(text)
                    yield (kind, text)
                if timed_out:
                    self._kill_process_group(proc)
                    await proc.wait()
                    stdout, stderr, exit_code = "", EXECUTION_TIMED_OUT, -1
                    # The envelope is authoritative, but a live consumer
                    # deserves the reason in-band too.
                    yield ("stderr", EXECUTION_TIMED_OUT)
                else:
                    await proc.wait()
                    exit_code = proc.returncode
                    stdout = "".join(parts["stdout"])
                    stderr = "".join(parts["stderr"])
        finally:
            for task in pumps:
                task.cancel()
            if proc is not None and proc.returncode is None:
                # Abandoned mid-stream (GeneratorExit lands here): reap the
                # child before the workspace goes away.
                self._kill_process_group(proc)
                await proc.wait()

        if pip_notes:
            stderr = pip_notes + ("\n" + stderr if stderr else "")

        after = await asyncio.to_thread(snapshot_workspace, self.workspace)
        changed = changed_files(before, after)
        usage = meter.finish(
            workspace_bytes_written=sum(after[rel][1] for rel in changed),
            files_changed=len(changed),
            deps_installed=installed,
        )
        yield (
            "end",
            ExecutionOutcome(
                stdout=stdout,
                stderr=stderr,
                exit_code=exit_code,
                files=[self.logical(rel) for rel in changed],
                usage=usage,
            ),
        )

    async def warmup(self) -> None:
        """Pre-heat the interpreter/XLA path so the first request doesn't pay it.

        In the TPU pod this runs at container start (the C++ server execs it
        before reporting Ready): import jax, touch the device, trigger libtpu
        init. Analogous in spirit to the reference image's matplotlib font-cache
        warmup at build time (executor/Dockerfile:103), but for the XLA client.
        """
        await self.execute(
            "try:\n"
            "    import jax\n"
            "    jax.numpy.zeros(8).block_until_ready()\n"
            "except Exception:\n"
            "    pass\n",
            timeout_s=120.0,
        )
