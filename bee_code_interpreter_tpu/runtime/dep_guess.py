"""Static import → PyPI dependency guesser.

Replaces the reference's out-of-process ``upm guess`` subprocess + sqlite
import-map (reference: executor/server.rs:126-133, executor/Dockerfile:30-37,
124-126) with an in-process static scan: parse the submitted source with
``ast``, collect absolutely-imported top-level module names, drop stdlib and
preinstalled/skip-listed names, and map the rest through a curated
import-name → PyPI-package table. No subprocess, no sqlite — this removes a
per-request fork+exec from the hot path (SURVEY.md §3.2 lists ``upm guess``
as a latency driver).

The C++ executor implements the same algorithm (executor/dep_guess.cpp) against
the same table file so both executors agree; this module is also the unit-test
oracle for that file format.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

# Import-name → PyPI-distribution-name, for the common cases where they differ.
# (Equivalent of upm's pypi_map.sqlite; the executor image ships this as
# executor/pypi_map.tsv for the C++ server.)
PYPI_MAP: dict[str, str] = {
    "attr": "attrs",
    "bs4": "beautifulsoup4",
    "cairosvg": "CairoSVG",
    "cv2": "opencv-python",
    "Crypto": "pycryptodome",
    "dateutil": "python-dateutil",
    "docx": "python-docx",
    "dotenv": "python-dotenv",
    "fitz": "pymupdf",
    "github": "PyGithub",
    "googleapiclient": "google-api-python-client",
    "jose": "python-jose",
    "kubernetes": "kubernetes",
    "lxml": "lxml",
    "magic": "python-magic",
    "mpl_toolkits": "matplotlib",
    "OpenSSL": "pyOpenSSL",
    "PIL": "pillow",
    "pptx": "python-pptx",
    "psycopg2": "psycopg2-binary",
    "pydub": "pydub",
    "pypdf": "pypdf",
    "PyPDF2": "PyPDF2",
    "serial": "pyserial",
    "skimage": "scikit-image",
    "sklearn": "scikit-learn",
    "slugify": "python-slugify",
    "socks": "PySocks",
    "telegram": "python-telegram-bot",
    "usb": "pyusb",
    "yaml": "PyYAML",
    "zmq": "pyzmq",
}

# Names that must never be pip-installed: provided by the OS/image, or aliases
# whose pip name collides with an unrelated/broken dist (reference:
# executor/requirements-skip.txt:1-26). The TPU image additionally pins the
# accelerator stack — auto-install must never clobber jax/libtpu versions
# (SURVEY.md §7 hard part (d)).
SKIP: frozenset[str] = frozenset(
    {
        # accelerator stack — pinned in the image, never reinstall
        "jax", "jaxlib", "libtpu", "torch", "torch_xla", "flax", "optax",
        "orbax", "chex", "haiku", "pallas",
        # OS-package-provided tools that upm-style guessers misattribute
        "ffmpeg", "pandoc", "magick", "imagemagick",
        # our own runtime
        "bee_code_interpreter_tpu",
    }
)


def guessed_imports(source_code: str) -> set[str]:
    """Top-level module names imported (absolutely) anywhere in the source."""
    try:
        tree = ast.parse(source_code)
    except SyntaxError:
        return set()
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names.update(alias.name.split(".")[0] for alias in node.names)
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            names.add(node.module.split(".")[0])
    return names


def guess_dependencies(
    source_code: str,
    preinstalled: frozenset[str] | set[str] = frozenset(),
    extra_skip: frozenset[str] | set[str] = frozenset(),
) -> list[str]:
    """PyPI package names to install before running ``source_code``.

    ``preinstalled`` holds *normalized distribution names* already in the image
    (loaded from requirements.txt like the reference's REQUIREMENTS set,
    executor/server.rs:44-67).
    """
    deps: set[str] = set()
    pre = {_normalize(p) for p in preinstalled}
    for mod in guessed_imports(source_code):
        if mod in sys.stdlib_module_names or mod in SKIP or mod in extra_skip:
            continue
        pkg = PYPI_MAP.get(mod, mod)
        if _normalize(pkg) in pre or _normalize(mod) in pre:
            continue
        deps.add(pkg)
    return sorted(deps)


def _normalize(name: str) -> str:
    # PEP 503 normalization, plus stripping extras ("pandas[excel]" → "pandas").
    name = name.split("[", 1)[0].strip()
    return name.lower().replace("_", "-").replace(".", "-")


def load_requirements_set(*paths: str | Path) -> frozenset[str]:
    """Preinstalled-requirements set from requirements.txt-style files.

    Strips comments, version specifiers, and extras, mirroring the reference's
    startup loading of /requirements.txt + /requirements-skip.txt
    (executor/server.rs:44-67, 198-201).
    """
    out: set[str] = set()
    for p in paths:
        p = Path(p)
        if not p.exists():
            continue
        for line in p.read_text().splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            for sep in ("==", ">=", "<=", "~=", "!=", ">", "<", ";", "@"):
                line = line.split(sep, 1)[0]
            out.add(_normalize(line))
    return frozenset(out)
