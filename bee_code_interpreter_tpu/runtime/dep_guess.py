"""Static import → PyPI dependency guesser.

Replaces the reference's out-of-process ``upm guess`` subprocess + sqlite
import-map (reference: executor/server.rs:126-133, executor/Dockerfile:30-37,
124-126) with an in-process static scan: parse the submitted source with
``ast``, collect absolutely-imported top-level module names, drop stdlib and
preinstalled/skip-listed names, and map the rest through a curated
import-name → PyPI-package table. No subprocess, no sqlite — this removes a
per-request fork+exec from the hot path (SURVEY.md §3.2 lists ``upm guess``
as a latency driver).

The C++ executor implements the same algorithm (executor/dep_guess.cpp) against
the same table file so both executors agree; this module is also the unit-test
oracle for that file format.

Coverage stance vs upm's pypi_map.sqlite (reference executor/Dockerfile:124-126):
upm ships a full PyPI-derived table; this environment has no egress, so that
table cannot be fetched or diffed against. What IS guaranteed, by tests:
~600 curated entries covering every rename in the executor image's own stack
(harvested from installed-dist metadata via ``scripts/generate-pypi-map.py
--harvest``) plus the high-traffic aliases LLM-generated code hits; C++/Python
parity over the ENTIRE map (tests/test_native_executor.py); and identity
fallback for everything else — pip normalizes case/underscore itself, so only
true renames belong here. A wrong invented mapping would pip-install the wrong
package (dependency-confusion shaped), which is why the long tail is curated
rather than bulk-generated.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

# Import-name → PyPI-distribution-name, for the cases where they differ.
# (Equivalent of upm's pypi_map.sqlite, curated down to the high-traffic
# entries LLM-generated code actually imports; the executor image ships this
# as executor/pypi_map.tsv for the C++ server — regenerate with
# scripts/generate-pypi-map.py after editing.) Identity mappings are omitted:
# ``guess_dependencies`` falls back to the import name itself.
PYPI_MAP: dict[str, str] = {
    # -- imaging / media ------------------------------------------------
    "PIL": "pillow",
    "cv2": "opencv-python",
    "skimage": "scikit-image",
    "imageio_ffmpeg": "imageio-ffmpeg",
    "ffmpeg": "ffmpeg-python",
    "pydub": "pydub",
    "moviepy": "moviepy",
    "cairosvg": "CairoSVG",
    "cairo": "pycairo",
    "wand": "Wand",
    "qrcode": "qrcode",
    "pytesseract": "pytesseract",
    "face_recognition": "face-recognition",
    "insightface": "insightface",
    # -- documents / office ---------------------------------------------
    "fitz": "pymupdf",
    "pymupdf": "pymupdf",
    "docx": "python-docx",
    "pptx": "python-pptx",
    "xlrd": "xlrd",
    "xlsxwriter": "XlsxWriter",
    "odf": "odfpy",
    "ebooklib": "EbookLib",
    "pdfminer": "pdfminer.six",
    "pdf2image": "pdf2image",
    "pikepdf": "pikepdf",
    "pypandoc": "pypandoc",
    "weasyprint": "weasyprint",
    "reportlab": "reportlab",
    "tabula": "tabula-py",
    "camelot": "camelot-py",
    "pypdf": "pypdf",
    "PyPDF2": "PyPDF2",
    "fpdf": "fpdf2",
    "markdown": "Markdown",
    "markdownify": "markdownify",
    "frontmatter": "python-frontmatter",
    "pylatex": "PyLaTeX",
    "pybtex": "pybtex",
    # -- scraping / web clients -----------------------------------------
    "bs4": "beautifulsoup4",
    "requests_oauthlib": "requests-oauthlib",
    "requests_toolbelt": "requests-toolbelt",
    "websocket": "websocket-client",
    "socks": "PySocks",
    "fake_useragent": "fake-useragent",
    "selenium": "selenium",
    "scrapy": "Scrapy",
    "cloudscraper": "cloudscraper",
    "newspaper": "newspaper3k",
    "readability": "readability-lxml",
    "feedparser": "feedparser",
    "yt_dlp": "yt-dlp",
    "youtube_dl": "youtube-dl",
    "wikipedia": "wikipedia",
    "duckduckgo_search": "duckduckgo-search",
    # -- data / scientific ----------------------------------------------
    "mpl_toolkits": "matplotlib",
    "pylab": "matplotlib",
    "sklearn": "scikit-learn",
    "umap": "umap-learn",
    "Bio": "biopython",
    "rdkit": "rdkit",
    "pywt": "PyWavelets",
    "netCDF4": "netCDF4",
    "osgeo": "GDAL",
    "shapefile": "pyshp",
    "mpl_finance": "mpl-finance",
    "mplfinance": "mplfinance",
    "ta": "ta",
    "yfinance": "yfinance",
    "pandas_datareader": "pandas-datareader",
    "pandas_ta": "pandas-ta",
    "stl": "numpy-stl",
    "graphviz": "graphviz",
    "pygraphviz": "pygraphviz",
    "igraph": "python-igraph",
    "community": "python-louvain",
    "fuzzywuzzy": "fuzzywuzzy",
    "Levenshtein": "Levenshtein",
    "jellyfish": "jellyfish",
    "patsy": "patsy",
    "pymc": "pymc",
    "cvxpy": "cvxpy",
    "pulp": "PuLP",
    "ortools": "ortools",
    "deap": "deap",
    "gymnasium": "gymnasium",
    "gym": "gym",
    # -- ML / NLP ---------------------------------------------------------
    "speech_recognition": "SpeechRecognition",
    "sentence_transformers": "sentence-transformers",
    "huggingface_hub": "huggingface-hub",
    "datasets": "datasets",
    "tokenizers": "tokenizers",
    "safetensors": "safetensors",
    "sklearn_crfsuite": "sklearn-crfsuite",
    "textblob": "textblob",
    "langdetect": "langdetect",
    "nltk": "nltk",
    "spacy": "spacy",
    "gensim": "gensim",
    "wordcloud": "wordcloud",
    "whisper": "openai-whisper",
    "tiktoken": "tiktoken",
    "langchain": "langchain",
    "anthropic": "anthropic",
    "openai": "openai",
    # namespace-package second-level names (see NAMESPACE_PREFIXES): the
    # guesser retains "google.X" instead of truncating to the uninstallable
    # "google", so these keys are reachable.
    "google.protobuf": "protobuf",
    "google.auth": "google-auth",
    "google.oauth2": "google-auth",
    "google.api_core": "google-api-core",
    "google.generativeai": "google-generativeai",
    "google.genai": "google-genai",
    "google.ads": "google-ads",
    # -- databases / storage ----------------------------------------------
    "psycopg2": "psycopg2-binary",
    "MySQLdb": "mysqlclient",
    "pymysql": "PyMySQL",
    "mysql": "mysql-connector-python",
    "sqlalchemy": "SQLAlchemy",
    "bson": "pymongo",
    "gridfs": "pymongo",
    "cassandra": "cassandra-driver",
    "couchdb": "CouchDB",
    "neo4j": "neo4j",
    "redis": "redis",
    "memcache": "python-memcached",
    "snowflake": "snowflake-connector-python",
    "duckdb": "duckdb",
    "pyarrow": "pyarrow",
    "fastparquet": "fastparquet",
    "h5py": "h5py",
    "tables": "tables",
    "zarr": "zarr",
    "smart_open": "smart-open",
    "fsspec": "fsspec",
    "s3fs": "s3fs",
    "gcsfs": "gcsfs",
    "minio": "minio",
    # -- cloud / APIs -----------------------------------------------------
    "googleapiclient": "google-api-python-client",
    "google_auth_oauthlib": "google-auth-oauthlib",
    "github": "PyGithub",
    "gitlab": "python-gitlab",
    "git": "GitPython",
    "jira": "jira",
    "slack_sdk": "slack-sdk",
    "telegram": "python-telegram-bot",
    "discord": "discord.py",
    "tweepy": "tweepy",
    "praw": "praw",
    "stripe": "stripe",
    "twilio": "twilio",
    "sendgrid": "sendgrid",
    "boto3": "boto3",
    "botocore": "botocore",
    "kubernetes": "kubernetes",
    "docker": "docker",
    "kafka": "kafka-python",
    "pika": "pika",
    "paho": "paho-mqtt",
    "grpc": "grpcio",
    "etcd3": "etcd3",
    "consul": "python-consul",
    # -- web frameworks ---------------------------------------------------
    "flask": "Flask",
    "flask_cors": "Flask-Cors",
    "flask_sqlalchemy": "Flask-SQLAlchemy",
    "flask_login": "Flask-Login",
    "flask_wtf": "Flask-WTF",
    "flask_migrate": "Flask-Migrate",
    "flask_restful": "Flask-RESTful",
    "django": "Django",
    "rest_framework": "djangorestframework",
    "corsheaders": "django-cors-headers",
    "fastapi": "fastapi",
    "starlette": "starlette",
    "uvicorn": "uvicorn",
    "gunicorn": "gunicorn",
    "sanic": "sanic",
    "tornado": "tornado",
    "aiohttp": "aiohttp",
    "socketio": "python-socketio",
    "engineio": "python-engineio",
    "jinja2": "Jinja2",
    "wtforms": "WTForms",
    "werkzeug": "Werkzeug",
    "multipart": "python-multipart",
    "jwt": "PyJWT",
    "jose": "python-jose",
    "email_validator": "email-validator",
    "itsdangerous": "itsdangerous",
    "graphene": "graphene",
    "strawberry": "strawberry-graphql",
    "streamlit": "streamlit",
    "gradio": "gradio",
    "dash": "dash",
    "nicegui": "nicegui",
    # -- crypto / security ------------------------------------------------
    "Crypto": "pycryptodome",
    "Cryptodome": "pycryptodomex",
    "OpenSSL": "pyOpenSSL",
    "nacl": "PyNaCl",
    "jwcrypto": "jwcrypto",
    "passlib": "passlib",
    "bcrypt": "bcrypt",
    "argon2": "argon2-cffi",
    "scapy": "scapy",
    "nmap": "python-nmap",
    "shodan": "shodan",
    "web3": "web3",
    "eth_account": "eth-account",
    "solana": "solana",
    "ccxt": "ccxt",
    # -- system / misc utilities ------------------------------------------
    "attr": "attrs",
    "attrs": "attrs",
    "dateutil": "python-dateutil",
    "dotenv": "python-dotenv",
    "magic": "python-magic",
    "serial": "pyserial",
    "usb": "pyusb",
    "yaml": "PyYAML",
    "zmq": "pyzmq",
    "slugify": "python-slugify",
    "unidecode": "Unidecode",
    "charset_normalizer": "charset-normalizer",
    "chardet": "chardet",
    "prettytable": "prettytable",
    "tabulate": "tabulate",
    "termcolor": "termcolor",
    "colorama": "colorama",
    "rich": "rich",
    "typer": "typer",
    "click": "click",
    "fire": "fire",
    "docopt": "docopt",
    "tqdm": "tqdm",
    "halo": "halo",
    "schedule": "schedule",
    "crontab": "python-crontab",
    "apscheduler": "APScheduler",
    "dateparser": "dateparser",
    "pendulum": "pendulum",
    "arrow": "arrow",
    "tzlocal": "tzlocal",
    "pytz": "pytz",
    "humanize": "humanize",
    "phonenumbers": "phonenumbers",
    "faker": "Faker",
    "mimesis": "mimesis",
    "constraint": "python-constraint",
    "ruamel": "ruamel.yaml",
    "toml": "toml",
    "tomlkit": "tomlkit",
    "ujson": "ujson",
    "orjson": "orjson",
    "msgpack": "msgpack",
    "jsonschema": "jsonschema",
    "cerberus": "Cerberus",
    "marshmallow": "marshmallow",
    "deepdiff": "deepdiff",
    "dictdiffer": "dictdiffer",
    "xmltodict": "xmltodict",
    "defusedxml": "defusedxml",
    "html5lib": "html5lib",
    "cssselect": "cssselect",
    "emoji": "emoji",
    "regex": "regex",
    "parse": "parse",
    "ply": "ply",
    "lark": "lark",
    "pyparsing": "pyparsing",
    "prometheus_client": "prometheus-client",
    "structlog": "structlog",
    "loguru": "loguru",
    "sentry_sdk": "sentry-sdk",
    "dotmap": "dotmap",
    "box": "python-box",
    "cachetools": "cachetools",
    "diskcache": "diskcache",
    "joblib": "joblib",
    "cloudpickle": "cloudpickle",
    "dill": "dill",
    "psutil": "psutil",
    "distro": "distro",
    "watchdog": "watchdog",
    "send2trash": "Send2Trash",
    "filelock": "filelock",
    "portalocker": "portalocker",
    "retrying": "retrying",
    "tenacity": "tenacity",
    "backoff": "backoff",
    "ratelimit": "ratelimit",
    "more_itertools": "more-itertools",
    "toolz": "toolz",
    "funcy": "funcy",
    "boltons": "boltons",
    "sortedcontainers": "sortedcontainers",
    "bidict": "bidict",
    "frozendict": "frozendict",
    "typing_extensions": "typing-extensions",
    "pkg_resources": "setuptools",
    "pygments": "Pygments",
    "sphinx": "Sphinx",
    "nbformat": "nbformat",
    "nbconvert": "nbconvert",
    "papermill": "papermill",
    "ipywidgets": "ipywidgets",
    "IPython": "ipython",
    "pexpect": "pexpect",
    "ptyprocess": "ptyprocess",
    "sh": "sh",
    "plumbum": "plumbum",
    "invoke": "invoke",
    "fabric": "fabric",
    "paramiko": "paramiko",
    "scp": "scp",
    "asyncssh": "asyncssh",
    "aiofiles": "aiofiles",
    "anyio": "anyio",
    "trio": "trio",
    "curio": "curio",
    "uvloop": "uvloop",
    "nest_asyncio": "nest-asyncio",
    # -- games / gui / audio ----------------------------------------------
    "pygame": "pygame",
    "pyglet": "pyglet",
    "arcade": "arcade",
    "wx": "wxPython",
    "gi": "PyGObject",
    "PyQt5": "PyQt5",
    "PyQt6": "PyQt6",
    "PySide6": "PySide6",
    "kivy": "Kivy",
    "turtle3d": "turtle3d",
    "sounddevice": "sounddevice",
    "soundfile": "soundfile",
    "librosa": "librosa",
    "mido": "mido",
    "music21": "music21",
    "pyaudio": "PyAudio",
    "playsound": "playsound",
    "gtts": "gTTS",
    "pyttsx3": "pyttsx3",
    "chess": "chess",
    "pynput": "pynput",
    "pyautogui": "PyAutoGUI",
    "keyboard": "keyboard",
    "mouse": "mouse",
    "screeninfo": "screeninfo",
    "mss": "mss",
}

# Long-tail import aliases (the reference ships upm's full pypi_map.sqlite,
# thousands of rows, its executor/Dockerfile:124-126; this environment has no
# egress to fetch it, so the tail is curated: every entry below is a real
# import-name → distribution-name divergence, several harvested from installed
# package metadata by `scripts/generate-pypi-map.py --harvest`).
PYPI_MAP.update({
    # -- verified from installed-dist metadata ---------------------------
    "Box2D": "box2d-py",
    "OpenGL": "PyOpenGL",
    "absl": "absl-py",
    "clang": "libclang",
    "elftools": "pyelftools",
    "grpc_status": "grpcio-status",
    "grpc_tools": "grpcio-tools",
    # (orbax / haiku map entries exist for completeness below, but those
    # imports are in SKIP — the pinned accelerator stack must never be
    # auto-installed; SKIP wins before the map is consulted)
    "markdown_it": "markdown-it-py",
    "opentelemetry": "opentelemetry-api",
    "proto": "proto-plus",
    "pythonjsonlogger": "python-json-logger",
    "rpds": "rpds-py",
    "tlz": "toolz",
    "tree": "dm-tree",
    "vertexai": "google-cloud-aiplatform",
    # -- classic traps (import name != dist name) ------------------------
    "MeCab": "mecab-python3",
    "RPi": "RPi.GPIO",
    "airflow": "apache-airflow",
    "alpha_vantage": "alpha-vantage",
    "ansible_runner": "ansible-runner",
    "barcode": "python-barcode",
    "binance": "python-binance",
    "bluetooth": "PyBluez",
    "brownie": "eth-brownie",
    "can": "python-can",
    "capnp": "pycapnp",
    "cpuinfo": "py-cpuinfo",
    "daemon": "python-daemon",
    "darts": "u8darts",
    "decouple": "python-decouple",
    "digitalocean": "python-digitalocean",
    "dns": "dnspython",
    "ee": "earthengine-api",
    "eyed3": "eyeD3",
    "factory": "factory-boy",
    "faiss": "faiss-cpu",
    "finnhub": "finnhub-python",
    "fireworks": "fireworks-ai",
    "flash_attn": "flash-attn",
    "fluidsynth": "pyFluidSynth",
    "gin": "gin-config",
    "hydra": "hydra-core",
    "imblearn": "imbalanced-learn",
    "impala": "impyla",
    "llama_cpp": "llama-cpp-python",
    "mega": "mega.py",
    "midiutil": "MIDIUtil",
    "nasdaqdatalink": "Nasdaq-Data-Link",
    "nio": "matrix-nio",
    "office365": "Office365-REST-Python-Client",
    "opensearchpy": "opensearch-py",
    "paddle": "paddlepaddle",
    "piptools": "pip-tools",
    "polygon": "polygon-api-client",
    "pyannote": "pyannote.audio",
    "pythoncom": "pywin32",
    "pywintypes": "pywin32",
    "rapidjson": "python-rapidjson",
    "rocksdb": "python-rocksdb",
    "skbio": "scikit-bio",
    "slack": "slackclient",
    "snappy": "python-snappy",
    "speedtest": "speedtest-cli",
    "spellchecker": "pyspellchecker",
    "talib": "TA-Lib",
    "tortoise": "tortoise-orm",
    "vcr": "vcrpy",
    "vcf": "PyVCF3",
    "weaviate": "weaviate-client",
    "webview": "pywebview",
    "whois": "python-whois",
    "win32api": "pywin32",
    "win32clipboard": "pywin32",
    "win32com": "pywin32",
    "win32con": "pywin32",
    "win32event": "pywin32",
    "win32file": "pywin32",
    "win32gui": "pywin32",
    "win32process": "pywin32",
    "win32ui": "pywin32",
    "zipline": "zipline-reloaded",
    # -- flask / django ecosystem ----------------------------------------
    "allauth": "django-allauth",
    "colorfield": "django-colorfield",
    "crispy_forms": "django-crispy-forms",
    "debug_toolbar": "django-debug-toolbar",
    "django_celery_beat": "django-celery-beat",
    "django_celery_results": "django-celery-results",
    "django_extensions": "django-extensions",
    "django_filters": "django-filter",
    "environ": "django-environ",
    "flask_admin": "Flask-Admin",
    "flask_apscheduler": "Flask-APScheduler",
    "flask_babel": "Flask-Babel",
    "flask_bcrypt": "Flask-Bcrypt",
    "flask_caching": "Flask-Caching",
    "flask_compress": "Flask-Compress",
    "flask_jwt_extended": "Flask-JWT-Extended",
    "flask_limiter": "Flask-Limiter",
    "flask_mail": "Flask-Mail",
    "flask_marshmallow": "flask-marshmallow",
    "flask_session": "Flask-Session",
    "flask_socketio": "Flask-SocketIO",
    "flask_talisman": "flask-talisman",
    "import_export": "django-import-export",
    "knox": "django-rest-knox",
    "mptt": "django-mptt",
    "oauth2_provider": "django-oauth-toolkit",
    "phonenumber_field": "django-phonenumber-field",
    "rest_framework_simplejwt": "djangorestframework-simplejwt",
    "silk": "django-silk",
    "simple_history": "django-simple-history",
    "storages": "django-storages",
    "taggit": "django-taggit",
    # -- web / http extras -----------------------------------------------
    "aiohttp_cors": "aiohttp-cors",
    "aiohttp_jinja2": "aiohttp-jinja2",
    "deep_translator": "deep-translator",
    "fastapi_pagination": "fastapi-pagination",
    "fastapi_users": "fastapi-users",
    "googlesearch": "googlesearch-python",
    "httpx_sse": "httpx-sse",
    "linkedin_api": "linkedin-api",
    "lxml_html_clean": "lxml-html-clean",
    "mechanicalsoup": "MechanicalSoup",
    "requests_cache": "requests-cache",
    "requests_html": "requests-html",
    "seleniumwire": "selenium-wire",
    "sse_starlette": "sse-starlette",
    "undetected_chromedriver": "undetected-chromedriver",
    "webdriver_manager": "webdriver-manager",
    # -- data / ML -------------------------------------------------------
    "category_encoders": "category-encoders",
    "efficientnet_pytorch": "efficientnet-pytorch",
    "feature_engine": "feature-engine",
    "keras_cv": "keras-cv",
    "keras_nlp": "keras-nlp",
    "keras_tuner": "keras-tuner",
    "ml_collections": "ml-collections",
    "mlx_lm": "mlx-lm",
    "pandas_profiling": "pandas-profiling",
    "pytorch_lightning": "pytorch-lightning",
    "sb3_contrib": "sb3-contrib",
    "scikit_posthocs": "scikit-posthocs",
    "segmentation_models_pytorch": "segmentation-models-pytorch",
    "sklearn_pandas": "sklearn-pandas",
    "stable_baselines3": "stable-baselines3",
    "tensorflow_addons": "tensorflow-addons",
    "tensorflow_datasets": "tensorflow-datasets",
    "tensorflow_hub": "tensorflow-hub",
    "tensorflow_probability": "tensorflow-probability",
    "tensorflow_text": "tensorflow-text",
    "tflite_runtime": "tflite-runtime",
    "ydata_profiling": "ydata-profiling",
    # -- LLM / vector stores ---------------------------------------------
    "langchain_anthropic": "langchain-anthropic",
    "langchain_community": "langchain-community",
    "langchain_core": "langchain-core",
    "langchain_openai": "langchain-openai",
    "llama_index": "llama-index",
    "qdrant_client": "qdrant-client",
    "rank_bm25": "rank-bm25",
    "semantic_kernel": "semantic-kernel",
    # -- NLP / text ------------------------------------------------------
    "bert_score": "bert-score",
    "camel_tools": "camel-tools",
    "email_reply_parser": "email-reply-parser",
    "imap_tools": "imap-tools",
    "indic_transliteration": "indic-transliteration",
    "korean_lunar_calendar": "korean-lunar-calendar",
    "mailparser": "mail-parser",
    "rouge_score": "rouge-score",
    # -- imaging / media -------------------------------------------------
    "blend_modes": "blend-modes",
    "imagehash": "ImageHash",
    "perlin_noise": "perlin-noise",
    "psd_tools": "psd-tools",
    "pydrive": "PyDrive",
    "pydrive2": "PyDrive2",
    "pyrebase": "Pyrebase4",
    "sv_ttk": "sv-ttk",
    # -- infra / db ------------------------------------------------------
    "clickhouse_connect": "clickhouse-connect",
    "clickhouse_driver": "clickhouse-driver",
    "cron_descriptor": "cron-descriptor",
    "elasticsearch_dsl": "elasticsearch-dsl",
    "firebase_admin": "firebase-admin",
    "ibm_db": "ibm-db",
    "influxdb_client": "influxdb-client",
    "jsonpath_ng": "jsonpath-ng",
    "linode_api4": "linode-api4",
    "mailjet_rest": "mailjet-rest",
    "matrix_client": "matrix-client",
    "model_bakery": "model-bakery",
    "prometheus_api_client": "prometheus-api-client",
    "pykube": "pykube-ng",
    "slack_bolt": "slack-bolt",
    "vertica_python": "vertica-python",
    # -- finance ---------------------------------------------------------
    "alpaca": "alpaca-py",
    "forex_python": "forex-python",
    "pandas_market_calendars": "pandas-market-calendars",
    "tradingview_ta": "tradingview-ta",
    "yahoo_fin": "yahoo-fin",
    # -- crypto / eth ----------------------------------------------------
    "eth_abi": "eth-abi",
    "eth_keys": "eth-keys",
    "eth_typing": "eth-typing",
    "eth_utils": "eth-utils",
    "slither": "slither-analyzer",
    # -- dev tools -------------------------------------------------------
    "discord_webhook": "discord-webhook",
    "do_mpc": "do-mpc",
    "great_tables": "great-tables",
    "json_repair": "json-repair",
    "pre_commit": "pre-commit",
    "pytest_asyncio": "pytest-asyncio",
    "pytest_cov": "pytest-cov",
    "pytest_mock": "pytest-mock",
    "time_machine": "time-machine",
    # -- science ---------------------------------------------------------
    "chembl_webresource_client": "chembl-webresource-client",
    "hijri_converter": "hijri-converter",
    # -- long-tail renames (r5): harvested from installed-dist metadata
    # (scripts/generate-pypi-map.py --harvest) plus curated well-known
    # import!=dist pairs. Only REAL renames are listed — pip normalizes
    # case/underscore/dash itself, so identity entries add nothing.
    "haiku": "dm-haiku",
    "functorch": "torch",
    "orbax": "orbax-checkpoint",
    "pasta": "google-pasta",
    "xdist": "pytest-xdist",
    "Xlib": "python-xlib",
    "vlc": "python-vlc",
    "apiclient": "google-api-python-client",  # legacy alias still in tutorials
    "z3": "z3-solver",
    "pysat": "python-sat",
    "arango": "python-arango",
    "pulsar": "pulsar-client",
    "stomp": "stomp.py",
    "ldap": "python-ldap",
    "saml2": "pysaml2",
    "onelogin": "python3-saml",
    "mastodon": "Mastodon.py",
    "ax": "ax-platform",
    "skopt": "scikit-optimize",
    "bayes_opt": "bayesian-optimization",
    "graphql": "graphql-core",
    "stdnum": "python-stdnum",
    "doctr": "python-doctr",
    "antlr4": "antlr4-python3-runtime",
    "keystone": "keystone-engine",
    "pwn": "pwntools",
    "miio": "python-miio",
    "kasa": "python-kasa",
    "board": "Adafruit-Blinka",
    "busio": "Adafruit-Blinka",
    "iris": "scitools-iris",
    "allel": "scikit-allel",
    "libarchive": "libarchive-c",
    "lru": "lru-dict",
    "benedict": "python-benedict",
    "telebot": "pyTelegramBotAPI",
    "facebook": "facebook-sdk",
    "atlassian": "atlassian-python-api",
    "trello": "py-trello",
    "shopify": "ShopifyAPI",
    "plaid": "plaid-python",
})

# Names that must never be pip-installed: provided by the OS/image, or aliases
# whose pip name collides with an unrelated/broken dist (reference:
# executor/requirements-skip.txt:1-26). The TPU image additionally pins the
# accelerator stack — auto-install must never clobber jax/libtpu versions
# (SURVEY.md §7 hard part (d)).
SKIP: frozenset[str] = frozenset(
    {
        # accelerator stack — pinned in the image, never reinstall
        # (functorch ships inside torch: its map entry resolves to torch,
        # which must stay pinned, so the import is skipped outright)
        "jax", "jaxlib", "libtpu", "torch", "torch_xla", "functorch",
        "flax", "optax", "orbax", "chex", "haiku", "pallas",
        # OS-package-provided tools that upm-style guessers misattribute.
        # NOT "ffmpeg": that import is a real pip dist (ffmpeg-python) and
        # PYPI_MAP redirects it — skipping here would block the install.
        "pandoc", "magick", "imagemagick",
        # our own runtime
        "bee_code_interpreter_tpu",
    }
)


# PEP 420 namespace packages whose top-level name is NOT an installable
# distribution: truncating "google.protobuf" to "google" would pip-install the
# obsolete `google` dist while the user's import stays broken, so the guesser
# retains one more path component under these prefixes and the map keys on the
# level that actually identifies a distribution.
NAMESPACE_PREFIXES: frozenset[str] = frozenset({
    "google", "google.cloud",
    # azure is a pure PEP-420 namespace: the top level installs nothing and
    # each second-level (or keyvault/mgmt/storage third-level) component is
    # its own distribution, named by the dots→dashes convention the
    # unmapped-namespace fallback already applies (azure.storage.blob →
    # azure-storage-blob).
    "azure", "azure.storage", "azure.keyvault", "azure.mgmt",
    "azure.search", "azure.ai", "azure.data", "azure.communication",
    "azure.monitor", "azure.iot", "azure.synapse",
})


def _retained_name(dotted: str) -> str:
    """Truncate a dotted module path to the map-lookup key: the top-level name,
    extended one level at a time while the prefix is a known namespace."""
    parts = dotted.split(".")
    keep = 1
    while keep < len(parts) and ".".join(parts[:keep]) in NAMESPACE_PREFIXES:
        keep += 1
    return ".".join(parts[:keep])


def guessed_imports(source_code: str) -> set[str]:
    """Module names imported (absolutely) anywhere in the source, truncated to
    the top level — except under namespace packages, where one more component
    is retained (``google.protobuf``, ``google.cloud.storage``)."""
    try:
        tree = ast.parse(source_code)
    except (SyntaxError, ValueError):
        # Best-effort: source ast.parse refuses (ValueError on NUL bytes —
        # which the FILE tokenizer the sandbox actually uses tolerates)
        # guesses nothing rather than failing the execution.
        return set()
    return guessed_imports_from_tree(tree)


def guessed_imports_from_tree(tree: ast.AST) -> set[str]:
    """:func:`guessed_imports` over an already-parsed tree — the edge-side
    analyzer (``analysis/inspect.py``) makes ONE AST pass per submission and
    feeds this from it rather than paying a second parse."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names.update(_retained_name(alias.name) for alias in node.names)
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            if node.module in NAMESPACE_PREFIXES:
                # `from google.cloud import storage` — the imported names are
                # the level that identifies the distribution.
                names.update(
                    _retained_name(f"{node.module}.{alias.name}")
                    for alias in node.names
                )
            else:
                names.add(_retained_name(node.module))
    return names


def guess_dependencies(
    source_code: str,
    preinstalled: frozenset[str] | set[str] = frozenset(),
    extra_skip: frozenset[str] | set[str] = frozenset(),
) -> list[str]:
    """PyPI package names to install before running ``source_code``.

    ``preinstalled`` holds *normalized distribution names* already in the image
    (loaded from requirements.txt like the reference's REQUIREMENTS set,
    executor/server.rs:44-67).
    """
    return dependencies_for_imports(
        guessed_imports(source_code), preinstalled, extra_skip
    )


def dependencies_for_imports(
    imports: set[str],
    preinstalled: frozenset[str] | set[str] = frozenset(),
    extra_skip: frozenset[str] | set[str] = frozenset(),
) -> list[str]:
    """The mapping half of :func:`guess_dependencies`, over an
    already-collected import set (one shared AST pass at the edge)."""
    deps: set[str] = set()
    pre = {_normalize(p) for p in preinstalled}
    for mod in imports:
        top = mod.split(".", 1)[0]
        if top in sys.stdlib_module_names or top in SKIP or top in extra_skip:
            continue
        if mod in NAMESPACE_PREFIXES:
            continue  # bare `import google` — the namespace itself installs nothing
        # Unmapped namespace-package names fall back to dots→dashes, which is
        # the actual convention for e.g. google.cloud.storage → google-cloud-storage.
        pkg = PYPI_MAP.get(mod, mod.replace(".", "-"))
        if _normalize(pkg) in pre or _normalize(mod) in pre:
            continue
        deps.add(pkg)
    return sorted(deps)


def filter_predicted(
    predicted: list[str] | tuple[str, ...],
    preinstalled: frozenset[str] | set[str] = frozenset(),
    extra_skip: frozenset[str] | set[str] = frozenset(),
) -> list[str]:
    """Edge-predicted PyPI package names filtered against THIS sandbox's
    preinstalled/skip sets — the pod-side half of edge dep pre-resolution
    (docs/analysis.md): when the edge already ran the AST scan and shipped
    its prediction with the execute call, the sandbox pays set lookups only,
    never a second parse. The skip list still applies here (defense in
    depth: a prediction must never clobber the pinned accelerator stack),
    and so does THIS interpreter's stdlib: edge and sandbox can run
    different Python versions, and a module that is stdlib HERE but not at
    the edge (telnetlib across the 3.12 removal, say) arrives predicted as
    an identity-mapped package name — installing an arbitrary same-named
    PyPI dist would be a dependency-confusion bug, so it is dropped."""
    pre = {_normalize(p) for p in preinstalled}
    skip = {_normalize(s) for s in SKIP} | {_normalize(s) for s in extra_skip}
    # SKIP names the *imports* of the pinned stack; their mapped dist names
    # (torch, dm-haiku, orbax-checkpoint, …) must be refused too.
    skip |= {
        _normalize(PYPI_MAP[imp]) for imp in SKIP if imp in PYPI_MAP
    }
    stdlib = {name.lower() for name in sys.stdlib_module_names}
    return sorted(
        {
            pkg
            for pkg in predicted
            if _normalize(pkg) not in pre | skip
            and pkg.lower() not in stdlib
            and pkg.lower().replace("-", "_") not in stdlib
        }
    )


def _normalize(name: str) -> str:
    # PEP 503 normalization, plus stripping extras ("pandas[excel]" → "pandas").
    name = name.split("[", 1)[0].strip()
    return name.lower().replace("_", "-").replace(".", "-")


def load_requirements_set(*paths: str | Path) -> frozenset[str]:
    """Preinstalled-requirements set from requirements.txt-style files.

    Strips comments, version specifiers, and extras, mirroring the reference's
    startup loading of /requirements.txt + /requirements-skip.txt
    (executor/server.rs:44-67, 198-201).
    """
    out: set[str] = set()
    for p in paths:
        p = Path(p)
        if not p.exists():
            continue
        for line in p.read_text().splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            for sep in ("==", ">=", "<=", "~=", "!=", ">", "<", ";", "@"):
                line = line.split(sep, 1)[0]
            out.add(_normalize(line))
    return frozenset(out)
