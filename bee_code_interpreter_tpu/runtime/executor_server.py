"""Standalone in-sandbox executor HTTP server (Python implementation).

Serves the executor wire contract on the pod network, identical to the native
C++ server (executor/server.cpp) and to the reference's Rust server
(executor/server.rs:186-192):

- ``PUT  /workspace/{path}``  — stream request body into the workspace
- ``GET  /workspace/{path}``  — stream file back (404 if absent)
- ``POST /execute``           — ``{source_code, env?, timeout?}`` →
                                ``{stdout, stderr, exit_code, files[]}``
- ``GET  /healthz``           — readiness (new; the reference relied solely on
                                k8s pod Ready)

This Python server is (a) the development/test double for the pod HTTP seam —
the fake the reference never had (SURVEY.md §4) — and (b) a fallback pod
entrypoint where the C++ binary isn't built. Run:

    python -m bee_code_interpreter_tpu.runtime.executor_server

Env: APP_LISTEN_ADDR (default 0.0.0.0:8000), APP_WORKSPACE (default
/workspace), APP_REQUIREMENTS / APP_REQUIREMENTS_SKIP (preinstalled-set files,
reference server.rs:198-201), APP_DISABLE_DEP_INSTALL, APP_SHIM_DIR,
APP_LOG_FORMAT (``json`` for structured one-line records).

Observability (docs/observability.md): the control plane sends a W3C
``traceparent`` plus ``X-Request-Id`` on every data-plane call; this server
adopts both — the request id lands on every pod-side log record, the trace
continues under the same trace_id (server-side spans retained in a small
local store), and the id is echoed back in the response headers.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os

from aiohttp import web

from bee_code_interpreter_tpu.observability import (
    REQUEST_ID_HEADER,
    JsonLogFormatter,
    Tracer,
    TraceStore,
    parse_traceparent,
)
from bee_code_interpreter_tpu.runtime.dep_guess import load_requirements_set
from bee_code_interpreter_tpu.runtime.executor_core import ExecutorCore
from bee_code_interpreter_tpu.utils.request_id import (
    RequestIdLoggingFilter,
    request_id_context_var,
)

logger = logging.getLogger(__name__)


def create_app(core: ExecutorCore, tracer: Tracer | None = None) -> web.Application:
    app = web.Application(client_max_size=1 << 30)
    # Pod-local retention only: the edge's store is the one an operator
    # queries; this one exists so in-pod spans/logs still correlate when a
    # pod is inspected directly.
    tracer = tracer or Tracer(store=TraceStore(max_traces=64, slowest_keep=8))

    @web.middleware
    async def trace_context_middleware(request: web.Request, handler):
        rid = request.headers.get(REQUEST_ID_HEADER)
        if rid:
            # Adopt the edge's id: every log record this request produces
            # (dep install, subprocess failures) correlates with the edge.
            request_id_context_var.set(rid)
        ctx = parse_traceparent(request.headers.get("traceparent"))
        if ctx is not None:
            trace_id, parent_span_id = ctx
            with tracer.trace(
                f"executor:{request.path}",
                trace_id=trace_id,
                parent_span_id=parent_span_id,
                request_id=rid,
            ):
                response = await handler(request)
        else:
            response = await handler(request)
        if rid:
            response.headers.setdefault(REQUEST_ID_HEADER, rid)
        return response

    app.middlewares.append(trace_context_middleware)

    async def upload_file(request: web.Request) -> web.Response:
        try:
            path = core.resolve(request.match_info["path"])
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "wb") as f:
            async for chunk in request.content.iter_chunked(1 << 20):
                f.write(chunk)
        return web.Response(status=204)

    async def download_file(request: web.Request) -> web.StreamResponse:
        try:
            path = core.resolve(request.match_info["path"])
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        if not path.is_file():
            return web.Response(status=404)
        return web.FileResponse(path)

    async def delete_file(request: web.Request) -> web.Response:
        """Remove one workspace file (sessions use this for rollback: files
        created after a checkpoint must not survive restoring it). 404 for
        a path that isn't there — callers treat that as already-gone."""
        try:
            path = core.resolve(request.match_info["path"])
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        if not path.is_file():
            return web.Response(status=404)
        path.unlink(missing_ok=True)
        return web.Response(status=204)

    async def execute(request: web.Request) -> web.Response:
        body = await request.json()
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        logger.info("Executing sandboxed code (%d bytes)", len(body["source_code"]))
        outcome = await core.execute(
            source_code=body["source_code"],
            env=body.get("env") or {},
            timeout_s=body.get("timeout"),
            # Edge dep pre-resolution (docs/analysis.md): with a prediction
            # attached, the core skips its own AST scan.
            predicted_deps=body.get("predicted_deps"),
        )
        logger.info("Sandboxed execution finished: exit_code=%s", outcome.exit_code)
        return web.json_response(
            {
                "stdout": outcome.stdout,
                "stderr": outcome.stderr,
                "exit_code": outcome.exit_code,
                "files": outcome.files,
                # additive diagnostic, mirrors the C++ server's field
                "duration_ms": (loop.time() - t0) * 1000,
                # per-execution resource accounting (docs/observability.md):
                # rusage deltas + wall + workspace byte deltas, measured by
                # ExecutorCore; the control-plane driver propagates this
                # into ExecuteResponse.usage.
                "usage": outcome.usage,
            }
        )

    async def execute_stream(request: web.Request) -> web.StreamResponse:
        """Streaming twin of ``POST /execute``: newline-delimited JSON
        events, one per output chunk —

            {"stream": "stdout"|"stderr", "data": "<text>"}\\n

        — closed by a terminal event carrying the exact non-streaming
        envelope (plus ``duration_ms``/``usage``):

            {"event": "end", "stdout": ..., "stderr": ..., "exit_code": ...,
             "files": [...], "duration_ms": ..., "usage": {...}}\\n

        Chunked transfer with per-event flush, so the control plane (and
        through it an SSE client) sees output the moment the sandboxed
        process writes it, not when the run ends."""
        body = await request.json()
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        logger.info(
            "Executing sandboxed code, streaming (%d bytes)",
            len(body["source_code"]),
        )
        response = web.StreamResponse(
            headers={"Content-Type": "application/x-ndjson"}
        )
        response.enable_chunked_encoding()
        await response.prepare(request)
        gen = core.execute_stream(
            source_code=body["source_code"],
            env=body.get("env") or {},
            timeout_s=body.get("timeout"),
            predicted_deps=body.get("predicted_deps"),
        )
        try:
            await _pump_stream(gen, response, loop, t0)
        except ConnectionResetError:
            # The consumer vanished mid-stream: expected (a dead SSE
            # client upstream), not an error worth a traceback — the
            # generator's own finally already reaped the user process.
            logger.info("Stream consumer disconnected mid-execution")
            return response
        finally:
            await gen.aclose()
        await response.write_eof()
        return response

    async def _pump_stream(gen, response, loop, t0: float) -> None:
        async for kind, payload in gen:
            if kind == "end":
                await response.write(
                    json.dumps(
                        {
                            "event": "end",
                            "stdout": payload.stdout,
                            "stderr": payload.stderr,
                            "exit_code": payload.exit_code,
                            "files": payload.files,
                            "duration_ms": (loop.time() - t0) * 1000,
                            "usage": payload.usage,
                        }
                    ).encode()
                    + b"\n"
                )
            else:
                await response.write(
                    json.dumps({"stream": kind, "data": payload}).encode()
                    + b"\n"
                )

    async def healthz(_request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "workspace": str(core.workspace)})

    app.router.add_put("/workspace/{path:.+}", upload_file)
    app.router.add_get("/workspace/{path:.+}", download_file)
    app.router.add_delete("/workspace/{path:.+}", delete_file)
    app.router.add_post("/execute", execute)
    app.router.add_post("/execute/stream", execute_stream)
    app.router.add_get("/healthz", healthz)
    return app


def core_from_env() -> ExecutorCore:
    preinstalled = load_requirements_set(
        os.environ.get("APP_REQUIREMENTS", "/requirements.txt"),
        os.environ.get("APP_REQUIREMENTS_SKIP", "/requirements-skip.txt"),
    )
    return ExecutorCore(
        workspace=os.environ.get("APP_WORKSPACE", "/workspace"),
        preinstalled=preinstalled,
        disable_dep_install=os.environ.get("APP_DISABLE_DEP_INSTALL", "") == "1",
        default_timeout_s=float(os.environ.get("APP_EXECUTION_TIMEOUT_S", "60")),
        shim_dir=os.environ.get("APP_SHIM_DIR") or None,
    )


def setup_logging() -> None:
    """Pod-side logging: request-id/trace-id on every record via the shared
    filter; APP_LOG_FORMAT=json matches the control plane's structured
    schema so both sides of a trace parse with the same pipeline."""
    handler = logging.StreamHandler()
    if os.environ.get("APP_LOG_FORMAT", "").lower() == "json":
        handler.setFormatter(JsonLogFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s [%(levelname)s] [%(request_id)s] "
                "[%(trace_id)s] %(name)s: %(message)s"
            )
        )
    handler.addFilter(RequestIdLoggingFilter())
    root = logging.getLogger()
    root.handlers = [handler]
    root.setLevel(logging.INFO)


def main() -> None:
    setup_logging()
    core = core_from_env()
    listen = os.environ.get("APP_LISTEN_ADDR", "0.0.0.0:8000")
    host, _, port = listen.rpartition(":")
    if os.environ.get("APP_WARMUP", "") == "1":
        asyncio.run(core.warmup())
    web.run_app(create_app(core), host=host or "0.0.0.0", port=int(port))


if __name__ == "__main__":
    main()
