"""Standalone in-sandbox executor HTTP server (Python implementation).

Serves the executor wire contract on the pod network, identical to the native
C++ server (executor/server.cpp) and to the reference's Rust server
(executor/server.rs:186-192):

- ``PUT  /workspace/{path}``  — stream request body into the workspace
- ``GET  /workspace/{path}``  — stream file back (404 if absent)
- ``POST /execute``           — ``{source_code, env?, timeout?}`` →
                                ``{stdout, stderr, exit_code, files[]}``
- ``GET  /healthz``           — readiness (new; the reference relied solely on
                                k8s pod Ready)

This Python server is (a) the development/test double for the pod HTTP seam —
the fake the reference never had (SURVEY.md §4) — and (b) a fallback pod
entrypoint where the C++ binary isn't built. Run:

    python -m bee_code_interpreter_tpu.runtime.executor_server

Env: APP_LISTEN_ADDR (default 0.0.0.0:8000), APP_WORKSPACE (default
/workspace), APP_REQUIREMENTS / APP_REQUIREMENTS_SKIP (preinstalled-set files,
reference server.rs:198-201), APP_DISABLE_DEP_INSTALL, APP_SHIM_DIR.
"""

from __future__ import annotations

import asyncio
import os

from aiohttp import web

from bee_code_interpreter_tpu.runtime.dep_guess import load_requirements_set
from bee_code_interpreter_tpu.runtime.executor_core import ExecutorCore


def create_app(core: ExecutorCore) -> web.Application:
    app = web.Application(client_max_size=1 << 30)

    async def upload_file(request: web.Request) -> web.Response:
        try:
            path = core.resolve(request.match_info["path"])
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "wb") as f:
            async for chunk in request.content.iter_chunked(1 << 20):
                f.write(chunk)
        return web.Response(status=204)

    async def download_file(request: web.Request) -> web.StreamResponse:
        try:
            path = core.resolve(request.match_info["path"])
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        if not path.is_file():
            return web.Response(status=404)
        return web.FileResponse(path)

    async def execute(request: web.Request) -> web.Response:
        body = await request.json()
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        outcome = await core.execute(
            source_code=body["source_code"],
            env=body.get("env") or {},
            timeout_s=body.get("timeout"),
        )
        return web.json_response(
            {
                "stdout": outcome.stdout,
                "stderr": outcome.stderr,
                "exit_code": outcome.exit_code,
                "files": outcome.files,
                # additive diagnostic, mirrors the C++ server's field
                "duration_ms": (loop.time() - t0) * 1000,
            }
        )

    async def healthz(_request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "workspace": str(core.workspace)})

    app.router.add_put("/workspace/{path:.+}", upload_file)
    app.router.add_get("/workspace/{path:.+}", download_file)
    app.router.add_post("/execute", execute)
    app.router.add_get("/healthz", healthz)
    return app


def core_from_env() -> ExecutorCore:
    preinstalled = load_requirements_set(
        os.environ.get("APP_REQUIREMENTS", "/requirements.txt"),
        os.environ.get("APP_REQUIREMENTS_SKIP", "/requirements-skip.txt"),
    )
    return ExecutorCore(
        workspace=os.environ.get("APP_WORKSPACE", "/workspace"),
        preinstalled=preinstalled,
        disable_dep_install=os.environ.get("APP_DISABLE_DEP_INSTALL", "") == "1",
        default_timeout_s=float(os.environ.get("APP_EXECUTION_TIMEOUT_S", "60")),
        shim_dir=os.environ.get("APP_SHIM_DIR") or None,
    )


def main() -> None:
    core = core_from_env()
    listen = os.environ.get("APP_LISTEN_ADDR", "0.0.0.0:8000")
    host, _, port = listen.rpartition(":")
    if os.environ.get("APP_WARMUP", "") == "1":
        asyncio.run(core.warmup())
    web.run_app(create_app(core), host=host or "0.0.0.0", port=int(port))


if __name__ == "__main__":
    main()
