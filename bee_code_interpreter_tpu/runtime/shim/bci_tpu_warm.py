"""Preload module for the TPU executor image: warm XLA client in the sandbox.

Listed in ``APP_PRESTART_IMPORTS`` (executor/Dockerfile) so the pre-started
worker doesn't just import numpy — it brings the pod's TPU all the way up
(jax import, libtpu init, device enumeration, one tiny compiled dispatch)
while the sandbox sits warm in the pool. The pod owns its chips exclusively
and is single-use, so holding the initialized client until the request
arrives wastes nothing — and the request's first ``jax`` (or rerouted numpy)
op starts on a live backend instead of paying multi-second libtpu init.

This realizes SURVEY.md §2's native-checklist item: "keeps a warm XLA client
so first-touch compile latency isn't paid per request".

Trade-off (documented in docs/configuration.md): backend-affecting request
env (e.g. ``JAX_PLATFORMS``) is ignored on the warm path once the backend is
initialized. Deployments that need per-request platform switching should
drop this module from APP_PRESTART_IMPORTS or set APP_PRESTART=0.

Import errors are swallowed by the bootstrap's preload loop, so listing this
module on a host without TPU/jax is harmless.
"""

import jax

# Initialize the backend and keep it held; a trivial dispatch also warms the
# compile/executable caches' hot paths (not any real program's compilation).
_devices = jax.devices()
jax.numpy.zeros(8).block_until_ready()
