"""Sandbox interpreter shim, loaded into every user process via PYTHONPATH.

TPU-native growth of the reference's sitecustomize (executor/sitecustomize.py:
1-31). Keeps the reference's headless-display patches and adds the numpy→XLA
reroute. Everything is installed through one lazy ``__import__`` patch so
interpreter startup stays free: nothing heavy imports until user code itself
imports the module in question.

Patches:
- ``numpy``           → XLA reroute entry points (runtime/xla_reroute.py)
- ``matplotlib.pyplot.show``  → ``savefig("plot.png")``   (headless pods)
- ``PIL`` ``ImageShow.show``  → ``img.save("image.png")``
- ``moviepy`` ``write_videofile``: logger silenced (tqdm noise in stderr)
- ``torch``           → if torch_xla is importable, make "xla" the default
                        device so torch code lands on the TPU too
- ``jax``             → if BCI_PROFILE_DIR is set, capture a jax.profiler
                        trace of the whole run into that directory
"""

import builtins
import sys

_patched = set()
_original_import = builtins.__import__
# True while the image's own (shadowed) sitecustomize executes: imports it
# performs are platform infrastructure (plugin registration often pulls in
# numpy), not the user "importing numpy" — patching then would (a) install the
# reroute before the request env is even visible and (b) wrap numpy for
# processes that never use it. Defer: the module stays in sys.modules and gets
# patched at the first post-site import statement instead.
_deferring = False
# Set for real once the shadowed sitecustomize (if any) is located, below;
# must exist before the __import__ patch is installed.
_chain_pending = False
_chain_finder = None

import threading as _threading

_chain_lock = _threading.Lock()


def _patch_numpy(numpy):
    try:
        try:
            from bee_code_interpreter_tpu.runtime import xla_reroute
        except ImportError:
            # Sandbox interpreters get only this shim dir on PYTHONPATH; the
            # shim ships inside the package tree (…/bee_code_interpreter_tpu/
            # runtime/shim/sitecustomize.py), so the directory *containing* the
            # package is four dirname()s up from this file.
            import os

            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
            if root not in sys.path:
                sys.path.append(root)
            from bee_code_interpreter_tpu.runtime import xla_reroute

        xla_reroute.install(numpy)
    except Exception:
        pass


def _patch_pyplot(pyplot):
    def show(*_args, **_kwargs):
        pyplot.savefig("plot.png")

    pyplot.show = show


def _patch_pil(image_show):
    def show(img, *_args, **_kwargs):
        img.save("image.png")
        return True

    image_show.show = show


def _patch_moviepy_editor(editor):
    try:
        original = editor.VideoClip.write_videofile

        def write_videofile(self, *args, **kwargs):
            kwargs.setdefault("logger", None)
            return original(self, *args, **kwargs)

        editor.VideoClip.write_videofile = write_videofile
    except Exception:
        pass


def _patch_torch(torch):
    try:
        import torch_xla.core.xla_model as xm  # noqa: F401

        torch.set_default_device("xla")
    except Exception:
        pass  # CPU torch stays CPU torch


def _patch_jax_profiler(jax):
    """BCI_PROFILE_DIR=<dir> captures a jax.profiler trace of the whole run.

    The trace starts when user code first imports jax and stops at interpreter
    exit; written under the workspace it rides the executor's changed-file
    snapshot back to the client (SURVEY.md §5 "add jax.profiler trace capture
    endpoints in the sandbox") — no separate download channel needed.
    """
    import atexit
    import os

    trace_dir = os.environ.get("BCI_PROFILE_DIR")
    if not trace_dir:
        return
    jax.profiler.start_trace(trace_dir)

    def _stop():
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass

    atexit.register(_stop)


_PATCHES = {
    "numpy": _patch_numpy,
    "matplotlib.pyplot": _patch_pyplot,
    "PIL.ImageShow": _patch_pil,
    "moviepy.editor": _patch_moviepy_editor,
    "torch": _patch_torch,
    "jax": _patch_jax_profiler,
}


# Accelerator-adjacent top-level imports that must see the image's own site
# hooks (PJRT plugin registration) before they initialize. Anything else
# (numpy, pandas, requests, …) runs fine without them — which is what makes
# the deferred chain safe.
_CHAIN_TRIGGERS = {
    "jax", "jaxlib", "flax", "optax", "orbax", "torch", "torch_xla",
    "tensorflow", "axon",
}


class _ChainTriggerFinder:
    """Meta-path tripwire: fire the deferred chain on the first attempt to
    import an accelerator library, whatever the import mechanism — a meta
    importer sees importlib.import_module and entry-point loaders too,
    which a builtins.__import__ patch alone would miss. Never provides a
    module itself (find_spec always defers to the real finders)."""

    def find_spec(self, fullname, path=None, target=None):
        if (
            _chain_pending
            and not _deferring
            and fullname.partition(".")[0] in _CHAIN_TRIGGERS
        ):
            _exec_chained_sitecustomize()
        return None


def _import(name, globals=None, locals=None, fromlist=(), level=0):
    module = _original_import(name, globals, locals, fromlist, level)
    if _deferring:
        return module
    for target, patch in _PATCHES.items():
        if target in _patched or target not in sys.modules:
            continue
        candidate = sys.modules[target]
        # Don't touch a module that is still executing its own package init
        # (sys.modules holds partially-initialized modules during import) —
        # patches applied then would be overwritten by the init itself.
        spec = getattr(candidate, "__spec__", None)
        if spec is not None and getattr(spec, "_initializing", False):
            continue
        _patched.add(target)
        try:
            patch(candidate)
        except Exception:
            pass
    return module


builtins.__import__ = _import


def _find_next_sitecustomize():
    """Path of the next sitecustomize.py further down sys.path, if any.

    Python imports only the *first* sitecustomize it finds; since this shim
    is prepended to PYTHONPATH it would otherwise shadow the sandbox image's
    own site hooks (e.g. the PJRT/TPU plugin registration some images
    perform there). Cooperate instead of replacing."""
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    for entry in sys.path:
        try:
            candidate = os.path.join(entry or ".", "sitecustomize.py")
            if os.path.abspath(os.path.dirname(candidate)) == here:
                continue
            if not os.path.isfile(candidate):
                continue
        except OSError:
            continue
        # abspath NOW: relative sys.path entries must not break the chain
        # after user code chdirs before its first accelerator import
        return os.path.abspath(candidate)
    return None


_chain_path = _find_next_sitecustomize()
_chain_pending = _chain_path is not None
_chain_finder = None
if _chain_pending:
    _chain_finder = _ChainTriggerFinder()
    sys.meta_path.insert(0, _chain_finder)


def _exec_chained_sitecustomize():
    global _deferring, _chain_pending
    with _chain_lock:
        # re-check under the lock: two threads importing different
        # accelerator libs concurrently must not run the chain twice
        # (duplicate PJRT registration / atexit hooks)
        if not _chain_pending:
            return
        _chain_pending = False
        import importlib.util

        try:
            _deferring = True
            spec = importlib.util.spec_from_file_location(
                "_chained_sitecustomize", _chain_path
            )
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)
        except Exception:
            pass
        finally:
            _deferring = False
    if _chain_finder is not None:
        try:
            sys.meta_path.remove(_chain_finder)
        except ValueError:
            pass


# The image's site hooks exist to prime accelerator plugins — work worth
# ~1 s of jax import in this image's case. Paying that on EVERY interpreter
# start taxes the pool-refill rate (and with it warm latency) for the many
# payloads that never touch an accelerator, so by default the chain is
# DEFERRED to the first accelerator-adjacent import (see _CHAIN_TRIGGERS in
# _import). BCI_EAGER_CHAIN=1 restores start-time chaining for images whose
# hooks do more than accelerator setup.
import os as _os

if _chain_pending and _os.environ.get("BCI_EAGER_CHAIN") == "1":
    _exec_chained_sitecustomize()
