"""Transparent numpy → XLA rerouting for LLM-submitted code.

The TPU-native growth of the reference's 31-line sitecustomize display shim
(executor/sitecustomize.py:6-31; SURVEY.md §2: "grows ... into the
numpy/torch→XLA rerouting layer"). User code keeps writing plain numpy; dense
compute transparently lands on the attached TPU:

- **Entry points**: the handful of numpy APIs where the FLOPs are — matmul,
  dot, einsum, tensordot, and the big elementwise/reduction producers — are
  wrapped. When an input crosses a size threshold (default 1M elements) and
  dtypes are XLA-friendly, the op executes via jax.numpy on the default device
  and returns a ``TpuArray``.
- **Stickiness**: ``TpuArray`` implements ``__array_function__`` and
  ``__array_ufunc__``, so *subsequent* numpy calls on it (np.sum, np.exp,
  np.mean, arithmetic, comparisons, slicing) dispatch straight to jax.numpy and
  stay on device — chains like ``np.sum(np.square(x))`` run fused on TPU
  without bouncing through host memory.
- **Graceful fallback** (SURVEY.md §7 hard part (b)): anything that needs a
  real ndarray — pandas, scipy, file I/O, ``np.asarray``, unknown numpy
  functions — hits ``__array__`` and materializes to host numpy transparently.
  Small arrays never leave numpy in the first place.

Nothing here imports jax at interpreter startup: wrappers are installed by an
import hook (see shim/sitecustomize.py) and jax loads lazily on the first
large-array hit. Set ``BCI_XLA_REROUTE=0`` to disable, or
``BCI_XLA_REROUTE_MIN_ELEMS`` to tune the threshold. Both are re-read at
**call time**, not only at install time: a warm (pre-started) sandbox installs
the proxies before the request env is applied, and user code that sets the
flag after numpy is already imported must still get the documented opt-out.

The first device placement is guarded by a backend-init watchdog
(``BCI_XLA_INIT_TIMEOUT_S``, default 30s): if jax's backend cannot come up in
time — e.g. a platform plugin blocking on an unreachable accelerator tunnel —
the reroute permanently falls back to host numpy instead of hanging the user's
script. That IS the module's "graceful fallback" promise applied to the
backend itself.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

_DEFAULT_MIN_ELEMS = 1 << 20

_jnp = None
_np = None


def _enabled() -> bool:
    """Per-call opt-out check — see module docstring for why not install-time."""
    return os.environ.get("BCI_XLA_REROUTE", "1") != "0"


def _min_elems() -> int:
    raw = os.environ.get("BCI_XLA_REROUTE_MIN_ELEMS")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return _DEFAULT_MIN_ELEMS


def _jax_numpy():
    global _jnp
    if _jnp is None:
        # jax's import chain (ml_dtypes) registers custom dtypes against the
        # *real* numpy ufuncs; importing it with our proxies installed breaks
        # that C-level registration. Restore originals around the import.
        with _pristine_numpy():
            import jax.numpy as jnp

        _jnp = jnp
    return _jnp


def _numpy():
    global _np
    if _np is None:
        import numpy as np

        _np = np
    return _np


_REROUTE_DTYPES = frozenset(
    {"float16", "float32", "float64", "bfloat16", "int8", "int16", "int32",
     "int64", "uint8", "uint32", "bool", "complex64"}
)


def _eligible(value: Any) -> bool:
    np = _numpy()
    return (
        isinstance(value, np.ndarray)
        and value.size >= _min_elems()
        and str(value.dtype) in _REROUTE_DTYPES
    )


# None = not yet probed, True = backend usable, False = init failed/timed out
# (reroute then stays on host numpy for the life of the process).
_backend_state: bool | None = None
_backend_lock = threading.Lock()


def _backend_ok() -> bool:
    """One-time watchdogged jax backend probe.

    jax backend init is the one step the reroute cannot survive failing
    mid-expression: a platform plugin that hooks init and blocks on an
    unreachable device (observed: a TPU tunnel plugin activating even under
    JAX_PLATFORMS=cpu) would turn "transparent acceleration" into a silent
    multi-minute hang. Probe it once on a daemon thread with a deadline; on
    timeout or error, disable rerouting permanently and let every entry point
    fall through to host numpy.
    """
    global _backend_state
    if _backend_state is not None:
        return _backend_state
    with _backend_lock:
        if _backend_state is not None:
            return _backend_state
        # Default 30s: comfortably above a healthy cold TPU init (~10-20s)
        # but well under the default 60s execution timeout, so a wedged
        # backend still leaves the user's script time to finish on host.
        try:
            timeout_s = float(os.environ.get("BCI_XLA_INIT_TIMEOUT_S", "30"))
        except ValueError:
            timeout_s = 30.0
        outcome: list[bool] = []

        def probe() -> None:
            try:
                # jax's import chain registers dtypes against the *real*
                # numpy entry points (see _jax_numpy) — this probe is usually
                # the process's first jax import, so the same pristine guard
                # applies here.
                with _pristine_numpy():
                    import jax

                    jax.devices()
                outcome.append(True)
            except Exception:
                outcome.append(False)

        thread = threading.Thread(
            target=probe, name="bci-xla-init-probe", daemon=True
        )
        thread.start()
        thread.join(timeout_s)
        _backend_state = bool(outcome and outcome[0])
    return _backend_state


def _to_device(value: Any):
    import jax

    return jax.device_put(value)


class TpuArray:
    """A device-resident array that keeps numpy code on the TPU.

    Wraps a jax.Array. numpy protocol hooks dispatch numpy API calls to
    jax.numpy by name; materialization happens only when host data is truly
    needed (``__array__``).
    """

    __slots__ = ("_jax",)
    # Higher than numpy's default so our protocol hooks win.
    __array_priority__ = 200

    def __init__(self, jax_array) -> None:
        self._jax = jax_array

    # -- introspection ----------------------------------------------------
    @property
    def shape(self):
        return self._jax.shape

    @property
    def dtype(self):
        return self._jax.dtype

    @property
    def ndim(self):
        return self._jax.ndim

    @property
    def size(self):
        return self._jax.size

    @property
    def T(self):
        return TpuArray(self._jax.T)

    @property
    def jax_array(self):
        """The underlying jax.Array, for code that wants to go native."""
        return self._jax

    @property
    def device(self):
        # Array-API device probe (numpy 2.x ndarray.device == "cpu"). scipy's
        # array-api-compat reads this on hypothesis-test results and feeds it
        # back into numpy-namespace asarray(..., device=...); reporting the
        # host view keeps that interop path working (SURVEY.md §7 hard part b:
        # reroute must not break pandas/scipy).
        return "cpu"

    def to_device(self, device, /, *, stream=None):
        if device == "cpu":
            return self
        raise ValueError(f"unsupported device: {device!r}")

    def __repr__(self):
        # Human output renders like numpy (pandas/print paths call str/repr on
        # cell objects); materializing here is fine — repr is for humans.
        return repr(self._jax.item()) if self._jax.ndim == 0 else repr(self.__array__())

    def __str__(self):
        return str(self._jax.item()) if self._jax.ndim == 0 else str(self.__array__())

    def __format__(self, spec):
        value = self._jax.item() if self._jax.ndim == 0 else self.__array__()
        return format(value, spec)

    def __len__(self):
        return self._jax.shape[0] if self._jax.ndim else 0

    # -- materialization (the graceful-fallback path) ---------------------
    def __array__(self, dtype=None, copy=None):
        host = _numpy().asarray(self._jax)
        return host.astype(dtype) if dtype is not None else host

    def __float__(self):
        return float(self._jax)

    def __int__(self):
        return int(self._jax)

    def __bool__(self):
        return bool(self._jax)

    def __iter__(self):
        return iter(_numpy().asarray(self._jax))

    def astype(self, dtype):
        return TpuArray(self._jax.astype(dtype))

    def item(self):
        return self._jax.item()

    # numpy ndarray conveniences used pervasively by user code
    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return TpuArray(self._jax.reshape(shape))

    def sum(self, *args, **kwargs):
        return _wrap(self._jax.sum(*args, **kwargs))

    def mean(self, *args, **kwargs):
        return _wrap(self._jax.mean(*args, **kwargs))

    def max(self, *args, **kwargs):
        return _wrap(self._jax.max(*args, **kwargs))

    def min(self, *args, **kwargs):
        return _wrap(self._jax.min(*args, **kwargs))

    def transpose(self, *axes):
        return TpuArray(self._jax.transpose(*axes))

    def copy(self):
        return TpuArray(self._jax)

    def __getitem__(self, idx):
        return _wrap(self._jax[_unwrap(idx)])

    # -- numpy protocol hooks: ops on TpuArray stay on device -------------
    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        fn = (
            getattr(_jax_numpy(), ufunc.__name__, None)
            if method == "__call__"
            else None
        )
        if fn is not None and kwargs.get("out") is None:
            return _wrap(fn(*map(_unwrap, inputs), **kwargs))
        # Graceful CPU fallback (SURVEY.md §7 hard part b): ufuncs with no
        # jax.numpy equivalent (e.g. scipy.special.stdtr), reduce/accumulate
        # forms, and out= targets run on host views. Materializing here (not
        # returning NotImplemented) matters: numpy defers to TpuArray's higher
        # __array_priority__, so bailing would poison the whole expression.
        out = kwargs.get("out")
        if out is not None and any(isinstance(o, TpuArray) for o in out):
            return NotImplemented  # jax arrays are immutable; no in-place target
        if method == "at":
            # np.add.at(x, idx, v) mutates x in place; a host view of a device
            # array would swallow (or, where the view aliases the buffer,
            # corrupt) the update. Refuse loudly instead.
            return NotImplemented
        np = _numpy()
        host_inputs = [
            np.asarray(x) if isinstance(x, TpuArray) else x for x in inputs
        ]
        return getattr(ufunc, method)(*host_inputs, **kwargs)

    def __array_function__(self, func, types, args, kwargs):
        jnp = _jax_numpy()
        # resolve e.g. numpy.linalg.norm -> jax.numpy.linalg.norm
        module = func.__module__ or "numpy"
        target = jnp
        for part in module.split(".")[1:]:
            target = getattr(target, part, None)
            if target is None:
                return NotImplemented
        fn = getattr(target, func.__name__, None)
        if fn is None:
            return NotImplemented
        try:
            return _wrap(fn(*_unwrap_tree(args), **_unwrap_tree(kwargs)))
        except (TypeError, NotImplementedError):
            return NotImplemented


def _unwrap(value):
    return value._jax if isinstance(value, TpuArray) else value


def _unwrap_tree(value):
    if isinstance(value, TpuArray):
        return value._jax
    if isinstance(value, (list, tuple)):
        return type(value)(_unwrap_tree(v) for v in value)
    if isinstance(value, dict):
        return {k: _unwrap_tree(v) for k, v in value.items()}
    return value


def _wrap(value):
    # jax.Array results stay wrapped; everything else passes through
    import jax

    if isinstance(value, jax.Array):
        return TpuArray(value)
    if isinstance(value, (list, tuple)):
        return type(value)(_wrap(v) for v in value)
    return value


# -- arithmetic dunders (generated) ---------------------------------------

def _binop(name: str, jnp_name: str, reflected: bool = False):
    def op(self, other):
        jnp = _jax_numpy()
        fn = getattr(jnp, jnp_name)
        a, b = (_unwrap(other), self._jax) if reflected else (self._jax, _unwrap(other))
        try:
            return _wrap(fn(a, b))
        except TypeError:
            return NotImplemented

    op.__name__ = name
    return op


for _name, _jnp_name in [
    ("add", "add"), ("sub", "subtract"), ("mul", "multiply"),
    ("truediv", "true_divide"), ("floordiv", "floor_divide"), ("mod", "mod"),
    ("pow", "power"), ("matmul", "matmul"),
]:
    setattr(TpuArray, f"__{_name}__", _binop(f"__{_name}__", _jnp_name))
    setattr(TpuArray, f"__r{_name}__", _binop(f"__r{_name}__", _jnp_name, reflected=True))

for _name, _jnp_name in [
    ("lt", "less"), ("le", "less_equal"), ("gt", "greater"),
    ("ge", "greater_equal"), ("eq", "equal"), ("ne", "not_equal"),
]:
    setattr(TpuArray, f"__{_name}__", _binop(f"__{_name}__", _jnp_name))

TpuArray.__neg__ = lambda self: _wrap(_jax_numpy().negative(self._jax))
TpuArray.__abs__ = lambda self: _wrap(_jax_numpy().abs(self._jax))


# -- numpy entry-point patching -------------------------------------------

# numpy-namespace callables wrapped as reroute entry points.
#
# CONSTRAINT: never proxy a ufunc object (np.add, np.square, np.matmul, ...).
# ml_dtypes — imported by jax — registers bfloat16 loops directly on those C
# objects at import time; replacing them in the numpy namespace breaks any
# later `import jax` with "ufunc add takes N arguments". Instead:
#
# - non-ufunc compute/reduction functions are proxied (safe: plain callables)
# - array *creation* is the on-ramp: a big host array gets device-placed and
#   wrapped, after which every ufunc chain (np.square, np.exp, +, @, ...)
#   dispatches through TpuArray.__array_ufunc__ and stays on device without
#   the numpy namespace ever being touched.
ENTRY_POINTS = (
    "dot", "einsum", "tensordot", "inner", "vdot",
    "sum", "mean", "std", "var", "prod",
)

# Creation functions wrapped so large results start life on the TPU. Random
# values are generated by host numpy first (identical RNG semantics, one h2d
# transfer), shape/fill creations go straight to the device.
CREATION_FUNCS = ("zeros", "ones", "full", "arange", "linspace")
RANDOM_FUNCS = ("rand", "randn", "random", "uniform", "standard_normal")


class _EntryProxy:
    """Callable proxy over a numpy function/ufunc.

    Calls with a large-array operand reroute to jax.numpy; everything else —
    including attribute access like ``np.add.reduce``, ``np.square.types``,
    ``np.matmul.at`` that third-party libraries rely on — forwards to the
    original object untouched.
    """

    __slots__ = ("__wrapped__", "_name")

    def __init__(self, original, name: str) -> None:
        object.__setattr__(self, "__wrapped__", original)
        object.__setattr__(self, "_name", name)

    def __call__(self, *args, **kwargs):
        # _backend_ok() last: small/ineligible calls must never pay (or hang
        # on) backend init, and a disabled reroute must not probe at all.
        if (
            _enabled()
            and any(_eligible(a) for a in args)
            and not kwargs.get("out")
            and _backend_ok()
        ):
            fn = getattr(_jax_numpy(), self._name, None)
            if fn is not None:
                try:
                    moved = [
                        _to_device(a) if _eligible(a) else _unwrap(a) for a in args
                    ]
                    return _wrap(fn(*moved, **_unwrap_tree(kwargs)))
                except Exception:
                    pass  # fall back to host numpy below
        return self.__wrapped__(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__wrapped__, name)

    def __repr__(self):
        return repr(self.__wrapped__)

    # class attributes (docstring, class name) shadow __getattr__; forward the
    # introspection attrs explicitly — numpy.ma parses np.<fn>.__doc__ at init
    @property
    def __doc__(self):  # type: ignore[override]
        return self.__wrapped__.__doc__

    @property
    def __name__(self):
        return getattr(self.__wrapped__, "__name__", self._name)


import contextlib


@contextlib.contextmanager
def _pristine_numpy():
    """Temporarily restore the original numpy entry points."""
    np = _np
    if np is None or not getattr(np, "__bci_xla_rerouted__", False):
        yield
        return
    saved = {}
    for name in ENTRY_POINTS:
        current = getattr(np, name, None)
        if isinstance(current, _EntryProxy):
            saved[name] = current
            setattr(np, name, current.__wrapped__)
    try:
        yield
    finally:
        for name, proxy in saved.items():
            setattr(np, name, proxy)


class _CreationProxy:
    """Wraps an array-creation function: big results start life on the TPU."""

    __slots__ = ("__wrapped__", "_host_first")

    def __init__(self, original, host_first: bool) -> None:
        object.__setattr__(self, "__wrapped__", original)
        # host_first: run the original (RNG semantics!) then device-place;
        # otherwise the result is value-deterministic and the wrap is free.
        object.__setattr__(self, "_host_first", host_first)

    def __call__(self, *args, **kwargs):
        host = self.__wrapped__(*args, **kwargs)
        if _enabled() and _eligible(host) and _backend_ok():
            try:
                return TpuArray(_to_device(host))
            except Exception:
                pass
        return host

    def __getattr__(self, name):
        return getattr(self.__wrapped__, name)

    def __repr__(self):
        return repr(self.__wrapped__)

    @property
    def __doc__(self):  # type: ignore[override]
        return self.__wrapped__.__doc__

    @property
    def __name__(self):
        return getattr(self.__wrapped__, "__name__", "creation")



def install(numpy_module=None) -> bool:
    """Patch the numpy module's entry points. Idempotent. Returns success.

    Note the proxies re-check ``BCI_XLA_REROUTE`` on every call, so installing
    while the flag is off would be harmless — but honoring it here too keeps
    the explicitly-opted-out interpreter entirely proxy-free.
    """
    if not _enabled():
        return False
    np = numpy_module
    if np is None:
        import numpy as np
    global _np
    _np = np
    if getattr(np, "__bci_xla_rerouted__", False):
        return True
    for name in ENTRY_POINTS:
        original = getattr(np, name, None)
        if original is None or isinstance(original, _EntryProxy):
            continue
        if isinstance(original, np.ufunc):  # see ENTRY_POINTS constraint
            continue
        setattr(np, name, _EntryProxy(original, name))
    for name in CREATION_FUNCS:
        original = getattr(np, name, None)
        if original is not None and not isinstance(original, (_CreationProxy, np.ufunc)):
            setattr(np, name, _CreationProxy(original, host_first=False))
    random_module = getattr(np, "random", None)
    if random_module is not None:
        for name in RANDOM_FUNCS:
            original = getattr(random_module, name, None)
            if original is not None and not isinstance(original, _CreationProxy):
                setattr(random_module, name, _CreationProxy(original, host_first=True))
    np.__bci_xla_rerouted__ = True
    return True


def uninstall(numpy_module=None) -> None:
    """Restore every proxied numpy entry point to the original callable.

    The complement ``install()`` never had: a warm sandbox whose request env
    sets ``BCI_XLA_REROUTE=0`` can now fully de-proxy numpy (the bootstrap
    calls this after applying the request env) instead of relying solely on
    the proxies' per-call flag check.
    """
    np = numpy_module
    if np is None:
        np = _np
    if np is None:
        import sys

        np = sys.modules.get("numpy")
    if np is None or not getattr(np, "__bci_xla_rerouted__", False):
        return
    for name in ENTRY_POINTS + CREATION_FUNCS:
        current = getattr(np, name, None)
        if isinstance(current, (_EntryProxy, _CreationProxy)):
            setattr(np, name, current.__wrapped__)
    random_module = getattr(np, "random", None)
    if random_module is not None:
        for name in RANDOM_FUNCS:
            current = getattr(random_module, name, None)
            if isinstance(current, _CreationProxy):
                setattr(random_module, name, current.__wrapped__)
    np.__bci_xla_rerouted__ = False
