"""Liveness probe: asserts the full execute path end-to-end over gRPC.

Reference: health_check.py:25-53 — Execute("print(21 * 2)") must return stdout
"42\\n". Used as the k8s liveness command and as the gate before the e2e suite.

The seed made a single 120 s attempt with no connect timeout, so a probe
against a booting (or dead) service either hung or died with a raw traceback.
Now: a per-attempt deadline (``--timeout``), retry-with-backoff on transient
gRPC statuses (``UNAVAILABLE`` — connection refused/reset — and
``DEADLINE_EXCEEDED``), and a clear nonzero-exit message when the service
stays unreachable. ``--verbose`` additionally fetches the deep-health view
(``GET /healthz?verbose=1`` on the HTTP listener: pool occupancy, breaker
states, fleet aggregates, SLO state — docs/observability.md), prints it,
and exits ``4`` when a fast-window SLO burn-rate alert is firing — alive,
but spending error budget at page rate.

``--router`` probes a fleet-router edge (docs/fleet.md) instead: ``addr`` is
the router's HTTP listener, the probe reads ``GET /v1/fleet/replicas``, and
the exit reuses the same ladder — ``2`` when any replica is unreachable
(dead), ``3`` when replicas are draining (and none dead), ``0`` when every
replica is healthy.

    python -m bee_code_interpreter_tpu.health_check [addr] \\
        [--timeout S] [--attempts N] [--backoff S] \\
        [--verbose] [--http-addr HOST:PORT] [--router]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

import grpc.aio
import httpx

from bee_code_interpreter_tpu.api.grpc_server import service_stubs
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
from bee_code_interpreter_tpu.resilience import RetryPolicy

RETRYABLE_STATUS = (grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.DEADLINE_EXCEEDED)

# Exit codes: 1 wrong answer, 2 unreachable/unhealthy, 3 draining, 4 SLO
# fast-burn warning (--verbose only). The distinct draining code lets k8s
# preStop / deploy tooling tell "finishing up, don't restart me" from
# "dead, restart me"; the SLO code never fires on the bare probe k8s runs,
# so readiness stays green while operators see budget exhaustion early.
DRAINING_EXIT = 3
SLO_BURN_EXIT = 4


def is_draining(verbose_body: dict) -> bool:
    """True when the deep-health view says the service is in graceful drain
    (``GET /healthz?verbose=1`` → ``{"status": "draining", ...}``)."""
    return verbose_body.get("status") == "draining"


class ServiceDraining(Exception):
    """The probe target is in graceful drain (alive, rejecting new work)."""

    def __init__(self, body: dict) -> None:
        super().__init__("service is draining")
        self.body = body


def _channel(addr: str) -> grpc.aio.Channel:
    cert = os.environ.get("APP_GRPC_TLS_CERT")
    key = os.environ.get("APP_GRPC_TLS_CERT_KEY")
    ca = os.environ.get("APP_GRPC_TLS_CA_CERT")
    if cert and key:
        creds = grpc.ssl_channel_credentials(
            root_certificates=ca.encode() if ca else None,
            private_key=key.encode(),
            certificate_chain=cert.encode(),
        )
        return grpc.aio.secure_channel(addr, creds)
    return grpc.aio.insecure_channel(addr)


async def _attempt(addr: str, timeout: float) -> None:
    async with _channel(addr) as channel:
        stubs = service_stubs(channel)
        # The RPC deadline doubles as the connect timeout: a dead endpoint
        # fails the attempt instead of hanging the probe.
        response = await stubs["Execute"](
            pb.ExecuteRequest(source_code="print(21 * 2)"), timeout=timeout
        )
    assert response.stdout == "42\n", f"unexpected stdout: {response.stdout!r}"
    assert response.exit_code == 0, f"unexpected exit code: {response.exit_code}"


async def check(
    addr: str,
    timeout: float = 120.0,
    attempts: int = 3,
    backoff: float = 2.0,
    http_addr: str | None = None,
) -> None:
    policy = RetryPolicy(attempts=attempts, wait_min_s=backoff, wait_max_s=backoff * 8)
    last: grpc.aio.AioRpcError | None = None
    for attempt in range(1, attempts + 1):
        try:
            await _attempt(addr, timeout)
            return
        except grpc.aio.AioRpcError as e:
            if e.code() not in RETRYABLE_STATUS:
                raise
            if e.code() is grpc.StatusCode.UNAVAILABLE and http_addr:
                # A draining replica answers UNAVAILABLE deterministically:
                # retrying just burns the whole backoff budget during every
                # rolling restart. Ask the deep-health view once, now.
                try:
                    body = await verbose_health(http_addr, timeout=5.0)
                except Exception:
                    body = {}
                if is_draining(body):
                    raise ServiceDraining(body) from e
            last = e
            if attempt < attempts:
                sleep_s = policy.backoff_s(attempt)
                print(
                    f"attempt {attempt}/{attempts}: gRPC {e.code().name} "
                    f"({e.details()}); retrying in {sleep_s:g}s",
                    file=sys.stderr,
                )
                await asyncio.sleep(sleep_s)
    assert last is not None
    raise last


def _connectable(listen: str) -> str:
    """A listen address as something the probe can dial: wildcard binds
    mapped to localhost."""
    host, _, port = listen.rpartition(":")
    if host in ("", "0.0.0.0", "::", "[::]"):
        host = "localhost"
    return f"{host}:{port}"


def _default_http_addr() -> str:
    """The service's own HTTP listener config (APP_HTTP_LISTEN_ADDR — the
    same env the service reads)."""
    return _connectable(os.environ.get("APP_HTTP_LISTEN_ADDR", "localhost:50081"))


def _default_router_addr() -> str:
    """The router's own listener config (APP_ROUTER_LISTEN_ADDR — the same
    env ``python -m bee_code_interpreter_tpu.fleet`` reads), so a bare
    ``--router`` probe inside the router pod dials the right port."""
    return _connectable(
        os.environ.get("APP_ROUTER_LISTEN_ADDR", "localhost:50080")
    )


def assess_router(body: dict) -> tuple[int, str]:
    """The ``--router`` verdict from a ``GET /v1/fleet/replicas`` document:
    ``(exit_code, message)`` on the standard ladder — dead replicas beat
    draining ones; an empty fleet is dead by definition."""
    replicas = body.get("replicas") or []
    dead = sorted(r["name"] for r in replicas if r.get("state") == "dead")
    draining = sorted(
        r["name"] for r in replicas if r.get("state") == "draining"
    )
    healthy = sorted(
        r["name"] for r in replicas if r.get("state") == "healthy"
    )
    if dead:
        return 2, (
            f"UNHEALTHY: {len(dead)}/{len(replicas)} replica(s) "
            f"unreachable: {', '.join(dead)}"
        )
    if not healthy:
        return 2, "UNHEALTHY: router has no healthy replicas"
    if draining:
        return DRAINING_EXIT, (
            f"DRAINING: replica(s) in graceful drain: {', '.join(draining)}"
        )
    return 0, f"healthy ({len(healthy)} replica(s))"


async def router_replicas(http_addr: str, timeout: float = 10.0) -> dict:
    """The router's ``GET /v1/fleet/replicas`` document."""
    async with httpx.AsyncClient(timeout=timeout) as client:
        response = await client.get(f"http://{http_addr}/v1/fleet/replicas")
        response.raise_for_status()
        return response.json()


async def router_slo(http_addr: str, timeout: float = 10.0) -> dict | None:
    """The router's federated ``GET /v1/slo`` document, or ``None`` when the
    surface is unreachable — the burn check is an add-on to the reachability
    verdict, never the reason the probe itself errors out."""
    try:
        async with httpx.AsyncClient(timeout=timeout) as client:
            response = await client.get(f"http://{http_addr}/v1/slo")
            response.raise_for_status()
            body = response.json()
            return body if isinstance(body, dict) else None
    except Exception:
        return None


def assess_router_burn(slo: dict | None) -> tuple[int, str | None]:
    """The fleet SLO-burn verdict layered on a clean reachability check
    (``slo-report.py``'s page semantics): the router's own user-perceived
    fast-burn pages, and so does any single replica's (``fleet_fast_burn``
    rollup) — a replica can burn its budget while retries keep the edge
    numbers clean."""
    if not slo:
        return 0, None
    if slo.get("fast_burn_alerting"):
        return SLO_BURN_EXIT, (
            "SLO BURN: router edge fast-burn page is firing "
            "(user-perceived error budget)"
        )
    if slo.get("fleet_fast_burn"):
        burning = sorted(
            name
            for name, doc in (slo.get("fleet") or {}).items()
            if isinstance(doc, dict) and doc.get("fast_burn_alerting")
        )
        return SLO_BURN_EXIT, (
            "SLO BURN: replica fast-burn page is firing: "
            f"{', '.join(burning) or 'unknown'}"
        )
    return 0, None


def router_main(args) -> None:
    try:
        body = asyncio.run(
            router_replicas(args.addr, timeout=min(args.timeout, 15.0))
        )
    except Exception as e:
        print(
            f"UNHEALTHY: fleet router at {args.addr} unreachable: {e}",
            file=sys.stderr,
        )
        sys.exit(2)
    code, message = assess_router(body)
    if code == 0:
        # Reachable and routable — but a firing fast-burn page still makes
        # the probe red (exit 4, the same code slo-report.py pages with).
        slo = asyncio.run(router_slo(args.addr, timeout=min(args.timeout, 15.0)))
        burn_code, burn_message = assess_router_burn(slo)
        if burn_code:
            code, message = burn_code, burn_message
    print(message, file=sys.stderr if code else sys.stdout)
    if args.verbose:
        print(json.dumps(body, indent=2))
    sys.exit(code)


async def verbose_health(http_addr: str, timeout: float = 10.0) -> dict:
    """The deep-health JSON from ``GET /healthz?verbose=1``."""
    async with httpx.AsyncClient(timeout=timeout) as client:
        response = await client.get(
            f"http://{http_addr}/healthz", params={"verbose": "1"}
        )
        response.raise_for_status()
        return response.json()


def main() -> None:
    parser = argparse.ArgumentParser(
        description="End-to-end gRPC health check (Execute must return 42)."
    )
    # Resolved after parsing: the right default depends on --router (the
    # router's HTTP listener, not the replica's gRPC one).
    parser.add_argument("addr", nargs="?", default=None)
    parser.add_argument(
        "--timeout",
        type=float,
        default=float(os.environ.get("APP_HEALTH_TIMEOUT_S", "120")),
        help="per-attempt RPC deadline in seconds (also bounds connect)",
    )
    parser.add_argument(
        "--attempts", type=int, default=3, help="total attempts before giving up"
    )
    parser.add_argument(
        "--backoff",
        type=float,
        default=2.0,
        help="initial retry backoff in seconds (doubles per attempt)",
    )
    parser.add_argument(
        "--verbose",
        action="store_true",
        help="also fetch GET /healthz?verbose=1 (pool, breakers, fleet) "
        "from the HTTP listener and print it",
    )
    parser.add_argument(
        "--http-addr",
        default=_default_http_addr(),
        help="HTTP listener for the --verbose deep-health view "
        "(default: derived from APP_HTTP_LISTEN_ADDR)",
    )
    parser.add_argument(
        "--router",
        action="store_true",
        help="probe a fleet-router edge instead: addr is the router's HTTP "
        "listener; exits 2 listing unreachable replicas, 3 when replicas "
        "are draining (docs/fleet.md)",
    )
    args = parser.parse_args()
    if args.router:
        args.addr = args.addr or _default_router_addr()
        router_main(args)
        return
    args.addr = args.addr or os.environ.get("APP_GRPC_ADDR", "localhost:50051")
    try:
        asyncio.run(
            check(
                args.addr,
                timeout=args.timeout,
                attempts=args.attempts,
                backoff=args.backoff,
                http_addr=args.http_addr,
            )
        )
    except ServiceDraining as e:
        # UNAVAILABLE is what a *draining* replica answers too (it rejects
        # new work while finishing in-flight executions) — the distinct exit
        # lets preStop/readiness tooling tell "finishing up" from "dead".
        print(
            f"DRAINING: service at {args.addr} is in graceful drain "
            f"({e.body.get('drain_inflight', 0)} in flight); "
            "not accepting new work",
            file=sys.stderr,
        )
        sys.exit(DRAINING_EXIT)
    except grpc.aio.AioRpcError as e:
        if e.code() is grpc.StatusCode.UNAVAILABLE:
            print(
                f"UNHEALTHY: service at {args.addr} unreachable after "
                f"{args.attempts} attempt(s): gRPC UNAVAILABLE ({e.details()})",
                file=sys.stderr,
            )
        else:
            print(
                f"UNHEALTHY: gRPC {e.code().name} from {args.addr}: {e.details()}",
                file=sys.stderr,
            )
        sys.exit(2)
    except AssertionError as e:
        print(f"UNHEALTHY: {e}", file=sys.stderr)
        sys.exit(1)
    print("healthy")
    if args.verbose:
        # Supplementary: the liveness verdict above already printed; a
        # missing HTTP listener degrades to a note, not a failed probe.
        try:
            body = asyncio.run(verbose_health(args.http_addr))
        except Exception as e:
            print(
                f"(verbose view unavailable from {args.http_addr}: {e})",
                file=sys.stderr,
            )
        else:
            print(json.dumps(body, indent=2))
            # The service is alive AND burning error budget at page rate
            # (both fast windows over threshold — docs/observability.md
            # "SLOs"): a warning exit k8s never sees (no --verbose on the
            # probe) but deploy tooling and operators do.
            if (body.get("slo") or {}).get("fast_burn_alerting"):
                print(
                    "WARNING: fast-window SLO burn-rate alert firing; "
                    "error budget is being spent at page rate",
                    file=sys.stderr,
                )
                sys.exit(SLO_BURN_EXIT)


if __name__ == "__main__":
    main()
