"""Liveness probe: asserts the full execute path end-to-end over gRPC.

Reference: health_check.py:25-53 — Execute("print(21 * 2)") must return stdout
"42\\n". Used as the k8s liveness command and as the gate before the e2e suite.

    python -m bee_code_interpreter_tpu.health_check [addr]
"""

from __future__ import annotations

import asyncio
import os
import sys

import grpc.aio

from bee_code_interpreter_tpu.api.grpc_server import service_stubs
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb


async def check(addr: str) -> None:
    cert = os.environ.get("APP_GRPC_TLS_CERT")
    key = os.environ.get("APP_GRPC_TLS_CERT_KEY")
    ca = os.environ.get("APP_GRPC_TLS_CA_CERT")
    if cert and key:
        creds = grpc.ssl_channel_credentials(
            root_certificates=ca.encode() if ca else None,
            private_key=key.encode(),
            certificate_chain=cert.encode(),
        )
        channel = grpc.aio.secure_channel(addr, creds)
    else:
        channel = grpc.aio.insecure_channel(addr)
    async with channel:
        stubs = service_stubs(channel)
        response = await stubs["Execute"](
            pb.ExecuteRequest(source_code="print(21 * 2)"), timeout=120
        )
    assert response.stdout == "42\n", f"unexpected stdout: {response.stdout!r}"
    assert response.exit_code == 0, f"unexpected exit code: {response.exit_code}"


def main() -> None:
    addr = sys.argv[1] if len(sys.argv) > 1 else os.environ.get(
        "APP_GRPC_ADDR", "localhost:50051"
    )
    asyncio.run(check(addr))
    print("healthy")


if __name__ == "__main__":
    main()
