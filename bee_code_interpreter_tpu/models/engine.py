"""Serving engine: a request queue in front of the continuous batcher.

``ContinuousBatcher`` (models/serving.py) is deliberately mechanism-only:
``submit`` raises when no row or not enough pages are free, and every
example had to hand-roll the same admit-when-capacity-frees loop around
it. This module is that loop as library code:

- ``submit`` ALWAYS accepts (up to an optional queue bound) and returns a
  ticket; admission into the batcher happens inside ``step`` the moment a
  row AND enough pages are free — page-pool exhaustion is backpressure,
  not an error.
- Admission order is (priority desc, arrival order) — a plain FCFS queue
  unless priorities are used. Head-of-line blocking is intentional: a
  large request at the head is not starved by small ones behind it
  (admitting out of order would let it wait forever under load).
- ``new_tokens`` is the STREAMING read: tokens appended since the last
  call for that ticket — poll it between steps to stream a response out.
- ``cancel`` works on queued tickets (dropped before ever touching the
  device, finish reason 'cancelled') and on admitted ones (proxied to the
  batcher, pages freed mid-decode).

The engine is host-side orchestration only — everything the device
executes is still the batcher's fixed-shape programs. The reference has
no serving stack at all (SURVEY §2).
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass

import numpy as np

from bee_code_interpreter_tpu.models.serving import (
    CapacityError,
    ContinuousBatcher,
    SamplingParams,
)


@dataclass
class _Queued:
    prompt: object
    max_new_tokens: int
    sampling: SamplingParams | None
    prefill_chunk: int | None
    adapter: int | None
    pages_needed: int
    interleave_admission: int | None = None
    priority: int = 0  # kept so a preempted ticket requeues in class


class Engine:
    """Queue + admission loop over a ``ContinuousBatcher``.

    ``max_queue`` bounds accepted-but-not-admitted requests (None =
    unbounded); ``submit`` raises RuntimeError at the bound — the one
    overload signal the caller must handle.
    """

    def __init__(self, batcher: ContinuousBatcher,
                 max_queue: int | None = None, metrics=None,
                 monitor=None) -> None:
        self.batcher = batcher
        self.max_queue = max_queue
        # Lifecycle monitor (observability.ServingMonitor): the engine owns
        # the queued/requeued/rejected part of a request's story, the
        # batcher the rest — one monitor sees both. Inherits the batcher's
        # when not given so a single attach() wires the whole stack.
        self._monitor = monitor if monitor is not None else getattr(
            batcher, "_monitor", None
        )
        # ticket -> original request for tickets admitted with interleaved
        # prefill — the only preemptable kind (see preempt); dropped on
        # preempt-resubmit consumption or release().
        self._preemptable: dict[int, _Queued] = {}
        # preempted tickets requeue at the HEAD of their priority class:
        # strictly decreasing negative seqs sort before every arrival seq
        self._front_seq = 0
        # Queue-level instrumentation (docs/observability.md): the batcher
        # covers decode cadence; the engine covers what happens BEFORE a
        # request reaches a batch row — depth, wait, capacity bounce-backs.
        self._metrics = metrics
        self._ticket_submit_t: dict[int, float] = {}
        if metrics is not None:
            self._queue_wait_seconds = metrics.histogram(
                "bci_serving_queue_wait_seconds",
                "Ticket wait from engine submit to batcher admission",
            )
            self._requeues_total = metrics.counter(
                "bci_serving_requeues_total",
                "Admissions bounced back to the queue by a capacity race",
            )
            self._rejected_total = metrics.counter(
                "bci_serving_queue_rejected_total",
                "Submissions rejected at the queue bound",
            )
            metrics.gauge(
                "bci_serving_queue_depth",
                "Accepted-but-not-admitted tickets",
                lambda: len(self._queued),
            )
        # heap entries: (-priority, arrival seq, ticket, request);
        # cancellation of a queued ticket is LAZY — the ticket leaves
        # self._queued and its entry is skipped when it surfaces
        self._heap: list[tuple[int, int, int, _Queued]] = []
        self._next_seq = 0
        self._next_ticket = 0
        # ticket -> batcher request id (admitted), 'queued',
        # 'cancelled', or ('error', msg) for an admission-time failure
        self._state: dict[int, object] = {}
        self._queued: set[int] = set()
        self._stream_cursor: dict[int, int] = {}
        self._holdback: dict[int, int] = {}

    # ----------------------------------------------------- snapshot/resume

    def state_dict(self) -> dict:
        """The engine's full serving state: the batcher snapshot (device
        pool + in-flight rows, serving.ContinuousBatcher.state_dict) plus
        the queue — tickets not yet admitted resume queued, in their
        original (priority, arrival) order. Same persistence caveat as the
        batcher's: pickles unless a request carries callable constraints."""
        import copy

        return {
            "batcher": self.batcher.state_dict(),
            "heap": copy.deepcopy(self._heap),
            "state": copy.deepcopy(self._state),
            "queued": set(self._queued),
            "stream_cursor": dict(self._stream_cursor),
            "holdback": dict(self._holdback),
            "next_seq": self._next_seq,
            "next_ticket": self._next_ticket,
            "preemptable": copy.deepcopy(self._preemptable),
            "front_seq": self._front_seq,
        }

    def load_state_dict(self, state: dict) -> None:
        import copy

        self.batcher.load_state_dict(state["batcher"])
        self._heap = copy.deepcopy(state["heap"])
        heapq.heapify(self._heap)
        self._state = copy.deepcopy(state["state"])
        self._queued = set(state["queued"])
        self._stream_cursor = dict(state["stream_cursor"])
        self._holdback = dict(state["holdback"])
        self._next_seq = state["next_seq"]
        self._next_ticket = state["next_ticket"]
        # .get(): snapshots from before the preemption API lack these keys
        self._preemptable = copy.deepcopy(state.get("preemptable", {}))
        self._front_seq = state.get("front_seq", 0)
        # max_queue is POLICY, not serving state: the receiving engine's
        # configured bound stays (a snapshot must not smuggle in an old
        # overload policy)

    # ------------------------------------------------------------- intake
    def submit(
        self,
        prompt,
        max_new_tokens: int,
        sampling: SamplingParams | None = None,
        prefill_chunk: int | None = None,
        adapter: int | None = None,
        priority: int = 0,
        interleave_admission: int | None = None,
    ) -> int:
        """Accept a request and return a ticket. Everything
        capacity-independent (empty prompt, budget > block table, pages >
        the whole pool, speculative sampling constraints, adapter range)
        fails HERE via the batcher's own ``validate_request`` — a queued
        request must not explode minutes later on an error the caller
        could have seen at submit."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        pages_needed = self.batcher.validate_request(
            prompt, max_new_tokens, sampling=sampling, adapter=adapter,
            interleave_admission=interleave_admission,
        )
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {prefill_chunk}")
        if self.max_queue is not None and len(self._queued) >= self.max_queue:
            if self._metrics is not None:
                self._rejected_total.inc()
            if self._monitor is not None:
                self._monitor.on_ticket_rejected("queue_full")
            raise RuntimeError(f"queue full ({self.max_queue})")
        req = _Queued(
            prompt, max_new_tokens, sampling, prefill_chunk, adapter,
            pages_needed=pages_needed,
            interleave_admission=interleave_admission,
            priority=priority,
        )
        ticket = self._next_ticket
        self._next_ticket += 1
        seq = self._next_seq
        self._next_seq += 1
        heapq.heappush(self._heap, (-priority, seq, ticket, req))
        self._state[ticket] = "queued"
        self._queued.add(ticket)
        self._stream_cursor[ticket] = 0
        # streaming holdback: while the request is live, the last
        # (max stop length - 1) tokens stay unstreamed — a stop sequence
        # completing later would TRIM tokens the stream had already
        # emitted otherwise. At retirement the remainder flushes post-trim.
        stops = sampling.stop_sequences if sampling is not None else ()
        self._holdback[ticket] = max((len(s) for s in stops), default=1) - 1
        if self._metrics is not None:
            self._ticket_submit_t[ticket] = time.monotonic()
        if self._monitor is not None:
            self._monitor.on_ticket_queued(ticket)
        return ticket

    def set_monitor(self, monitor) -> None:
        """Attach a lifecycle monitor (ServingMonitor.attach calls this)."""
        self._monitor = monitor

    # -------------------------------------------------------------- admit
    def _admit_ready(self) -> None:
        while self._heap:
            neg_prio, seq, ticket, req = self._heap[0]
            if ticket not in self._queued:  # cancelled while queued
                heapq.heappop(self._heap)
                continue
            if not self.batcher.has_free_row():
                return
            # page backpressure: strictly FCFS-within-priority — the head
            # waits for ITS pages; smaller requests behind it do not jump.
            # Prefix-cache credit counts: pages the submission would REUSE
            # (held by sharing rows or parked) need no fresh allocation,
            # so ignoring them would stall admissions the batcher accepts.
            available = (
                len(self.batcher.free_pages) + len(self.batcher.evictable)
            )
            fresh_needed = req.pages_needed - self.batcher.prefix_credit(
                req.prompt, req.adapter
            )
            if fresh_needed > available:
                return
            heapq.heappop(self._heap)
            self._queued.discard(ticket)
            if self._monitor is not None:
                # BEFORE the submit: the monitor stages this ticket's queue
                # wait so the lifecycle record born inside the call starts
                # its clock at engine intake (blocking admission fixes TTFT
                # before submit returns)
                self._monitor.on_ticket_admitting(ticket)
            try:
                rid = self.batcher.submit(
                    req.prompt, req.max_new_tokens, sampling=req.sampling,
                    prefill_chunk=req.prefill_chunk, adapter=req.adapter,
                    interleave_admission=req.interleave_admission,
                )
            except CapacityError:
                # capacity race (e.g. prefix-matched pages changed the
                # arithmetic): put it back and stop admitting this step.
                # Only the batcher's own backpressure signal requeues —
                # a bare RuntimeError here could be jaxlib's
                # XlaRuntimeError (device OOM/failure during admission
                # prefill), which must become an error ticket below, not
                # an infinite requeue loop against a failing device.
                heapq.heappush(self._heap, (neg_prio, seq, ticket, req))
                self._queued.add(ticket)
                if self._metrics is not None:
                    self._requeues_total.inc()
                if self._monitor is not None:
                    self._monitor.on_ticket_requeued(ticket)
                return
            except Exception as e:
                # validate_request ran at intake, so this "cannot happen";
                # if it does anyway (validation drift), fail the ticket
                # loudly-but-locally instead of wedging it in 'queued'
                # forever and taking the whole step loop down
                self._state[ticket] = ("error", repr(e))
                self._ticket_submit_t.pop(ticket, None)
                if self._monitor is not None:
                    self._monitor.on_ticket_failed(ticket, repr(e))
                continue
            self._state[ticket] = rid
            if req.interleave_admission is not None:
                # only interleaved admissions are preemptable mid-prefill;
                # keep the request so preempt() can requeue it verbatim
                self._preemptable[ticket] = req
            if self._metrics is not None:
                t0 = self._ticket_submit_t.pop(ticket, None)
                if t0 is not None:
                    self._queue_wait_seconds.observe(time.monotonic() - t0)

    # --------------------------------------------------------------- step
    def step(self) -> None:
        """Admit whatever fits, then advance the batch one round."""
        self._admit_ready()
        self.batcher.step()
        self._admit_ready()  # rows/pages freed by retirements this step

    def run_to_completion(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if not self._queued and not self.batcher.busy:
                return
            self.step()
        raise RuntimeError("run_to_completion exceeded max_steps")

    @property
    def pending(self) -> int:
        """Accepted-but-not-admitted request count (queue depth)."""
        return len(self._queued)

    @property
    def stats(self) -> dict:
        """The batcher's operator counters plus queue depth, with the
        request counts at TICKET level (a queued ticket exists before the
        batcher ever sees it)."""
        st = {**self.batcher.stats, "queued": len(self._queued)}
        st["requests_submitted"] = len(self._state)
        st["requests_finished"] = sum(
            1 for t in self._state if self.is_done(t)
        )
        return st

    # ------------------------------------------------------------ results
    def _rid(self, ticket: int):
        if ticket not in self._state:
            raise KeyError(f"unknown ticket {ticket}")
        return self._state[ticket]

    def is_done(self, ticket: int) -> bool:
        rid = self._rid(ticket)
        if rid == "queued":
            return False
        if rid == "cancelled" or isinstance(rid, tuple):
            return True
        return self.batcher.is_done(rid)

    def result(self, ticket: int) -> list[int]:
        rid = self._rid(ticket)
        if rid == "queued":
            raise RuntimeError(f"ticket {ticket} still queued")
        if rid == "cancelled" or isinstance(rid, tuple):
            return []
        return self.batcher.result(rid)

    def result_logprobs(self, ticket: int) -> list[float]:
        rid = self._rid(ticket)
        if rid == "queued":
            raise RuntimeError(f"ticket {ticket} still queued")
        if rid == "cancelled" or isinstance(rid, tuple):
            return []
        return self.batcher.result_logprobs(rid)

    def finish_reason(self, ticket: int) -> str:
        rid = self._rid(ticket)
        if rid == "queued":
            raise RuntimeError(f"ticket {ticket} still queued")
        if rid == "cancelled":
            return "cancelled"
        if isinstance(rid, tuple):
            return "error"
        return self.batcher.finish_reason(rid)

    def ticket_error(self, ticket: int) -> str | None:
        """repr of an admission-time failure (finish reason 'error' from
        the engine itself) or the batcher's recorded callable error."""
        rid = self._rid(ticket)
        if isinstance(rid, tuple):
            return rid[1]
        if rid in ("queued", "cancelled"):
            return None
        return self.batcher.request_error(rid)

    def partial_result(self, ticket: int) -> list[int]:
        """Tokens generated so far — safe at ANY time (empty while queued,
        after cancellation of queued work, on an admission failure, or
        after release). The streaming and text layers build on this
        instead of poking at internal state."""
        rid = self._rid(ticket)
        if rid in ("queued", "cancelled") or isinstance(rid, tuple):
            return []
        return list(self.batcher.results.get(rid, ()))

    def new_tokens(self, ticket: int) -> list[int]:
        """STREAMING read: tokens appended for this ticket since the last
        ``new_tokens`` call (empty while queued). Poll between steps to
        stream a response; the final chunk lands no later than the step
        that finishes the request. While the request is live, the last
        (max stop length - 1) tokens are held back so a stop sequence
        completing later can never trim a token the stream already
        emitted — the stream's concatenation always equals ``result``."""
        tokens = self.partial_result(ticket)
        if not tokens:
            return []
        limit = (
            len(tokens) if self.is_done(ticket)
            else max(0, len(tokens) - self._holdback[ticket])
        )
        cursor = self._stream_cursor[ticket]
        if limit <= cursor:
            return []
        self._stream_cursor[ticket] = limit
        return list(tokens[cursor:limit])

    def preempt(self, ticket: int) -> bool:
        """Evict an admitted ticket whose INTERLEAVED prefill hasn't
        produced a token yet, back to the HEAD of its priority class (it
        already earned its pages once; making it re-race arrivals would
        starve long prompts under load). The batcher frees its pages and
        forgets the old request id; re-admission recomputes the prefill —
        exact, because nothing was emitted. Returns False for queued,
        finished, decoding (use :meth:`cancel` to stop those and keep their
        partial output) or blocking-admitted tickets; an unknown ticket
        raises KeyError — the same contract as :meth:`result`."""
        rid = self._rid(ticket)
        if not isinstance(rid, int):
            return False
        req = self._preemptable.pop(ticket, None)
        if req is None or not self.batcher.preempt(rid):
            return False
        self._front_seq -= 1
        heapq.heappush(
            self._heap, (-req.priority, self._front_seq, ticket, req)
        )
        self._queued.add(ticket)
        self._state[ticket] = "queued"
        self._stream_cursor[ticket] = 0
        if self._metrics is not None:
            # queue wait re-measures from the preemption, matching the
            # monitor's fresh queued clock below
            self._ticket_submit_t[ticket] = time.monotonic()
        if self._monitor is not None:
            self._monitor.on_ticket_queued(ticket)
        return True

    def cancel(self, ticket: int) -> None:
        """Cancel queued (never touches the device) or admitted (pages
        freed mid-decode) work; racing completion is a no-op."""
        rid = self._rid(ticket)
        if rid == "queued":
            self._queued.discard(ticket)  # heap entry skipped lazily
            self._state[ticket] = "cancelled"
            self._stream_cursor.pop(ticket, None)
            self._holdback.pop(ticket, None)
            self._ticket_submit_t.pop(ticket, None)
            if self._monitor is not None:
                self._monitor.on_ticket_cancelled(ticket)
            return
        if rid != "cancelled" and not isinstance(rid, tuple):
            self.batcher.cancel(rid)

    def release(self, ticket: int) -> None:
        rid = self._rid(ticket)
        if rid == "queued":
            raise RuntimeError(f"ticket {ticket} still queued")
        if rid != "cancelled" and not isinstance(rid, tuple):
            self.batcher.release(rid)
        self._stream_cursor.pop(ticket, None)
        self._holdback.pop(ticket, None)
        self._preemptable.pop(ticket, None)
