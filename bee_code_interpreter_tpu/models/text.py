"""Text-level serving: tokenizer-in, stop STRINGS, UTF-8-safe streaming.

``Engine``/``ContinuousBatcher`` speak token ids; real serving APIs speak
text. The gap is not just encode/decode at the edges — two contracts only
exist at the text level:

- **Stop strings.** A stop like ``"\\n\\n"`` can arrive split across any
  token boundary (or inside one token that also carries wanted text), so
  it CANNOT be compiled to token-id stop sequences. The text engine scans
  the decoded completion after every step and, on a match, truncates the
  text at the stop and cancels the underlying request (the current step's
  overshoot tokens are simply never shown — the user-visible contract is
  the text, not the token count).
- **Streaming without torn characters.** Detokenizers are not prefix-
  stable (merges, byte-level BPE continuation, multi-token unicode), so
  streamed text is computed by decoding the FULL token list and diffing
  against what was already emitted — plus a holdback of
  ``max(len(stop)) - 1`` characters so a stop string completing later can
  never claw back emitted text. The concatenated stream always equals
  ``text()``.

The tokenizer is a PROTOCOL, not a dependency: anything with
``encode(str) -> list[int]`` and ``decode(list[int]) -> str`` works — a
HuggingFace tokenizer does (pass ``add_special_tokens=False`` semantics
yourself if needed), and the tests use a trivial hermetic one. The
reference has no serving stack at all (SURVEY §2).
"""

from __future__ import annotations

from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import SamplingParams


class TextEngine:
    """Text requests over an ``Engine``: ``submit(text)`` → ticket,
    ``step()``/``run_to_completion()`` to advance, ``text(ticket)`` for
    the finished completion and ``new_text(ticket)`` for streaming."""

    def __init__(self, engine: Engine, tokenizer) -> None:
        for method in ("encode", "decode"):
            if not callable(getattr(tokenizer, method, None)):
                raise TypeError(
                    f"tokenizer must implement {method}(); got "
                    f"{type(tokenizer).__name__}"
                )
        self.engine = engine
        self.tokenizer = tokenizer
        self._stops: dict[int, tuple[str, ...]] = {}
        self._holdback: dict[int, int] = {}
        self._emitted: dict[int, str] = {}  # text already streamed
        self._final: dict[int, str | None] = {}  # fixed text (None = live)
        self._reason: dict[int, str] = {}
        self._live: set[int] = set()
        # memo: ticket -> (token count, decoded text). _scan and new_text
        # both need the decode every step; without the memo each request
        # pays O(len^2) tokenizer work over its lifetime.
        self._decode_memo: dict[int, tuple[int, str]] = {}

    # ------------------------------------------------------------- intake
    def submit(
        self,
        text: str,
        max_new_tokens: int,
        stop: tuple[str, ...] = (),
        sampling: SamplingParams | None = None,
        **engine_kwargs,
    ) -> int:
        stop = tuple(stop)
        if any(not s for s in stop):
            raise ValueError("stop strings must be non-empty")
        prompt = self.tokenizer.encode(text)
        ticket = self.engine.submit(
            prompt, max_new_tokens, sampling=sampling, **engine_kwargs
        )
        self._stops[ticket] = stop
        self._holdback[ticket] = max((len(s) for s in stop), default=1) - 1
        self._emitted[ticket] = ""
        self._final[ticket] = None
        self._live.add(ticket)
        return ticket

    # --------------------------------------------------------------- step
    def _decoded(self, ticket: int) -> str:
        tokens = self.engine.partial_result(ticket)
        if not tokens:
            return ""
        memo = self._decode_memo.get(ticket)
        if memo is not None and memo[0] == len(tokens):
            return memo[1]
        text = self.tokenizer.decode(tokens)
        self._decode_memo[ticket] = (len(tokens), text)
        return text

    @staticmethod
    def _stable(text: str) -> str:
        """Drop the UNSTABLE decode tail: byte-level BPE emits U+FFFD for
        an incomplete multi-byte character until its continuation tokens
        arrive — those trailing chars are held back from streaming (and
        flushed at completion, when the decode is final)."""
        return text.rstrip("\ufffd")

    def _scan(self, ticket: int) -> None:
        """Post-step stop-string scan for one live text request: the
        EARLIEST stop match wins; a match cancels the underlying request
        (freeing its pages) and fixes the text at the truncation."""
        if self._final[ticket] is not None:
            return
        decoded = self._decoded(ticket)
        best: int | None = None
        for s in self._stops[ticket]:
            at = decoded.find(s)
            if at != -1 and (best is None or at < best):
                best = at
        if best is not None:
            self._final[ticket] = decoded[:best]
            # recorded NOW: deriving it later by re-decoding would flip to
            # 'cancelled' once the underlying request is released
            self._reason[ticket] = "stop"
            self._live.discard(ticket)
            if not self.engine.is_done(ticket):
                self.engine.cancel(ticket)
        elif self.engine.is_done(ticket):
            self._final[ticket] = decoded
            self._reason[ticket] = self.engine.finish_reason(ticket)
            self._live.discard(ticket)

    def step(self) -> None:
        self.engine.step()
        for ticket in list(self._live):
            self._scan(ticket)

    def run_to_completion(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if not self._live:
                return
            self.step()
        raise RuntimeError("run_to_completion exceeded max_steps")

    # ------------------------------------------------------------ results
    def is_done(self, ticket: int) -> bool:
        # keyed on _reason, which release() retains: the done-flag must
        # survive release (the engine/batcher layers uphold the same
        # contract) or a poller on a released ticket spins forever
        return ticket in self._reason

    def release(self, ticket: int) -> None:
        """Drop this ticket's text state AND the underlying request's —
        the long-running-server hygiene the engine/batcher layers already
        require. ``finish_reason`` stays observable (a string per
        ticket); ``text`` does not."""
        if ticket in self._final and self._final[ticket] is None:
            raise RuntimeError(f"ticket {ticket} still generating")
        self.engine.release(ticket)
        for d in (self._stops, self._holdback, self._emitted, self._final,
                  self._decode_memo):
            d.pop(ticket, None)
        self._live.discard(ticket)

    def text(self, ticket: int) -> str:
        if ticket not in self._final:
            if ticket in self._reason:
                raise KeyError(f"ticket {ticket} released")
            raise KeyError(f"unknown ticket {ticket}")
        final = self._final[ticket]
        if final is None:
            raise RuntimeError(f"ticket {ticket} still generating")
        return final

    def finish_reason(self, ticket: int) -> str:
        """'stop' when a stop string matched (even though the underlying
        request was cancelled to free its pages); otherwise the engine's
        reason — recorded at the moment the text was fixed, so it
        survives releasing the underlying request."""
        if ticket not in self._reason:
            if ticket in self._final:
                raise RuntimeError(f"ticket {ticket} still generating")
            raise KeyError(f"unknown ticket {ticket}")
        return self._reason[ticket]

    def new_text(self, ticket: int) -> str:
        """Streaming read: decoded text appended since the last call,
        holding back ``max(len(stop)) - 1`` characters while live so a
        later stop match can never claw back emitted text. The
        concatenation of every chunk equals ``text()``."""
        if ticket not in self._final:
            if ticket in self._reason:
                raise KeyError(f"ticket {ticket} released")
            raise KeyError(f"unknown ticket {ticket}")
        emitted = self._emitted[ticket]
        final = self._final[ticket]
        if final is not None:
            if not final.startswith(emitted):
                return ""  # decode tail shifted under the stream (see below)
            self._emitted[ticket] = final
            return final[len(emitted):]
        # stop holdback: a stop completing later must START within the
        # last (len(stop)-1) chars of the text that existed when it
        # completes, and every emission stopped at least that far back
        # (scans run every step, so any earlier-starting match would
        # already have fixed the text). _stable additionally holds back a
        # byte-level-BPE U+FFFD tail until its continuation arrives.
        # Emission is PREFIX-VERIFIED: if the decode mutated text the
        # stream already carries (a tokenizer unstable beyond its tail),
        # nothing more is emitted and text() remains the contract.
        visible = self._stable(self._decoded(ticket))
        limit = max(0, len(visible) - self._holdback[ticket])
        if limit <= len(emitted) or not visible.startswith(emitted):
            return ""
        chunk = visible[len(emitted): limit]
        self._emitted[ticket] = visible[:limit]
        return chunk
