"""Serving bench phase: tokens/sec + TTFT trajectory with an
instrumentation A/B (docs/observability.md "Serving observability").

ROADMAP item 4 asks for "a tokens/sec + TTFT trajectory alongside warm-
execute p50"; this module is that measurement as library code so bench.py
(via an executor payload), the tier-1 suite (directly, with tiny
parameters), and an operator at a REPL all run the SAME arithmetic:

- **Throughput**: steady-state tokens/sec of a continuous-batching run on
  already-compiled programs (an explicit warmup pass eats every compile),
  measured on two arms — one with the full observability stack attached
  (metrics registry + ServingMonitor, exactly the production wiring) and
  one bare — so the artifact carries a MEASURED instrumentation overhead
  instead of a promise. The arms ALTERNATE repeat-by-repeat; throughput
  is each arm's best-of-``repeats`` (min-of-N discards scheduler noise),
  while the overhead is the MEDIAN of the per-round bare/instrumented
  ratios: adjacent-in-time pairs see the same machine state, so slow
  drift (CPU frequency, co-tenants) cancels out of every ratio — measured
  as ratio-of-mins the same stack read anywhere from 1% to 10% on a noisy
  box, as median-of-paired-ratios it is stable to ~1 point.
- **Latency**: TTFT p50/p95 and inter-token latency p50 from the
  instrumented arm's per-request lifecycle records (the same records
  ``GET /v1/serving/requests`` serves).

CPU-pinned tiny-model by default: the point is a stable trajectory of the
SERVING STACK's behavior in every artifact; hardware decode numbers live
in scripts/bench-decode.py's evidence ledger. Note the default geometry
(batch 8 × 32 tokens) is the FAIREST tiny-model denominator for the
overhead A/B, not a flattering one: instrumentation cost is fixed per
step/request, and the tiny model's ~1-2 ms CPU steps are already a far
harsher ratio than any real serving config's 10-100 ms steps — a
half-empty batch of 16-token requests would just measure the denominator,
not the instrumentation.
"""

from __future__ import annotations

import time


def _percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile on a small sample (q in [0, 1])."""
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
    return ordered[idx]


def run_serving_bench(
    n_requests: int = 8,
    max_new_tokens: int = 64,
    repeats: int = 7,
    prompt_len: int = 6,
    max_batch: int = 8,
    overhead_budget_pct: float = 5.0,
    inner: int = 2,
    temperature: float = 0.0,
) -> dict:
    """One serving bench run; returns the BENCH-artifact dict (see module
    docstring). Deterministic workload (fixed seeds, greedy decode) so the
    two arms execute identical token streams. ``temperature`` > 0 runs the
    SAMPLED decode path instead (per-request seeded generators — still
    deterministic, still arm-identical): the A/B lever for host/device
    split changes that only show on the sampling path, e.g. the jaxlint
    host-sync audit's lazy-greedy fix (docs/analysis.md "Accelerator
    lint")."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bee_code_interpreter_tpu.models import transformer as T
    from bee_code_interpreter_tpu.models.engine import Engine
    from bee_code_interpreter_tpu.models.serving import (
        ContinuousBatcher,
        SamplingParams,
    )
    from bee_code_interpreter_tpu.observability import (
        DeviceMonitor,
        FlightRecorder,
        ServingMonitor,
        TraceStore,
    )
    from bee_code_interpreter_tpu.utils.metrics import Registry

    config = dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32
    )
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompts = [
        np.random.default_rng(i).integers(
            0, config.vocab_size, prompt_len + (i % 3), dtype=np.int32
        )
        for i in range(n_requests)
    ]
    pages_per_seq = -(-(prompt_len + 2 + max_new_tokens) // 4)
    geometry = dict(
        max_batch=max_batch,
        n_pages=1 + max_batch * pages_per_seq,
        page_size=4,
        max_pages_per_seq=pages_per_seq,
    )

    def build(instrumented: bool):
        if instrumented:
            registry = Registry()
            recorder = FlightRecorder(metrics=registry)
            monitor = ServingMonitor(
                metrics=registry, store=TraceStore(), recorder=recorder
            )
            batcher = ContinuousBatcher(
                params, config, metrics=registry, **geometry
            )
            engine = Engine(batcher, metrics=registry)
            monitor.attach(engine)
            # The accelerator plane rides the instrumented arm too: the
            # overhead number must price compile tracking + per-step mesh
            # telemetry, not just the serving monitor
            # (docs/observability.md "Accelerator observability").
            DeviceMonitor(metrics=registry, recorder=recorder).attach(engine)
            return engine, monitor
        return Engine(ContinuousBatcher(params, config, **geometry)), None

    sampling = [
        SamplingParams(temperature=temperature, seed=100 + i)
        if temperature > 0.0
        else None
        for i in range(n_requests)
    ]

    def run_once(engine) -> tuple[float, list[int]]:
        t0 = time.perf_counter()
        tickets = [
            engine.submit(p, max_new_tokens, sampling=s)
            for p, s in zip(prompts, sampling)
        ]
        engine.run_to_completion()
        dt = time.perf_counter() - t0
        outputs = []
        for ticket in tickets:
            out = engine.result(ticket)
            outputs.append(out)
            engine.release(ticket)
        return dt, outputs

    engines: dict[bool, object] = {}
    monitors: dict[bool, object] = {}
    want: dict[bool, list] = {}
    best: dict[bool, float] = {False: float("inf"), True: float("inf")}
    for instrumented in (False, True):
        engines[instrumented], monitors[instrumented] = build(instrumented)
        _, want[instrumented] = run_once(engines[instrumented])  # compiles
    if want[False] != want[True]:
        raise RuntimeError(
            "instrumented and bare arms decoded different tokens"
        )
    tokens = sum(len(o) for o in want[True])
    ratios: list[float] = []
    for _ in range(max(1, repeats)):
        round_dt: dict[bool, float] = {}
        for instrumented in (False, True):  # interleaved (see docstring)
            # min over `inner` back-to-back passes per arm per round:
            # single-pass spikes (a scheduler hiccup inside one 100 ms run)
            # would otherwise dominate the round's ratio
            round_best = float("inf")
            for _inner in range(max(1, inner)):
                dt, outputs = run_once(engines[instrumented])
                if outputs != want[instrumented]:
                    raise RuntimeError(
                        "serving bench outputs drifted between passes"
                    )
                round_best = min(round_best, dt)
            best[instrumented] = min(best[instrumented], round_best)
            round_dt[instrumented] = round_best
        ratios.append(round_dt[True] / round_dt[False])

    on_tps = tokens / best[True]
    off_tps = tokens / best[False]
    # the first measured round still rides machine warm-up (frequency
    # scaling, cache population) disproportionately often — drop its ratio
    # when enough rounds remain for a median
    if len(ratios) >= 3:
        ratios = ratios[1:]
    overhead_pct = max(0.0, (_percentile(ratios, 0.50) - 1.0) * 100.0)

    # latency distribution from the instrumented arm's lifecycle records
    # (warmup + repeats requests all recorded — more samples, same path)
    records = monitors[True].requests(outcome="ok")
    ttft_ms = [r["ttft_ms"] for r in records if r["ttft_ms"] is not None]
    itl_ms = [
        (r["duration_ms"] - r["ttft_ms"]) / (r["output_tokens"] - 1)
        for r in records
        if r["ttft_ms"] is not None
        and r["duration_ms"] is not None
        and r["output_tokens"] > 1
    ]
    return {
        "tokens_per_s": round(on_tps, 1),
        "uninstrumented_tokens_per_s": round(off_tps, 1),
        "overhead_pct": round(overhead_pct, 2),
        "overhead_budget_pct": overhead_budget_pct,
        "overhead_ok": overhead_pct < overhead_budget_pct,
        "ttft_p50_ms": round(_percentile(ttft_ms, 0.50), 3) if ttft_ms else None,
        "ttft_p95_ms": round(_percentile(ttft_ms, 0.95), 3) if ttft_ms else None,
        "inter_token_p50_ms": (
            round(_percentile(itl_ms, 0.50), 3) if itl_ms else None
        ),
        "requests": n_requests,
        "max_new_tokens": max_new_tokens,
        "repeats": repeats,
        "config": (
            "tiny f32, "
            + (f"sampled T={temperature}" if temperature > 0.0 else "greedy")
            + ", paged pool"
        ),
    }
