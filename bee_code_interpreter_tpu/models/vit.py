"""Vision Transformer (encoder) family, TPU-first.

Third pillar of the model family next to the llama-style decoder
(models/transformer.py) and the conv ResNet (models/vision.py): it is the
bidirectional-attention consumer of the shared attention stack — patches
attend all-to-all through the same `_attention` dispatch the decoder uses
(Pallas flash kernel with ``causal=False`` on TPU, ring/Ulysses over an
``sp`` mesh axis for very long token grids, reference einsum on CPU).

TPU-first choices:

- **Patchify as one conv** (`P×P` kernel, stride `P`, NHWC) — a single
  MXU-shaped contraction instead of reshape gymnastics.
- **Scan over uniform blocks**: ViT blocks are homogeneous (unlike the
  ResNet's widening stages), so per-layer params stack on a leading
  ``[n_layers]`` axis and the encoder body is one ``lax.scan`` — compile
  time flat in depth, same trick as the decoder.
- **bf16 compute / f32 masters**, Megatron column/row PartitionSpecs over
  ``fsdp``/``tp`` mesh axes, activations constrained on (batch, tokens).
- **Global-average-pool head** (no CLS token): one less ragged token, and
  the pooled reduction fuses into the head matmul.

``ViTConfig.vit_b16()`` reproduces the ViT-Base/16 shape (12×768, ~86M
params, pinned by tests/test_vit.py); ``tiny()`` is the CI size.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bee_code_interpreter_tpu.models.transformer import _attention, rms_norm
from bee_code_interpreter_tpu.parallel.mesh import batch_axes

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    sp_attention: str = "ring"  # sequence-parallel strategy over sp meshes

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def vit_b16(cls) -> "ViTConfig":
        """The classic ViT-Base/16 shape (~86M params)."""
        return cls()

    @classmethod
    def tiny(cls) -> "ViTConfig":
        return cls(image_size=32, patch_size=8, d_model=64, n_layers=2,
                   n_heads=4, d_ff=128, num_classes=10)


# ------------------------------------------------------------------- weights


def init_params(config: ViTConfig, key: jax.Array) -> Params:
    c = config
    k_patch, k_pos, k_layers, k_head = jax.random.split(key, 4)

    def dense(key, fan_in, *shape):
        return jax.random.normal(key, shape, dtype=jnp.float32) / math.sqrt(fan_in)

    def layer(key):
        ks = jax.random.split(key, 6)
        return {
            "ln1": jnp.ones((c.d_model,), jnp.float32),
            "wq": dense(ks[0], c.d_model, c.d_model, c.d_model),
            "wk": dense(ks[1], c.d_model, c.d_model, c.d_model),
            "wv": dense(ks[2], c.d_model, c.d_model, c.d_model),
            "wo": dense(ks[3], c.d_model, c.d_model, c.d_model),
            "ln2": jnp.ones((c.d_model,), jnp.float32),
            "w_up": dense(ks[4], c.d_model, c.d_model, c.d_ff),
            "w_down": dense(ks[5], c.d_ff, c.d_ff, c.d_model),
        }

    p = c.patch_size
    return {
        "patch_embed": dense(k_patch, p * p * 3, p, p, 3, c.d_model),  # HWIO
        "pos_embed": 0.02 * jax.random.normal(
            k_pos, (c.n_patches, c.d_model), jnp.float32
        ),
        "layers": jax.vmap(layer)(jax.random.split(k_layers, c.n_layers)),
        "ln_f": jnp.ones((c.d_model,), jnp.float32),
        "head": {
            "w": dense(k_head, c.d_model, c.d_model, c.num_classes),
            "b": jnp.zeros((c.num_classes,), jnp.float32),
        },
    }


def param_specs(config: ViTConfig, mesh: Mesh) -> Params:
    """Megatron col/row specs over whichever of (fsdp, tp) exist."""
    tp = "tp" if "tp" in mesh.axis_names else None
    fsdp = "fsdp" if "fsdp" in mesh.axis_names else None
    col = P(None, fsdp, tp)   # stacked [n_layers, d_in, d_out/tp]
    row = P(None, tp, fsdp)
    rep = P(None)
    return {
        "patch_embed": P(None, None, None, tp),
        "pos_embed": P(),
        "layers": {
            "ln1": rep, "ln2": rep,
            "wq": col, "wk": col, "wv": col, "wo": row,
            "w_up": col, "w_down": row,
        },
        "ln_f": P(),
        # head stays replicated: [d_model, num_classes] is tiny and
        # num_classes rarely divides tp
        "head": {"w": P(None, None), "b": P()},
    }


def shard_params(params: Params, config: ViTConfig, mesh: Mesh) -> Params:
    specs = param_specs(config, mesh)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
    )


# ------------------------------------------------------------------- forward


def forward(
    params: Params,
    images: jax.Array,  # [B, H, W, 3]
    config: ViTConfig,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Returns logits [B, num_classes] (f32)."""
    c = config
    B = images.shape[0]

    def constrain(x):
        if mesh is None:
            return x
        sp = "sp" if "sp" in mesh.axis_names else None
        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(batch_axes(mesh), sp, None))
        )

    # patchify: one strided conv, NHWC x HWIO -> [B, H/P, W/P, D] -> tokens
    x = lax.conv_general_dilated(
        images.astype(c.dtype), params["patch_embed"].astype(c.dtype),
        window_strides=(c.patch_size, c.patch_size), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ).reshape(B, c.n_patches, c.d_model)
    h = constrain(x + params["pos_embed"].astype(c.dtype))

    def block(h, layer):
        x = rms_norm(h, layer["ln1"])
        dh, nh = c.head_dim, c.n_heads

        def proj(w):
            out = jnp.einsum("btd,dk->btk", x, w.astype(c.dtype))
            return out.reshape(B, -1, nh, dh).transpose(0, 2, 1, 3)

        q, k, v = proj(layer["wq"]), proj(layer["wk"]), proj(layer["wv"])
        attn = _attention(q, k, v, mesh, c.sp_attention, causal=False)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, -1, nh * dh)
        h = h + constrain(
            jnp.einsum("btk,kd->btd", attn, layer["wo"].astype(c.dtype))
        )
        y = rms_norm(h, layer["ln2"])
        up = jnp.einsum("btd,df->btf", y, layer["w_up"].astype(c.dtype))
        mlp = jnp.einsum(
            "btf,fd->btd", jax.nn.gelu(up), layer["w_down"].astype(c.dtype)
        )
        return h + constrain(mlp), None

    h, _ = lax.scan(block, h, params["layers"])
    h = rms_norm(h, params["ln_f"])
    pooled = h.mean(axis=1).astype(jnp.float32)  # global average pool
    return pooled @ params["head"]["w"] + params["head"]["b"]


def loss_fn(params, batch, config, mesh=None):
    logits = forward(params, batch["images"], config, mesh)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]
    ).mean()


class ViT:
    """Config + mesh bundle mirroring Transformer/ResNet."""

    def __init__(self, config: ViTConfig, mesh: Mesh | None = None) -> None:
        self.config = config
        self.mesh = mesh

    def init(self, key: jax.Array) -> Params:
        params = init_params(self.config, key)
        if self.mesh is not None:
            params = shard_params(params, self.config, self.mesh)
        return params

    def apply(self, params: Params, images: jax.Array) -> jax.Array:
        return forward(params, images, self.config, self.mesh)

    def make_train_step(self, optimizer=None):
        optimizer = optimizer or optax.adamw(3e-4, weight_decay=0.05)

        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, self.config, self.mesh
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return jax.jit(train_step, donate_argnums=(0, 1))

    def batch_sharding(self) -> NamedSharding | None:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P(batch_axes(self.mesh)))
