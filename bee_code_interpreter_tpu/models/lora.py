"""LoRA (low-rank adaptation) fine-tuning for the transformer family.

Fits the functional design with zero model edits: LoRA state is a separate
small pytree of stacked per-layer ``A [n_layers, d_in, r]`` / ``B
[n_layers, r, d_out]`` factors for chosen projections, and ``merge_lora``
produces an ordinary params pytree with ``W + (alpha/r)·A@B`` folded in —
the merged weights feed the unchanged ``forward``/``decode_step``/pipeline
paths, shard under the same Megatron PartitionSpecs, and the merge einsum
is one extra [d_in, r]×[r, d_out] matmul per layer at trace time (fused by
XLA into the parameter cast it already does).

Training differentiates the loss **through the merge** with respect to the
LoRA factors only (``jax.grad`` argnum on the lora pytree) — the base stays
frozen and no optimizer state is allocated for it, which is the point:
AdamW moments for an 8B model cost 2×32 GB f32, while rank-16 LoRA state
fits in tens of MB.

``B`` is zero-initialized (standard LoRA): the adapted model starts exactly
equal to the base, pinned by tests/test_lora.py.

The reference has no training of any kind (SURVEY.md §2); this module is
framework completeness: sandboxed agents fine-tune the bundled families
without shipping a second copy of the model code.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import optax

from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    loss_fn,
)

Params = dict[str, Any]

DEFAULT_TARGETS = ("wq", "wv")  # the classic LoRA placement


def init_lora_from_layers(
    layers: Params,  # a "layers" pytree: stacked [n_layers, ...] leaves
    key: jax.Array,
    rank: int = 8,
    targets: tuple[str, ...] = DEFAULT_TARGETS,
) -> Params:
    """LoRA state for ANY stacked-layer family (transformer, ViT, ...):
    per-target stacked A (gaussian / sqrt(d)) and B (zeros), with shapes
    read off the layer pytree itself — every [n_layers, d_in, d_out]
    projection is a valid target. Pass concrete params or an abstract
    ``jax.eval_shape`` pytree; only shapes are read."""
    dims = {
        name: leaf.shape
        for name, leaf in layers.items()
        if hasattr(leaf, "ndim") and leaf.ndim == 3
    }
    unknown = set(targets) - set(dims)
    if unknown:
        raise ValueError(f"no LoRA target(s) {sorted(unknown)}; have {sorted(dims)}")
    keys = jax.random.split(key, len(targets))
    state: Params = {}
    for t, k in zip(targets, keys):
        n_layers, d_in, d_out = dims[t]
        state[t] = {
            "A": jax.random.normal(k, (n_layers, d_in, rank), jnp.float32)
            / math.sqrt(d_in),
            "B": jnp.zeros((n_layers, rank, d_out), jnp.float32),
        }
    return state


def init_lora(
    config: TransformerConfig,
    key: jax.Array,
    rank: int = 8,
    targets: tuple[str, ...] = DEFAULT_TARGETS,
) -> Params:
    """Transformer-config convenience wrapper over ``init_lora_from_layers``
    (shapes derive from init_params via abstract eval — no arrays are
    materialized; one source of truth for the layout). The scale
    (alpha/rank) is a static argument of ``merge_lora``/
    ``make_lora_train_step``, NOT a pytree leaf — leaves are what
    optimizers update."""
    from bee_code_interpreter_tpu.models.transformer import init_params

    abstract = jax.eval_shape(
        lambda k: init_params(config, k), jax.random.PRNGKey(0)
    )["layers"]
    return init_lora_from_layers(abstract, key, rank=rank, targets=targets)


def merge_lora(params: Params, lora: Params, scale: float = 1.0) -> Params:
    """Base params with ``W + scale·A@B`` folded into each target — an
    ordinary params pytree for the unchanged forward/decode paths.
    ``scale`` is the standard alpha/rank."""
    from bee_code_interpreter_tpu.ops.weight_quant import is_quantized

    if any(is_quantized(params["layers"].get(t)) for t in lora):
        # folding a rank-r delta into int8 would re-quantize the base on
        # every merge; the supported order is merge THEN quantize
        raise NotImplementedError(
            "merge_lora needs fp base weights (quantize AFTER merging)"
        )
    layers = dict(params["layers"])
    for t, ab in lora.items():
        delta = jnp.einsum("lir,lro->lio", ab["A"], ab["B"]) * scale
        layers[t] = params["layers"][t] + delta
    return {**params, "layers": layers}


def make_lora_train_step(
    config: TransformerConfig,
    optimizer=None,
    mesh=None,
    scale: float = 1.0,
):
    """Jitted step updating ONLY the LoRA factors; base params are frozen
    (no gradient, no optimizer state). Returns (step, optimizer)."""
    optimizer = optimizer or optax.adamw(1e-3)

    def lora_loss(lora, params, batch):
        return loss_fn(merge_lora(params, lora, scale), batch, config, mesh)

    def step(lora, opt_state, params, batch):
        loss, grads = jax.value_and_grad(lora_loss)(lora, params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        return optax.apply_updates(lora, updates), opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1)), optimizer


def stack_lora_bank(adapters: list[Params]) -> Params:
    """Stack adapters into the multi-LoRA serving bank
    (``models/serving.py``): per target ``A [n_layers, n_adapters+1, d_in,
    r]`` / ``B [n_layers, n_adapters+1, r, d_out]``, with index 0 an
    ALL-ZEROS base adapter (identity delta) so un-adapted rows run the
    same compiled program, and user adapters at 1..n in order. The layer
    axis leads so the decode scan slices it alongside params/cache. All
    adapters must share targets, rank, and shapes — heterogeneous ranks
    would need per-adapter padding, refused instead."""
    if not adapters:
        raise ValueError("need at least one adapter")
    targets = set(adapters[0])
    for a in adapters[1:]:
        if set(a) != targets:
            raise ValueError(
                f"adapters must share targets: {sorted(targets)} vs "
                f"{sorted(a)}"
            )
    bank: Params = {}
    for t in sorted(targets):
        for leaf in ("A", "B"):
            shapes = {a[t][leaf].shape for a in adapters}
            if len(shapes) != 1:
                raise ValueError(
                    f"adapters disagree on {t}/{leaf} shape: {shapes}"
                )
        bank[t] = {
            leaf: jnp.stack(
                [jnp.zeros_like(adapters[0][t][leaf])]
                + [a[t][leaf] for a in adapters],
                axis=1,
            )
            for leaf in ("A", "B")
        }
    return bank


def lora_param_count(lora: Params) -> int:
    return sum(x.size for ab in lora.values() for x in ab.values())
