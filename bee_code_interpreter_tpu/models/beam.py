"""Beam-search decoding over the KV-cached decode path.

Completes the decode-API family (greedy / sampled / speculative / beam).
TPU-first mechanics: beams ride the batch dimension — the cache is tiled to
``B·W`` rows once after prefill, every step is one ``decode_step`` over all
beams, and beam reordering is a batched gather on the cache's batch axis
(``jnp.take``; the standard trade — exact search bookkeeping for one
gather's worth of HBM traffic per step). The whole loop is a ``lax.scan``
with static shapes; ``beam_size=1`` degenerates to greedy and is pinned
token-exact against ``generate_cached`` by tests/test_beam.py.

No EOS semantics: the framework is tokenizer-free (sandboxed users bring
their own vocabulary), so beams are compared by total log-probability at a
fixed length — which is also why there is no length-penalty knob: with
every beam the same length it could only rescale all scores by one
constant, never change the ranking.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    decode_step,
    forward,
    init_decode_cache,
)


def beam_search(
    params,
    config: TransformerConfig,
    prompt: jax.Array,  # [B, L] int32
    max_new_tokens: int = 32,
    beam_size: int = 4,
    return_all: bool = False,
):
    """Highest-log-prob continuation under beam search.

    Returns [B, L + max_new_tokens] (the best beam), or with
    ``return_all`` a tuple of ([B, W, L + max_new_tokens] sequences sorted
    best-first, [B, W] scores).
    """
    c = config
    if not c.moe_exact:
        # capacity-based MoE routes all B·W beam rows in one competing pool,
        # so a beam's tokens/score would depend on which sibling beams share
        # the batch and the score-equals-rescoring pin breaks — same
        # routing-pool-size hazard speculative_generate refuses. This is a
        # property of capacity-based routing, not a missing feature:
        # tests/test_beam.py::test_moe_routing_pool_coupling_demonstrated
        # PROVES it (identical rows, different logits by pool position once
        # capacity saturates); decoupling would need per-beam routing pools,
        # which forfeits the batched expert matmul the MoE path exists for
        # (moe_exact — dropless + per-token groups — removes the
        # competition: no eviction → per-token independent routing →
        # sibling beams decouple bitwise)
        raise NotImplementedError(
            "beam_search requires a moe_exact config — dense, or MoE with "
            "moe_dropless + moe_group_size=1 (capacity routing pools "
            "couple sibling beams); use Transformer.generate_cached for "
            "capacity-routed MoE"
        )
    W = beam_size
    if W < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    if max_new_tokens < 1:
        # 0 would silently drop the first-token scatter (OOB writes are
        # dropped under jit) and return scores for a token not in the output
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    B, L = prompt.shape
    total = L + max_new_tokens

    logits, (k_pre, v_pre) = forward(params, prompt, c, return_kv=True)
    cache = init_decode_cache(c, B, total, k_pre, v_pre)
    # beams ride the batch dim: tile cache rows B -> B*W (beam-major per row)
    cache = jax.tree.map(
        lambda x: jnp.repeat(x, W, axis=1), cache
    )  # leaves [n_layers, B*W, ...]

    # first expansion: top-W distinct first tokens per row
    lp0 = jax.nn.log_softmax(logits[:, L - 1, :], axis=-1)  # [B, V]
    scores, first = lax.top_k(lp0, W)  # [B, W]
    seqs = jnp.zeros((B, W, total), jnp.int32)
    seqs = seqs.at[:, :, :L].set(prompt[:, None, :])
    seqs = seqs.at[:, :, L].set(first)
    current = first.reshape(B * W, 1)

    V = c.vocab_size

    def step(carry, pos):
        seqs, scores, current, cache = carry
        step_logits, cache = decode_step(params, current, pos, cache, c)
        lp = jax.nn.log_softmax(step_logits[:, 0, :], axis=-1)  # [B*W, V]
        joint = scores[:, :, None] + lp.reshape(B, W, V)  # [B, W, V]
        scores, flat = lax.top_k(joint.reshape(B, W * V), W)  # [B, W]
        beam_idx = flat // V  # [B, W] which parent beam
        token = (flat % V).astype(jnp.int32)

        # reorder histories and caches to the winning parents
        seqs = jnp.take_along_axis(seqs, beam_idx[:, :, None], axis=1)
        seqs = seqs.at[:, :, pos + 1].set(token)
        flat_parent = (
            jnp.arange(B, dtype=jnp.int32)[:, None] * W + beam_idx
        ).reshape(B * W)
        cache = jax.tree.map(
            lambda x: jnp.take(x, flat_parent, axis=1), cache
        )
        return (seqs, scores, token.reshape(B * W, 1), cache), None

    (seqs, scores, _, _), _ = lax.scan(
        step,
        (seqs, scores, current, cache),
        jnp.arange(L, total - 1, dtype=jnp.int32),
    )

    order = jnp.argsort(-scores, axis=1)  # best first
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    if return_all:
        return seqs, scores
    return seqs[:, 0]
