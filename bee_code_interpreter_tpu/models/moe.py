"""Mixture-of-Experts MLP with expert parallelism over an ``ep`` mesh axis.

TPU-first design (GShard / Mesh-TensorFlow capacity-based dense dispatch —
NOT a ragged/sort-based CUDA-style implementation):

- Routing produces static-shaped **dispatch** and **combine** tensors
  ``[G, E, C]`` (tokens × experts × capacity slots); token movement is plain
  einsums. No dynamic shapes, no sorting — everything lowers to MXU matmuls
  and XLA keeps the program fully static.
- Expert weights carry a leading ``[n_experts]`` axis sharded over the mesh's
  ``ep`` axis (PartitionSpec ``P('ep', ...)``); the dispatch/combine einsums
  contract the token dimension (sharded over dp/fsdp) against the expert
  dimension (sharded over ep), so **GSPMD inserts the all-to-alls over ICI**
  — the same collective pattern a hand-written MoE would issue, without any
  hand-written communication.
- Tokens over capacity are *dropped* (contribute zero; the residual
  connection carries them), the standard trade for static shapes on TPU.
- An auxiliary load-balancing loss (Shazeer-style: E · Σ_e fraction_e ·
  mean-prob_e) keeps routing from collapsing; the transformer adds it to the
  training loss scaled by ``moe_aux_weight``.

The reference (a code-execution service) has no MoE; this module exists for
the framework's model-family/parallelism completeness: the full dp × ep × tp
training step is exercised on virtual devices by tests/test_moe.py and the
driver's ``dryrun_multichip``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

Params = dict


def init_moe_params(
    key: jax.Array,
    d_model: int,
    ff_dim: int,
    n_experts: int,
) -> Params:
    """Router + per-expert SwiGLU weights (f32 masters, [E, ...] stacked)."""
    k_router, k_gate, k_up, k_down = jax.random.split(key, 4)

    def dense(key, fan_in, *shape):
        return jax.random.normal(key, shape, dtype=jnp.float32) / math.sqrt(fan_in)

    return {
        "router": dense(k_router, d_model, d_model, n_experts),
        "we_gate": dense(k_gate, d_model, n_experts, d_model, ff_dim),
        "we_up": dense(k_up, d_model, n_experts, d_model, ff_dim),
        "we_down": dense(k_down, ff_dim, n_experts, ff_dim, d_model),
    }


def expert_capacity(
    n_tokens: int,
    n_experts: int,
    top_k: int,
    capacity_factor: float,
    dropless: bool = False,
) -> int:
    """Per-expert capacity slots, rounded up to 8 (sublane-friendly tiles).

    ``dropless`` sizes capacity to the worst case — every token in the group
    choosing this expert — so no token can ever be evicted. That makes
    routing per-token independent: a token's expert assignment and combine
    weights depend only on its own router logits, never on batch-mates
    competing for slots. Cost: dispatch/combine grow to [g, E, g] per group
    (quadratic in group size) — affordable for decode-sized groups, which is
    what serving-exactness needs it for."""
    if dropless:
        return max(8, -(-n_tokens // 8) * 8)
    raw = capacity_factor * n_tokens * top_k / n_experts
    return max(8, int(math.ceil(raw / 8)) * 8)


def _route_group(
    xf: jax.Array,  # [g, D] one routing group
    router: jax.Array,  # [D, E]
    *,
    n_experts: int,
    top_k: int,
    capacity: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-group dispatch/combine tensors [g, E, C] + per-group aux loss.

    GShard position-in-expert assignment: earlier tokens (and earlier top-k
    choices) win capacity slots; losers are dropped (combine weight zero —
    the residual stream carries them unchanged). Routing math stays in f32
    (softmax over expert logits is precision-sensitive).
    """
    logits = jnp.einsum(
        "gd,de->ge", xf.astype(jnp.float32), router.astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)

    gate_vals, gate_idx = lax.top_k(probs, top_k)  # [g, k]
    # renormalize the kept gates so the combine weights sum to 1 per token
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9
    )

    C = capacity
    dispatch = jnp.zeros((xf.shape[0], n_experts, C), dtype=jnp.float32)
    combine = jnp.zeros_like(dispatch)
    filled = jnp.zeros((n_experts,), dtype=jnp.int32)
    for j in range(top_k):
        onehot = jax.nn.one_hot(gate_idx[:, j], n_experts, dtype=jnp.int32)
        position = jnp.cumsum(onehot, axis=0) - onehot + filled[None, :]
        filled = filled + onehot.sum(axis=0)
        slot = (position * onehot).sum(axis=-1)  # position in chosen expert
        keep = (slot < C).astype(jnp.float32)
        slot_oh = jax.nn.one_hot(slot, C, dtype=jnp.float32)
        pair = onehot.astype(jnp.float32)[:, :, None] * slot_oh[:, None, :]
        dispatch = dispatch + pair * keep[:, None, None]
        combine = combine + pair * (gate_vals[:, j] * keep)[:, None, None]

    # Load balancing (Shazeer): E · Σ_e (fraction of tokens routed to e) ·
    # (mean router prob of e). Uses the top-1 assignment for the fraction.
    top1 = jax.nn.one_hot(gate_idx[:, 0], n_experts, dtype=jnp.float32)
    aux = n_experts * jnp.sum(top1.mean(axis=0) * probs.mean(axis=0))
    return dispatch, combine, aux


def moe_mlp(
    params: Params,
    x: jax.Array,  # [B, L, D]
    *,
    n_experts: int,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    dtype=jnp.bfloat16,
    group_size: int = 1024,
    dropless: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Returns (output [B, L, D], aux load-balancing loss scalar f32).

    Tokens are routed in fixed-size **groups** (GShard's group dimension):
    dispatch/combine memory is ``G · E · C_group`` with ``C_group`` set by
    the group size, i.e. linear in the global token count — without the
    group axis it is quadratic (capacity itself grows with G). Groups also
    bound the all-to-all message sizes. When the token count doesn't divide
    into groups, routing falls back to one global group.
    """
    B, L, D = x.shape
    G = B * L
    xf = x.reshape(G, D)

    n_groups = max(1, G // group_size)
    if G % n_groups != 0:
        n_groups = 1
    g = G // n_groups
    C = expert_capacity(g, n_experts, top_k, capacity_factor, dropless)

    xg = xf.reshape(n_groups, g, D)
    dispatch, combine, aux = jax.vmap(
        lambda xs: _route_group(
            xs, params["router"], n_experts=n_experts, top_k=top_k, capacity=C
        )
    )(xg)  # [n, g, E, C] ×2, [n]

    # token → expert movement: contraction over the (dp-sharded) token dim
    # against the (ep-sharded) expert dim — GSPMD's all-to-all lives here.
    # The group axis rides along as a batch dim into the expert matmuls
    # ([E, n·C, D] worth of rows per expert).
    expert_in = jnp.einsum(
        "ngec,ngd->necd", dispatch.astype(dtype), xg.astype(dtype)
    )  # [n, E, C, D]
    gate = jnp.einsum("necd,edf->necf", expert_in, params["we_gate"].astype(dtype))
    up = jnp.einsum("necd,edf->necf", expert_in, params["we_up"].astype(dtype))
    expert_out = jnp.einsum(
        "necf,efd->necd", jax.nn.silu(gate) * up, params["we_down"].astype(dtype)
    )  # [n, E, C, D]
    out = jnp.einsum(
        "ngec,necd->ngd", combine.astype(dtype), expert_out
    )  # [n, g, D]

    return out.reshape(B, L, D), aux.mean()
