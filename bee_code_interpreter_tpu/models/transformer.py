"""Llama-style decoder transformer, TPU-first.

Design (idiomatic JAX/XLA, not a port of anything):

- **Pure functional**: params are a pytree of jnp arrays; init/apply/loss/
  train_step are free functions bundled in a thin ``Transformer`` class.
- **Scan over layers**: per-layer params are stacked on a leading [n_layers]
  axis and the decoder body is a single ``lax.scan`` — one layer gets traced
  and compiled once regardless of depth (compile time and HLO size stay flat).
- **bfloat16 compute, float32 master params**: matmuls ride the MXU in bf16
  via a cast at apply time; the optimizer state and params stay f32.
- **GSPMD sharding**: ``param_specs`` gives Megatron-style PartitionSpecs
  (column-parallel wq/wk/wv/w_gate/w_up, row-parallel wo/w_down, replicated
  norms) over the mesh axes that exist; activations are constrained to
  P('dp', 'sp') on (batch, sequence). XLA inserts the all-reduces over ICI.
- **Sequence parallelism** when the mesh has sp > 1: the ppermute ring
  (parallel/ring_attention.py — flash kernel per hop on TPU) or Ulysses
  all-to-all (parallel/ulysses.py), per ``sp_attention`` — long-context is
  a first-class path, not a fallback. On sp == 1 meshes the GQA-native
  Pallas flash kernel (ops/flash_attention.py) runs directly on TPU.

Components: RMSNorm, RoPE, grouped multi-head attention (K/V never
broadcast — compact through kernels, ring, decode), SwiGLU or MoE MLP
(one ``_mlp_block``), next-token cross-entropy with z-loss, AdamW train
step, pipelined forward, KV-cached decode (bf16 or int8 cache),
temperature/top-k/top-p sampling, and the decode_window verify primitive
behind models/speculative.py.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bee_code_interpreter_tpu.parallel.ring_attention import ring_attention

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int | None = None  # grouped-query attention; None = MHA
    d_ff: int | None = None  # None = SwiGLU default 8/3 * d_model rounded
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    z_loss: float = 1e-4
    # Mixture-of-Experts: n_experts > 0 replaces every layer's dense SwiGLU
    # MLP with an expert-parallel MoE MLP (models/moe.py — GShard-style
    # dense dispatch; expert weights shard over the mesh's "ep" axis).
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    moe_group_size: int = 1024  # GShard routing-group size (memory bound)
    # Dropless routing: capacity sized to the worst case so no token is ever
    # evicted — routing becomes per-token independent. With
    # moe_group_size=1 on top (each token routes in its own group, so the
    # expert einsums see pool size only as a batch dim) the forward is
    # BITWISE batch-independent, which restores the batch-isolation /
    # solo-equality bar for SERVING MoE configs — see `moe_exact` below;
    # the guards in serving/beam/speculative key on it. Cost: every token
    # pays all E experts' MLPs (E/top_k × the routed FLOPs) — the price of
    # exactness, not the training configuration.
    moe_dropless: bool = False
    # RoPE linear position interpolation (context extension): effective
    # position = position / rope_scaling. 1.0 = off; e.g. 4.0 runs a model
    # trained at max_seq_len L with positions compressed from 4L into the
    # trained range.
    rope_scaling: float = 1.0
    # Sequence-parallel attention strategy when the mesh has sp > 1:
    # "ring" rotates compact K/V over ppermute (parallel/ring_attention.py);
    # "ulysses" re-shards heads<->sequence with all-to-alls and runs the
    # local flash kernel on the full sequence (parallel/ulysses.py).
    sp_attention: str = "ring"
    # Decode KV-cache storage: "bf16" (compute dtype) or "int8" (symmetric
    # per-token/head absmax quantization, ops/kv_cache.py — halves the bytes
    # the bandwidth-bound decode loop streams per step).
    kv_cache_dtype: str = "bf16"
    # Sliding-window attention (Mistral-style): each query attends only the
    # last `sliding_window` positions. None = full causal attention. The
    # flash kernels skip fully-out-of-window blocks; single-shard/tp meshes
    # only (the sp ring/Ulysses paths don't thread the window).
    sliding_window: int | None = None
    # Single-token paged decode through the Pallas paged-attention kernel
    # (ops/paged_attention.py): pages read IN PLACE via scalar-prefetched
    # block tables instead of paged_read's gather (which materializes a
    # contiguous cache copy every step). Applies to decode_step_paged
    # (W == 1) on bf16 pools with full causal attention; other shapes and
    # the int8 pool keep the einsum path.
    paged_attention_kernel: bool = False

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        # SwiGLU sizing, rounded to 256 for MXU-friendly tiles
        raw = int(8 * self.d_model / 3)
        return (raw + 255) // 256 * 256

    @property
    def moe_exact(self) -> bool:
        """True when per-request outputs are bitwise independent of batch
        composition — dense configs always; MoE configs under dropless
        per-token routing (moe_dropless + moe_group_size=1: no capacity
        eviction, and the expert einsums see the pool only as a batch
        dim). The exactness-claiming features (serving solo-equality,
        prefix cache, speculative verify, beam rescoring) key on this;
        dropless with larger groups is deterministic and ulp-stable but
        reduction tiling varies with pool shape, so near-exact logit ties
        could flip a token."""
        return self.n_experts == 0 or (
            self.moe_dropless and self.moe_group_size == 1
        )

    @classmethod
    def tiny(cls) -> "TransformerConfig":
        """Test/dry-run size."""
        return cls(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                   max_seq_len=128, d_ff=128)

    @classmethod
    def tiny_moe(cls) -> "TransformerConfig":
        """Test/dry-run MoE size (4 experts, top-2 routing)."""
        return cls(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                   max_seq_len=128, d_ff=128, n_experts=4)

    @classmethod
    def llama3_8b(cls) -> "TransformerConfig":
        """The BASELINE.json flagship config (Llama-3-8B shapes)."""
        return cls(vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, d_ff=14336, max_seq_len=8192)

    @classmethod
    def mixtral_8x7b(cls) -> "TransformerConfig":
        """Flagship MoE config (Mixtral-8x7B shapes: 8 experts, top-2)."""
        return cls(vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, d_ff=14336, max_seq_len=8192,
                   n_experts=8, moe_top_k=2)


# ---------------------------------------------------------------- components


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    xf = x.astype(jnp.float32)
    norm = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * scale.astype(jnp.float32)).astype(x.dtype)


def rope(
    x: jax.Array, positions: jax.Array, theta: float, scaling: float = 1.0
) -> jax.Array:
    """Rotary embeddings over [B, H, L, D_head] with positions [B, L].

    ``scaling`` > 1 is linear position interpolation (Chen et al. — effective
    position = position / scaling), the simple context-extension recipe: a
    model trained at L runs at scaling·L with positions compressed back into
    the trained range."""
    if scaling <= 0:
        raise ValueError(f"rope scaling must be > 0, got {scaling}")
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)  # [d/2]
    scaled = positions.astype(jnp.float32) / scaling
    angles = scaled[:, None, :, None] * freqs  # [B,1,L,d/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


def qeinsum(spec: str, x: jax.Array, leaf, dtype) -> jax.Array:
    """Einsum against a weight LEAF that is either a plain array or a
    weight-only-int8 dict ({"q", "s"} — ops/weight_quant.py). Quantized
    leaves compute ``(x @ q) * s``: the per-out-channel scale applied as
    the matmul epilogue (exact algebra), so the int8→compute-dtype convert
    fuses into the dot and no dequantized copy materializes. The ONE
    dispatch point every dense projection in forward/decode shares, which
    is why the quantized pytree is a drop-in everywhere at once."""
    from bee_code_interpreter_tpu.ops.weight_quant import is_quantized

    if is_quantized(leaf):
        y = jnp.einsum(spec, x, leaf["q"].astype(dtype))
        return (y * leaf["s"]).astype(dtype)
    return jnp.einsum(spec, x, leaf.astype(dtype))


# ------------------------------------------------------------------- weights


def init_params(config: TransformerConfig, key: jax.Array) -> Params:
    """f32 master params; stacked [n_layers, ...] leading axis for lax.scan."""
    c = config
    k_embed, k_layers, k_out = jax.random.split(key, 3)

    def dense(key, fan_in, *shape):
        return jax.random.normal(key, shape, dtype=jnp.float32) / math.sqrt(fan_in)

    def layer(key):
        ks = jax.random.split(key, 7)
        dh, kvh = c.head_dim, c.kv_heads
        out = {
            "ln1": jnp.ones((c.d_model,), jnp.float32),
            "wq": dense(ks[0], c.d_model, c.d_model, c.n_heads * dh),
            "wk": dense(ks[1], c.d_model, c.d_model, kvh * dh),
            "wv": dense(ks[2], c.d_model, c.d_model, kvh * dh),
            "wo": dense(ks[3], c.n_heads * dh, c.n_heads * dh, c.d_model),
            "ln2": jnp.ones((c.d_model,), jnp.float32),
        }
        if c.n_experts:
            from bee_code_interpreter_tpu.models.moe import init_moe_params

            out["moe"] = init_moe_params(ks[4], c.d_model, c.ff_dim, c.n_experts)
        else:
            out["w_gate"] = dense(ks[4], c.d_model, c.d_model, c.ff_dim)
            out["w_up"] = dense(ks[5], c.d_model, c.d_model, c.ff_dim)
            out["w_down"] = dense(ks[6], c.ff_dim, c.ff_dim, c.d_model)
        return out

    layer_keys = jax.random.split(k_layers, c.n_layers)
    stacked = jax.vmap(layer)(layer_keys)
    return {
        "embed": dense(k_embed, c.d_model, c.vocab_size, c.d_model),
        "layers": stacked,
        "ln_f": jnp.ones((c.d_model,), jnp.float32),
        "lm_head": dense(k_out, c.d_model, c.d_model, c.vocab_size),
    }


def param_specs(config: TransformerConfig, mesh: Mesh) -> Params:
    """Megatron-style PartitionSpecs over whichever of (fsdp, tp, ep) exist."""
    tp = "tp" if "tp" in mesh.axis_names else None
    fsdp = "fsdp" if "fsdp" in mesh.axis_names else None
    ep = "ep" if "ep" in mesh.axis_names else None

    col = P(fsdp, tp)      # [d_in, d_out/tp] column-parallel
    row = P(tp, fsdp)      # [d_in/tp, d_out] row-parallel
    rep = P()
    layer = {
        "ln1": _stack(rep), "ln2": _stack(rep),
        "wq": _stack(col), "wk": _stack(col), "wv": _stack(col),
        "wo": _stack(row),
    }
    if config.n_experts:
        # expert axis over ep, expert-internal matmuls Megatron-style
        layer["moe"] = {
            "router": _stack(P(None, None)),  # small; replicated
            "we_gate": _stack(P(ep, fsdp, tp)),
            "we_up": _stack(P(ep, fsdp, tp)),
            "we_down": _stack(P(ep, tp, fsdp)),
        }
    else:
        layer["w_gate"] = _stack(col)
        layer["w_up"] = _stack(col)
        layer["w_down"] = _stack(row)
    return {
        "embed": P(tp, None),     # vocab-sharded embedding
        "layers": layer,
        "ln_f": rep,
        "lm_head": P(None, tp),   # column-parallel output projection
    }


def _stack(spec: P) -> P:
    return P(None, *spec)  # leading n_layers axis is replicated


def shard_params(params: Params, config: TransformerConfig, mesh: Mesh) -> Params:
    """Place params per ``param_specs``. Weight-only-quantized leaves
    ({'q','s'} — ops/weight_quant.py) shard too: q takes the fp weight's
    spec verbatim, and s (per-out-channel, shape = weight shape minus the
    contracted axis) takes the spec with the d_in axis dropped — so a
    tp-column-sharded weight keeps its scales on the same shards and
    qeinsum's epilogue multiply stays local (no collective)."""
    from bee_code_interpreter_tpu.ops.weight_quant import is_quantized

    specs = param_specs(config, mesh)

    def place(x, spec):
        if is_quantized(x):
            s_spec = P(*spec[:-2], spec[-1])
            return {
                "q": jax.device_put(x["q"], NamedSharding(mesh, spec)),
                "s": jax.device_put(x["s"], NamedSharding(mesh, s_spec)),
            }
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(
        place, params, specs,
        is_leaf=lambda x: is_quantized(x)
        or isinstance(x, jnp.ndarray) or hasattr(x, "shape"),
    )


# ------------------------------------------------------------------- forward


def _local_attention(q, k, v, causal: bool = True, window: int | None = None):
    """Single-shard attention — the shared ops-level platform dispatch
    (Pallas flash on TPU, reference elsewhere; GQA-native)."""
    from bee_code_interpreter_tpu.ops.flash_attention import local_attention

    return local_attention(q, k, v, causal=causal, window=window)


def _attention(
    q, k, v, mesh: Mesh | None, sp_attention: str = "ring",
    causal: bool = True, window: int | None = None,
):
    """Attention (causal by default; ``causal=False`` for encoders — the
    ViT path); q [B, H, L, D], k/v [B, KVH, L, D] (KVH ≤ H).

    K/V stay compact through the whole path (flash kernel index-maps KV
    heads, the ring rotates KVH-sized blocks) — GQA never materializes the
    head broadcast, saving H/KVH × KV HBM/ICI traffic.

    With a mesh, runs inside shard_map — batch over dp, heads over tp,
    sequence over sp. Manual SPMD is required here anyway: GSPMD cannot
    partition a pallas_call, and the sp > 1 path needs explicit collectives
    (the ppermute ring, or Ulysses' all-to-alls per ``sp_attention``).
    """
    if sp_attention not in ("ring", "ulysses"):
        raise ValueError(
            f"sp_attention must be 'ring' or 'ulysses', got {sp_attention!r}"
        )
    if mesh is None:
        return _local_attention(q, k, v, causal, window)
    axes = mesh.axis_names
    tp = "tp" if "tp" in axes else None
    has_sp = "sp" in axes and mesh.shape["sp"] > 1
    sp = "sp" if has_sp else None
    if tp is not None and k.shape[1] % mesh.shape["tp"] != 0:
        # KV heads don't split over tp: broadcast up — but only to
        # lcm(KVH, tp), the minimal multiple that shards evenly (both divide
        # n_heads, so the lcm does too and group-major q→kv pairing is
        # preserved); repeating all the way to n_heads would multiply KV
        # HBM/ICI traffic in exactly the KV-bandwidth-bound regime the
        # compact-GQA path exists for
        rep = math.lcm(k.shape[1], mesh.shape["tp"]) // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    spec = P(_batch_axes(mesh), tp, sp, None)

    if has_sp:
        # sliding_window rides both sp strategies: the ring masks per hop in
        # global offsets (parallel/ring_attention.py), Ulysses applies the
        # ordinary local mask after its sequence gather (parallel/ulysses.py)
        if sp_attention == "ulysses":
            from bee_code_interpreter_tpu.parallel.ulysses import (
                ulysses_attention,
            )

            local = functools.partial(
                ulysses_attention, axis_name="sp", causal=causal,
                window=window,
            )
        else:
            local = functools.partial(
                ring_attention, axis_name="sp", causal=causal, window=window
            )
    else:
        local = functools.partial(_local_attention, causal=causal, window=window)
    # pallas_call under shard_map's vma checking hits a jax-internal lowering
    # limitation (see tests/test_parallel.py flash-ring cases); every
    # uses_flash() branch here runs the kernel (local, flash-hop ring, or
    # inside ulysses), so disable the check exactly there and keep it for
    # the kernel-free CPU paths.
    from bee_code_interpreter_tpu.ops.flash_attention import uses_flash
    from bee_code_interpreter_tpu.parallel.mesh import shard_map_compat

    uses_pallas = uses_flash()
    fn = shard_map_compat(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=not uses_pallas,
    )
    return fn(q, k, v)


def _layer_apply(
    h: jax.Array,  # [B, L, D]
    layer: Params,
    config: TransformerConfig,
    positions: jax.Array,  # [B, L]
    *,
    mesh: Mesh | None = None,
    constrain=lambda x: x,
    return_kv: bool = False,
) -> tuple[jax.Array, tuple | None, jax.Array]:
    """One decoder layer — THE single source of the layer math, shared by
    ``forward`` (mesh attention + sharding constraints via the hooks) and
    ``forward_pipelined`` (single-shard defaults). Returns
    (h, kv_out | None, aux-loss scalar)."""
    c = config
    B, L = h.shape[0], h.shape[1]
    x = rms_norm(h, layer["ln1"])
    dh, nh, kvh = c.head_dim, c.n_heads, c.kv_heads

    def proj(w, heads):
        out = qeinsum("bld,dk->blk", x, w, c.dtype)
        return out.reshape(B, L, heads, dh).transpose(0, 2, 1, 3)

    q = rope(proj(layer["wq"], nh), positions, c.rope_theta, c.rope_scaling)
    k = rope(proj(layer["wk"], kvh), positions, c.rope_theta, c.rope_scaling)
    v = proj(layer["wv"], kvh)
    kv_out = (k, v) if return_kv else None
    # GQA-native: compact k/v go in as-is
    attn = _attention(q, k, v, mesh, c.sp_attention, window=c.sliding_window)
    attn = attn.transpose(0, 2, 1, 3).reshape(B, L, nh * dh)
    h = h + constrain(qeinsum("blk,kd->bld", attn, layer["wo"], c.dtype))

    y = rms_norm(h, layer["ln2"])
    mlp, aux = _mlp_block(y, layer, c)
    h = h + constrain(mlp)
    return h, kv_out, aux


def _mlp_block(
    y: jax.Array, layer: Params, config: TransformerConfig
) -> tuple[jax.Array, jax.Array]:
    """The post-attention MLP (dense SwiGLU or MoE) — ONE copy shared by
    _layer_apply, decode_window (and through it decode_step), and
    decode_step_paged. Returns (mlp_out, aux) with aux = 0.0 for dense
    configs (decode paths drop it)."""
    c = config
    if c.n_experts:
        from bee_code_interpreter_tpu.models.moe import moe_mlp

        return moe_mlp(
            layer["moe"], y,
            n_experts=c.n_experts, top_k=c.moe_top_k,
            capacity_factor=c.moe_capacity_factor, dtype=c.dtype,
            group_size=c.moe_group_size, dropless=c.moe_dropless,
        )
    gate = qeinsum("bld,df->blf", y, layer["w_gate"], c.dtype)
    up = qeinsum("bld,df->blf", y, layer["w_up"], c.dtype)
    mlp = qeinsum("blf,fd->bld", jax.nn.silu(gate) * up, layer["w_down"], c.dtype)
    return mlp, jnp.float32(0.0)


def _batch_axes(mesh: Mesh | None):
    """Activation batch dim shards over every data-parallel-ish axis present
    (shared policy: parallel.mesh.batch_axes)."""
    from bee_code_interpreter_tpu.parallel.mesh import batch_axes

    return batch_axes(mesh)


def forward(
    params: Params,
    tokens: jax.Array,  # [B, L] int32
    config: TransformerConfig,
    mesh: Mesh | None = None,
    return_kv: bool = False,
    return_aux: bool = False,
) -> jax.Array | tuple:
    """Returns logits [B, L, vocab] (f32).

    With ``return_kv`` (the prefill half of cached decoding), also returns the
    per-layer post-RoPE K/V stacked [n_layers, B, kv_heads, L, head_dim] —
    pre-GQA-broadcast, so the cache stores kv_heads not n_heads.
    With ``return_aux`` (MoE training), also returns the summed per-layer
    load-balancing auxiliary loss (0.0 for dense configs).
    """
    c = config
    use_ring = mesh is not None and "sp" in mesh.axis_names and (
        mesh.shape["sp"] > 1
    )

    def act_spec(*spec):  # noqa: D401
        if mesh is None:
            return None
        return NamedSharding(mesh, P(*spec))

    def constrain(x, *spec):
        if mesh is None:
            return x
        return lax.with_sharding_constraint(x, act_spec(*spec))

    B, L = tokens.shape
    sp = "sp" if use_ring else None
    batch_ax = _batch_axes(mesh)
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (B, L))

    h = params["embed"].astype(c.dtype)[tokens]  # [B, L, D]
    h = constrain(h, batch_ax, sp, None)

    def layer_step(h, layer):
        h, kv_out, aux = _layer_apply(
            h, layer, c, positions,
            mesh=mesh,
            constrain=lambda x: constrain(x, batch_ax, sp, None),
            return_kv=return_kv,
        )
        return h, (kv_out, aux)

    h, (kv, aux_layers) = lax.scan(layer_step, h, params["layers"])
    h = rms_norm(h, params["ln_f"])
    logits = qeinsum("bld,dv->blv", h, params["lm_head"], c.dtype)
    logits = logits.astype(jnp.float32)
    extras = []
    if return_kv:
        extras.append(kv)
    if return_aux:
        extras.append(aux_layers.sum())
    if extras:
        return (logits, *extras)
    return logits


# -------------------------------------------------------------- pipelined fwd


def forward_pipelined(
    params: Params,
    tokens: jax.Array,  # [B, L] int32
    config: TransformerConfig,
    mesh: Mesh,
    n_microbatches: int,
    return_aux: bool = False,
) -> jax.Array | tuple:
    """Pipeline-parallel forward: the layer stack sharded over the mesh's
    ``pp`` axis, microbatches (batch-dim splits) streamed through the GPipe
    schedule (parallel/pipeline.py); batch additionally shards over dp/fsdp
    axes when present. Embedding / final norm / lm head run outside the
    pipeline. Differentiable — ``jax.grad`` through this is pipeline-parallel
    training. tp/sp inside stages would need nested shard_map; use the
    non-pipelined ``forward`` for those axes instead.

    MoE configs ride the pipeline's aux carry: each stage returns its
    layers' load-balancing loss, masked to real (non-bubble) ticks and
    averaged over microbatches (``with_aux`` in spmd_pipeline) — equal to a
    sequential per-microbatch forward. Note routing pools are per
    microbatch: under capacity pressure tokens compete within their
    microbatch, not the full batch, so logits match the non-pipelined
    ``forward`` only drop-free (ample capacity) — the same caveat as cached
    decode (see ``generate_cached``)."""
    from bee_code_interpreter_tpu.parallel.pipeline import spmd_pipeline

    c = config
    if c.n_experts and not return_aux:
        # training MoE without the load-balancing term drives experts toward
        # collapse; fail loudly rather than silently discard it (inference
        # callers pass return_aux=True and drop the scalar)
        raise ValueError(
            "MoE configs require return_aux=True on forward_pipelined: the "
            "load-balancing aux loss must reach the objective"
        )
    B, L = tokens.shape
    if B % n_microbatches != 0:
        raise ValueError(
            f"batch {B} not divisible into {n_microbatches} microbatches"
        )

    h = params["embed"].astype(c.dtype)[tokens]  # [B, L, D]

    batch_axes = _batch_axes(mesh) or ()

    def stage(h, layer):
        # batch-dim microbatching: absolute positions are simply 0..L-1 for
        # every row, whatever shard of the batch this stage holds
        pos = jnp.broadcast_to(
            jnp.arange(h.shape[1], dtype=jnp.int32), h.shape[:2]
        )
        h, _, aux = _layer_apply(h, layer, c, pos)
        return h, aux

    h, aux = spmd_pipeline(
        stage, params["layers"], h,
        mesh=mesh, n_microbatches=n_microbatches, batch_axes=batch_axes,
        with_aux=True,
    )
    h = rms_norm(h, params["ln_f"])
    logits = qeinsum("bld,dv->blv", h, params["lm_head"], c.dtype)
    logits = logits.astype(jnp.float32)
    if return_aux:
        return logits, aux
    return logits


# ------------------------------------------------------------- cached decode


def alloc_decode_cache(
    config: TransformerConfig, B: int, total_len: int
) -> dict:
    """Zeroed decode cache in the configured layout. bf16 stores values
    directly; int8 adds per-(token, head) scale leaves — the presence of
    scales is what selects the quantized strategy in ops/kv_cache.py."""
    c = config
    shape = (c.n_layers, B, c.kv_heads, total_len, c.head_dim)
    if c.kv_cache_dtype == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_s": jnp.zeros(shape[:-1] + (1,), jnp.float32),
            "v_s": jnp.zeros(shape[:-1] + (1,), jnp.float32),
        }
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def init_decode_cache(
    config: TransformerConfig,
    B: int,
    total_len: int,
    k_pre: jax.Array,  # [n_layers, B, kvh, L_prompt, Dh] (prefill K)
    v_pre: jax.Array,
) -> dict:
    """Allocate the full-length decode cache and seed it with the prefill
    K/V through the same append strategy the decode bodies use."""
    from bee_code_interpreter_tpu.ops.kv_cache import cache_append

    return cache_append(
        alloc_decode_cache(config, B, total_len), k_pre, v_pre, 0
    )


def decode_step(
    params: Params,
    token: jax.Array,  # [B, 1] int32 — the token just produced/fed
    pos: jax.Array,  # scalar int32: its position in the sequence
    cache: dict,  # init_decode_cache layout; leaves [n_layers, B, kvh, max, ·]
    config: TransformerConfig,
) -> tuple[jax.Array, dict]:
    """One incremental decode step: O(L) attention against the cache instead
    of the O(L^2) full re-encode (the round-1 generate). Static shapes: the
    cache is allocated at its final length and masked by position, so the
    whole decode loop is one compiled program.

    Runs with plain einsum attention (no pallas/shard_map): a 1-token query
    is MXU-trivial and GSPMD can shard these einsums over tp on its own.
    With ``kv_cache_dtype="int8"`` the cache stays int8 in HBM (half the
    bytes the bandwidth-bound loop streams); dequantization rides the
    attention einsums' operand pipeline.

    This IS ``decode_window`` with W=1 for both cache layouts — ONE layer
    body (cache strategy selected by ops/kv_cache.cache_append/cache_read),
    so the int8 and bf16 decode math cannot drift apart.
    """
    return decode_window(params, token, pos, cache, config)


def decode_window(
    params: Params,
    tokens: jax.Array,  # [B, W] int32 — W consecutive tokens
    pos0: jax.Array,  # scalar int32: position of tokens[:, 0]
    cache: dict,  # init_decode_cache layout
    config: TransformerConfig,
    mesh: Mesh | None = None,
) -> tuple[jax.Array, dict]:
    """Multi-token cached decode: like ``decode_step`` but for a window of
    ``W`` consecutive tokens at positions ``pos0..pos0+W-1`` — one forward
    over the window with causal masking against the (updated) cache. This
    is speculative decoding's verify step: the target model scores a
    drafted window in ONE pass instead of W sequential steps.

    Static shapes throughout (W is static; ``pos0`` is dynamic). Both cache
    layouts: the int8 strategy quantizes the window per (token, head) row —
    each row's scale is independent, so a window append is bit-identical to
    W single-step appends and the speculative verify stays exact over the
    quantized cache.

    ``mesh``: decode attention is plain einsums, so GSPMD shards them from
    the param shardings on its own; the constraint here just pins the
    activation batch to the data axes (same annotation level as ``forward``)
    so a chunked prefill on a sharded model lays out like the decode loop.
    """
    c = config
    B, W = tokens.shape
    max_len = cache["k"].shape[3]
    positions = pos0 + jnp.arange(W, dtype=jnp.int32)[None, :]  # [1, W]
    positions = jnp.broadcast_to(positions, (B, W))

    def constrain(x):
        if mesh is None:
            return x
        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(_batch_axes(mesh), None, None))
        )

    h = constrain(params["embed"].astype(c.dtype)[tokens])  # [B, W, D]

    def layer_step(h, scanned):
        layer, c_layer = scanned
        x = rms_norm(h, layer["ln1"])
        dh, nh, kvh = c.head_dim, c.n_heads, c.kv_heads

        def proj(w, heads):
            out = qeinsum("bld,dk->blk", x, w, c.dtype)
            return out.reshape(B, W, heads, dh).transpose(0, 2, 1, 3)

        q = rope(
            proj(layer["wq"], nh), positions, c.rope_theta, c.rope_scaling
        )  # [B,nh,W,Dh]
        k_new = rope(proj(layer["wk"], kvh), positions, c.rope_theta, c.rope_scaling)
        v_new = proj(layer["wv"], kvh)
        from bee_code_interpreter_tpu.ops.kv_cache import (
            cache_append,
            cache_read,
        )

        c_layer = cache_append(c_layer, k_new, v_new, pos0)
        kf, vf = cache_read(c_layer, c.dtype)  # kf f32, vf c.dtype

        rep = nh // kvh
        qg = q.reshape(B, kvh, rep, W, dh).astype(jnp.float32)
        scores = jnp.einsum("bgrwd,bgsd->bgrws", qg, kf) / math.sqrt(dh)
        # row w (position pos0+w) sees cache positions s <= pos0+w (and
        # within the sliding window when configured)
        row_pos = (pos0 + jnp.arange(W))[:, None]  # [W, 1]
        visible = jnp.arange(max_len)[None, :] <= row_pos  # [W, max]
        if c.sliding_window is not None:
            visible &= (
                jnp.arange(max_len)[None, :] > row_pos - c.sliding_window
            )
        scores = jnp.where(
            visible[None, None, None, :, :], scores, -jnp.inf
        )
        weights = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
        attn = jnp.einsum("bgrws,bgsd->bgrwd", weights, vf)
        attn = attn.transpose(0, 3, 1, 2, 4).reshape(B, W, nh * dh)
        h = h + constrain(
            qeinsum("blk,kd->bld", attn, layer["wo"], c.dtype)
        )

        y = rms_norm(h, layer["ln2"])
        mlp, _ = _mlp_block(y, layer, c)
        h = h + constrain(mlp)
        return h, c_layer

    h, cache = lax.scan(layer_step, h, (params["layers"], cache))
    h = rms_norm(h, params["ln_f"])
    logits = qeinsum("bld,dv->blv", h, params["lm_head"], c.dtype)
    return logits.astype(jnp.float32), cache


def decode_step_paged(
    params: Params,
    token: jax.Array,  # [B, 1] int32 — each row's current token
    pos: jax.Array,  # [B] int32 — PER-ROW positions (heterogeneous lengths)
    cache: dict,  # ops/paged_kv_cache.alloc_paged_cache pool
    block_table: jax.Array,  # [B, P] int32 logical block -> physical page
    config: TransformerConfig,
    lora_bank: dict | None = None,
    adapter_idx: jax.Array | None = None,
    lora_scale: float = 1.0,
) -> tuple[jax.Array, dict]:
    """One incremental decode step over the PAGED cache — the serving-side
    sibling of ``decode_step``. This IS ``decode_window_paged`` with W=1
    (one body, mirroring the contiguous decode_step/decode_window
    unification)."""
    return decode_window_paged(
        params, token, pos, cache, block_table, config,
        lora_bank, adapter_idx, lora_scale,
    )


def decode_window_paged(
    params: Params,
    tokens: jax.Array,  # [B, W] int32 — W consecutive tokens per row
    pos0: jax.Array,  # [B] int32 — PER-ROW position of tokens[:, 0]
    cache: dict,  # ops/paged_kv_cache.alloc_paged_cache pool
    block_table: jax.Array,  # [B, P] int32 logical block -> physical page
    config: TransformerConfig,
    lora_bank: dict | None = None,  # {target: {A: [n_layers, n_adapters, d, r], B: ...}}
    adapter_idx: jax.Array | None = None,  # [B] int32 per-row adapter
    lora_scale: float = 1.0,
) -> tuple[jax.Array, dict]:
    """Multi-token cached decode over the PAGED pool with PER-ROW window
    positions — the verify primitive for speculative decoding INSIDE
    continuous batching: each row scores its own drafted window at its own
    cursor in one pass, rows at heterogeneous lengths together
    (models/serving.py). The serving-side sibling of ``decode_window``.

    The layer math is decode_window's grouped-query einsums verbatim; only
    the cache indexing differs (a row's W tokens may straddle a page
    boundary — one scatter either way), so paged-vs-contiguous equality is
    an indexing property (pinned by tests/test_paged_kv_cache.py,
    including permuted page tables). Both pool layouts — int8 pools carry
    per-row scale planes per page and append/read quantize exactly like
    the contiguous strategy. Rows whose slots would exceed the table's
    page budget are a scheduler bug (the scatter clamps).

    ``lora_bank`` enables MULTI-LoRA serving (S-LoRA style): a stacked
    bank of adapters for the attention projections, with ``adapter_idx``
    selecting each row's adapter — heterogeneous adapters decode together
    in ONE compiled program. The delta is applied unmerged
    (``x@A[idx]@B[idx]·scale`` — two rank-r einsums per target, tiny next
    to the base matmul), so the shared base weights stream from HBM once
    for the whole batch regardless of how many adapters ride on it.
    ``lora_bank is None`` is a static (trace-time) branch: the base path
    is untouched. Pinned by tests/test_multilora_serving.py.
    """
    from bee_code_interpreter_tpu.ops.paged_kv_cache import (
        paged_append,
        paged_read,
    )

    c = config
    B, W = tokens.shape
    if lora_bank is not None:
        if adapter_idx is None:
            raise ValueError("lora_bank needs adapter_idx")
        unknown = set(lora_bank) - {"wq", "wk", "wv", "wo"}
        if unknown:
            raise ValueError(
                f"lora_bank targets {sorted(unknown)} unsupported in the "
                "decode path (attention projections only)"
            )
    page_size = cache["k"].shape[3]
    S = block_table.shape[1] * page_size
    positions = pos0[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]  # [B, W]
    page_idx = jnp.take_along_axis(
        block_table, positions // page_size, axis=1
    )  # [B, W]
    slot_idx = positions % page_size

    h = params["embed"].astype(c.dtype)[tokens]  # [B, W, D]

    def layer_step(h, scanned):
        if lora_bank is None:
            layer, c_layer = scanned  # pool slices [n_pages, kvh, ps, dh]
            lora_layer = {}
        else:
            layer, c_layer, lora_layer = scanned
        x = rms_norm(h, layer["ln1"])
        dh, nh, kvh = c.head_dim, c.n_heads, c.kv_heads

        def lora_delta(x_in, name):
            if name not in lora_layer:
                return None
            Ab = lora_layer[name]["A"][adapter_idx].astype(c.dtype)  # [B,d,r]
            Bb = lora_layer[name]["B"][adapter_idx].astype(c.dtype)  # [B,r,o]
            return jnp.einsum(
                "blr,bro->blo", jnp.einsum("bld,bdr->blr", x_in, Ab), Bb
            ) * jnp.asarray(lora_scale, c.dtype)

        def proj(w, heads, name):
            out = qeinsum("bld,dk->blk", x, w, c.dtype)
            delta = lora_delta(x, name)
            if delta is not None:
                out = out + delta
            return out.reshape(B, W, heads, dh).transpose(0, 2, 1, 3)

        q = rope(proj(layer["wq"], nh, "wq"), positions, c.rope_theta, c.rope_scaling)
        k_new = rope(proj(layer["wk"], kvh, "wk"), positions, c.rope_theta, c.rope_scaling)
        v_new = proj(layer["wv"], kvh, "wv")
        c_layer = paged_append(
            c_layer,
            k_new.transpose(0, 2, 1, 3),  # [B, W, kvh, dh]
            v_new.transpose(0, 2, 1, 3),
            page_idx, slot_idx,
        )
        if (
            c.paged_attention_kernel and W == 1
            and "k_s" not in c_layer and c.sliding_window is None
        ):
            # in-place page reads: no gathered cache copy (see the config
            # field / ops/paged_attention.py)
            from bee_code_interpreter_tpu.ops.paged_attention import (
                paged_decode_attention,
            )

            attn = paged_decode_attention(
                q[:, :, 0, :], c_layer["k"], c_layer["v"], block_table,
                positions[:, 0] + 1,
            ).reshape(B, 1, nh * dh).astype(c.dtype)
        else:
            kf, vf = paged_read(c_layer, block_table, c.dtype)  # [B,kvh,S,dh]

            rep = nh // kvh
            qg = q.reshape(B, kvh, rep, W, dh).astype(jnp.float32)
            scores = jnp.einsum("bgrwd,bgsd->bgrws", qg, kf) / math.sqrt(dh)
            # row (b, w) sees cache positions s <= pos0_b + w (and within
            # the sliding window when configured)
            visible = (
                jnp.arange(S)[None, None, :] <= positions[:, :, None]
            )  # [B, W, S]
            if c.sliding_window is not None:
                visible &= (
                    jnp.arange(S)[None, None, :]
                    > positions[:, :, None] - c.sliding_window
                )
            scores = jnp.where(visible[:, None, None, :, :], scores, -jnp.inf)
            weights = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
            attn = jnp.einsum("bgrws,bgsd->bgrwd", weights, vf)
            attn = attn.transpose(0, 3, 1, 2, 4).reshape(B, W, nh * dh)
        o = qeinsum("blk,kd->bld", attn, layer["wo"], c.dtype)
        delta_o = lora_delta(attn, "wo")
        if delta_o is not None:
            o = o + delta_o
        h = h + o

        y = rms_norm(h, layer["ln2"])
        mlp, _ = _mlp_block(y, layer, c)
        h = h + mlp
        return h, c_layer

    scanned = (
        (params["layers"], cache) if lora_bank is None
        else (params["layers"], cache, lora_bank)
    )
    h, cache = lax.scan(layer_step, h, scanned)
    h = rms_norm(h, params["ln_f"])
    logits = qeinsum("bld,dv->blv", h, params["lm_head"], c.dtype)
    return logits.astype(jnp.float32), cache


def prefill_chunked(
    params: Params,
    prompt: jax.Array,  # [B, L] int32
    config: TransformerConfig,
    total_len: int,
    chunk: int = 512,
    mesh: Mesh | None = None,
) -> tuple[jax.Array, dict]:
    """Build the decode cache by streaming the prompt through
    ``decode_window`` in fixed-size chunks instead of one O(L²) forward —
    activation memory is bounded by the chunk (attention scores are
    [B, H, chunk, L] instead of [B, H, L, L]), the standard long-prompt
    prefill. Returns (last-position logits [B, vocab], cache) — exactly
    what starting decode needs; per-chunk causality is decode_window's
    position masking, so the result is pinned equal to the full forward
    (tests/test_chunked_prefill.py).

    Full chunks run under one ``lax.scan`` (one compile); a static
    remainder chunk (L % chunk) adds at most one more.
    """
    c = config
    B, L = prompt.shape
    if L == 0:
        # an empty prompt yields no last_logits to start decode from; fail
        # here, not later in sample_logits with an opaque None error
        raise ValueError("prompt must be non-empty (L >= 1)")
    if total_len < L:
        # an undersized cache would be silently corrupted: clamped
        # dynamic_update_slice writes shift later chunks onto earlier rows
        raise ValueError(
            f"total_len ({total_len}) must cover the prompt length ({L})"
        )
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    cache = alloc_decode_cache(c, B, total_len)

    n_full, rem = divmod(L, chunk)
    last_logits = None
    if n_full:
        chunks = prompt[:, : n_full * chunk].reshape(B, n_full, chunk)

        def body(cache, x):
            toks, pos0 = x
            logits, cache = decode_window(params, toks, pos0, cache, c, mesh)
            return cache, logits[:, -1, :]

        cache, last_per_chunk = lax.scan(
            body,
            cache,
            (
                chunks.transpose(1, 0, 2),  # [n_full, B, chunk]
                jnp.arange(n_full, dtype=jnp.int32) * chunk,
            ),
        )
        last_logits = last_per_chunk[-1]
    if rem:
        logits, cache = decode_window(
            params, prompt[:, n_full * chunk :], jnp.int32(n_full * chunk),
            cache, c, mesh,
        )
        last_logits = logits[:, -1, :]
    return last_logits, cache


# ----------------------------------------------------------------- sampling


def sample_logits(
    logits: jax.Array,  # [B, V] f32
    key: jax.Array,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Next-token selection: greedy at ``temperature == 0`` (exact argmax),
    otherwise categorical over temperature-scaled logits with optional
    top-k then top-p (nucleus) filtering. All filters are static-shape
    (mask-to--inf, no dynamic vocab slicing) so the decode loop stays one
    compiled program. Returns [B, 1] int32."""
    if top_k is not None and top_k < 1:
        # validated regardless of temperature: a config tested greedy-first
        # must fail fast, not only when sampling is later enabled
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    x = filter_logits(logits / temperature, top_k, top_p)
    return jax.random.categorical(key, x, axis=-1).astype(jnp.int32)[:, None]


def filter_logits(
    x: jax.Array,  # [B, V] temperature-scaled logits
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """THE definition of the sampling filters (mask-to--inf): top-k, then
    nucleus — keep the smallest prefix of the descending-prob order whose
    mass reaches ``top_p``, always at least the top token. ``sample_logits``
    draws from this on device; serving's host-side sampler mirrors it in
    numpy with parity pinned against this function
    (tests/test_serving.py::test_host_filter_parity_with_device)."""
    if top_k is not None:
        kth = lax.top_k(x, top_k)[0][:, -1:]  # [B, 1] k-th largest
        x = jnp.where(x >= kth, x, -jnp.inf)
    if top_p is not None:
        sort_idx = jnp.argsort(-x, axis=-1)
        sorted_x = jnp.take_along_axis(x, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_x, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_p  # mass BEFORE this token < p
        # position 0 of the descending order is the top token: always
        # eligible, so degenerate top_p (<= 0) cannot mask the whole vocab
        keep_sorted = keep_sorted.at[:, 0].set(True)
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(x.shape[0])[:, None], sort_idx
        ].set(keep_sorted)
        x = jnp.where(keep, x, -jnp.inf)
    return x


# ---------------------------------------------------------------- loss/train


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],  # tokens [B, L], targets [B, L]
    config: TransformerConfig,
    mesh: Mesh | None = None,
) -> jax.Array:
    logits, aux = forward(
        params, batch["tokens"], config, mesh, return_aux=True
    )
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(
        logits, batch["targets"][..., None], axis=-1
    )[..., 0]
    nll = logz - target_logit
    # z-loss keeps logits from drifting (stability at bf16)
    loss = nll + config.z_loss * logz**2
    # MoE load-balancing term (0.0 for dense configs)
    return loss.mean() + config.moe_aux_weight * aux


class Transformer:
    """Config + mesh bundle with jitted apply/train_step factories."""

    def __init__(self, config: TransformerConfig, mesh: Mesh | None = None) -> None:
        self.config = config
        self.mesh = mesh

    def init(self, key: jax.Array) -> Params:
        params = init_params(self.config, key)
        if self.mesh is not None:
            params = shard_params(params, self.config, self.mesh)
        return params

    def apply(self, params: Params, tokens: jax.Array) -> jax.Array:
        return forward(params, tokens, self.config, self.mesh)

    def make_optimizer(self, learning_rate: float = 3e-4):
        return optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=0.1)

    def make_train_step(self, optimizer=None):
        optimizer = optimizer or self.make_optimizer()

        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, self.config, self.mesh
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return jax.jit(train_step, donate_argnums=(0, 1))

    def batch_sharding(self) -> NamedSharding | None:
        if self.mesh is None:
            return None
        sp = "sp" if "sp" in self.mesh.axis_names else None
        return NamedSharding(self.mesh, P(_batch_axes(self.mesh), sp))

    # ------------------------------------------------------------- generate

    def generate(
        self, params: Params, prompt: jax.Array, max_new_tokens: int = 32
    ) -> jax.Array:
        """Greedy decode (no KV cache; full-sequence re-encode per step —
        the simple correctness path; cached decode is the listed follow-up)."""
        B, L = prompt.shape
        total = L + max_new_tokens
        tokens = jnp.zeros((B, total), dtype=jnp.int32).at[:, :L].set(prompt)

        def step(carry, idx):
            tokens = carry
            logits = forward(params, tokens, self.config, self.mesh)
            # logits at position idx-1 predict token idx
            prev = lax.dynamic_slice_in_dim(logits, idx - 1, 1, axis=1)  # [B,1,V]
            next_tok = jnp.argmax(prev, axis=-1).astype(jnp.int32)  # [B,1]
            tokens = lax.dynamic_update_slice(tokens, next_tok, (0, idx))
            return tokens, None

        tokens, _ = lax.scan(
            step, tokens, jnp.arange(L, total), length=max_new_tokens
        )
        return tokens

    def generate_cached(
        self,
        params: Params,
        prompt: jax.Array,
        max_new_tokens: int = 32,
        *,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        key: jax.Array | None = None,
        eos_id: int | None = None,
        prefill_chunk: int | None = None,
    ) -> jax.Array:
        """KV-cached decode: one O(L^2) prefill, then ``max_new_tokens - 1``
        O(L) incremental steps (decode_step). Default is greedy
        (``temperature=0``) and pinned equal to ``generate`` by
        tests/test_models.py; ``temperature``/``top_k``/``top_p`` select
        sampled decoding (``sample_logits``; ``key`` defaults to PRNGKey(0)
        and is split per step, so a fixed key is fully deterministic).
        ``eos_id`` freezes a row once it emits that token — every later
        position repeats ``eos_id`` (static shapes: the loop always runs
        ``max_new_tokens`` steps; finished rows just stop changing).
        ``prefill_chunk`` streams the prompt through ``prefill_chunked``
        instead of one O(L²) forward (long prompts in bounded memory;
        either cache layout — note the int8 cache's prefill attention reads
        progressively quantized K/V, the same semantics incremental decode
        has, where the full prefill attends in exact bf16 before
        quantizing). For
        MoE configs greedy equality holds only drop-free (ample capacity):
        under capacity pressure the full forward routes tokens in
        competition while decode routes each token alone — inherent to
        capacity-based MoE (tests/test_moe.py)."""
        c = self.config
        B, L = prompt.shape
        total = L + max_new_tokens
        if key is None:
            key = jax.random.PRNGKey(0)

        if prefill_chunk is not None:
            last_logits, cache = prefill_chunked(
                params, prompt, c, total, chunk=prefill_chunk, mesh=self.mesh
            )
        else:
            logits, (k_pre, v_pre) = forward(
                params, prompt, c, self.mesh, return_kv=True
            )
            cache = init_decode_cache(c, B, total, k_pre, v_pre)
            last_logits = logits[:, L - 1, :]

        key, sub = jax.random.split(key)
        first = sample_logits(last_logits, sub, temperature, top_k, top_p)
        tokens = (
            jnp.zeros((B, total), dtype=jnp.int32)
            .at[:, :L].set(prompt)
            .at[:, L : L + 1].set(first)
        )

        done0 = (
            (first == eos_id) if eos_id is not None
            else jnp.zeros_like(first, dtype=bool)
        )

        def step(carry, pos):
            tokens, current, cache, key, done = carry
            step_logits, cache = decode_step(params, current, pos, cache, c)
            key, sub = jax.random.split(key)
            next_tok = sample_logits(
                step_logits[:, -1, :], sub, temperature, top_k, top_p
            )
            if eos_id is not None:
                next_tok = jnp.where(done, jnp.int32(eos_id), next_tok)
                done = done | (next_tok == eos_id)
            tokens = lax.dynamic_update_slice(tokens, next_tok, (0, pos + 1))
            return (tokens, next_tok, cache, key, done), None

        (tokens, _, _, _, _), _ = lax.scan(
            step,
            (tokens, first, cache, key, done0),
            jnp.arange(L, total - 1, dtype=jnp.int32),
        )
        return tokens
