"""Speculative decoding (greedy draft-verify) for the transformer family.

A small draft model proposes ``gamma`` greedy tokens from its own KV cache
(``decode_step`` ×γ — cheap), then the target model scores the whole window
in ONE cached forward (``decode_window``) and commits the longest prefix on
which the draft matched its own greedy choice, plus the target's correction
token. Greedy verification is **exact**: the output equals the target's own
greedy decode token-for-token, for ANY draft — the draft only changes how
many target forwards are needed (pinned by tests/test_speculative.py with
both a perfect draft and an unrelated random draft). One caveat: "the
target's greedy decode" here means argmax of the window forward's logits,
which agree with single-step decode only up to rounding (same math,
different contraction shapes); at f32 the difference is ~1e-6 and argmax
flips are vanishing, at bf16 a near-tied argmax can land differently —
rounding noise, not an algorithmic divergence.

TPU-first mechanics:

- One compiled program: the outer accept loop is a ``lax.while_loop`` over
  a cursor into a statically-sized token buffer (padded by γ+2 so the
  fixed-width window writes never clamp near the end); the per-round accept
  length is data-dependent, the shapes never are.
- **No cache rewind**: rejected draft positions do write K/V into both
  caches, but every cache read is masked by query position (``s ≤ p``), so
  stale entries beyond the committed cursor are invisible until the real
  token overwrites them. Rewind logic — the fiddly part of most
  implementations — falls out of the position-masked cache design. This
  holds for the int8 target cache too: quantization scales are per
  (token, head) row, so a stale row's scale is overwritten with its row
  and never contaminates neighbours.
- **Lockstep batches**: the committed length per round is the minimum
  accept length over the batch. Rows that matched further simply recommit
  the same tokens next round — still exact, keeps every cache update a
  single scalar-position slice.

The draft can be any Transformer config/params sharing the vocab (typically
fewer layers / smaller d_model).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    decode_step,
    decode_window,
    forward,
    init_decode_cache,
)


def speculative_generate(
    target_params,
    target_config: TransformerConfig,
    draft_params,
    draft_config: TransformerConfig,
    prompt: jax.Array,  # [B, L] int32
    max_new_tokens: int = 32,
    gamma: int = 4,
) -> jax.Array:
    """Greedy decode of the TARGET model, accelerated by the draft.

    Returns [B, L + max_new_tokens] — token-for-token equal to
    ``Transformer(target_config).generate_cached(target_params, prompt,
    max_new_tokens)``.
    """
    tc, dc = target_config, draft_config
    if tc.vocab_size != dc.vocab_size:
        raise ValueError("target and draft must share a vocabulary")
    if not tc.moe_exact:
        # capacity-based MoE routing depends on the routing-pool size: the
        # verify window routes B·(γ+1) tokens where plain greedy decode
        # routes B·1, so under capacity pressure the two can drop different
        # tokens and the exactness guarantee breaks. Refuse rather than be
        # silently approximate (same stance as forward_pipelined's aux
        # guard); MoE DRAFTS are fine — drafts only propose. The hazard is
        # proven executable in tests/test_beam.py::
        # test_moe_routing_pool_coupling_demonstrated.
        # (moe_exact targets — dropless + per-token groups — route each
        # token independently: window size stops mattering and the
        # exactness guarantee holds bitwise)
        raise NotImplementedError(
            "speculative_generate requires a moe_exact target — dense, or "
            "MoE with moe_dropless + moe_group_size=1 (capacity routing "
            "pools differ between the verify window and plain decode); "
            "use Transformer.generate_cached for capacity-routed MoE "
            "targets"
        )
    B, L = prompt.shape
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    # window writes are fixed-width γ+1 starting at pos+1; pad so the last
    # round's write stays in bounds (dynamic_update_slice clamps the start
    # index when an update would overflow — which would silently shift the
    # write onto committed tokens)
    buf = L + max_new_tokens + gamma + 2

    t_logits, (tk, tv) = forward(target_params, prompt, tc, return_kv=True)
    target_cache = init_decode_cache(tc, B, buf, tk, tv)
    _, (dk, dv) = forward(draft_params, prompt, dc, return_kv=True)
    draft_cache = init_decode_cache(dc, B, buf, dk, dv)

    first = jnp.argmax(t_logits[:, L - 1, :], axis=-1).astype(jnp.int32)
    tokens = (
        jnp.zeros((B, buf), dtype=jnp.int32)
        .at[:, :L].set(prompt)
        .at[:, L].set(first)
    )
    last = L + max_new_tokens - 1  # buffer index of the final token

    def round_body(state):
        tokens, pos, target_cache, draft_cache = state
        current = lax.dynamic_slice_in_dim(tokens, pos, 1, axis=1)  # [B, 1]

        # --- draft proposes γ greedy tokens from (current, pos) ----------
        def draft_step(carry, _):
            tok, p, cache = carry
            lg, cache = decode_step(draft_params, tok, p, cache, dc)
            nxt = jnp.argmax(lg[:, -1:, :], axis=-1).astype(jnp.int32)
            return (nxt, p + 1, cache), nxt

        (_, _, draft_cache), drafts = lax.scan(
            draft_step, (current, pos, draft_cache), None, length=gamma
        )
        drafts = drafts[:, :, 0].T  # [γ, B, 1] -> [B, γ]

        # --- target verifies the whole window in one forward --------------
        window = jnp.concatenate([current, drafts], axis=1)  # [B, γ+1]
        t_logits, target_cache = decode_window(
            target_params, window, pos, target_cache, tc
        )
        t_pred = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, γ+1]

        # longest leading run where the draft equals the target's greedy
        # choice; lockstep across the batch (min) keeps positions scalar
        match = (drafts == t_pred[:, :gamma]).astype(jnp.int32)  # [B, γ]
        lead = jnp.cumprod(match, axis=1)
        n = jnp.min(lead.sum(axis=1)).astype(jnp.int32)  # scalar in [0, γ]

        # commit drafts[:, :n] at pos+1.. and the target's token at pos+n+1;
        # slots beyond n get the bonus value too — they sit past the cursor,
        # invisible and overwritten by later rounds
        bonus = jnp.take_along_axis(t_pred, jnp.full((B, 1), n), axis=1)
        idx = jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]
        vals = jnp.where(idx < n, jnp.pad(drafts, ((0, 0), (0, 1))), bonus)
        tokens = lax.dynamic_update_slice(tokens, vals, (0, pos + 1))
        return tokens, pos + n + 1, target_cache, draft_cache

    def cond(state):
        return state[1] < last

    tokens, _, _, _ = lax.while_loop(
        cond, round_body, (tokens, jnp.int32(L), target_cache, draft_cache)
    )
    return tokens[:, : L + max_new_tokens]
