"""Data-parallel serving: a router over N engine replicas.

Tensor parallelism (``ContinuousBatcher(mesh=...)``) scales one model
instance ACROSS chips; this module scales throughput by running N
independent replicas — each its own ``Engine`` over its own batcher, placed
on its own device (or its own tp sub-mesh) — behind one submit/step/result
surface. The dp × tp product is the standard serving topology (one replica
per tp-group, a router in front); the reference has no serving stack at all
(SURVEY §2).

Routing is least-outstanding by default. With ``prefix_affinity=True``
requests are STICKY by prompt prefix: the first block-sized chunk of the
prompt hashes to a preferred replica, so repeat prompts land where their
prefix-cache pages live (affinity yields to load when the preferred replica
is more than ``affinity_slack`` requests busier than the idlest — a cache
hit is not worth unbounded queueing).

Host-side only: each replica's device work is exactly the single-engine
path, stepped in turn from this one loop. Production deployments run one
process per replica and an RPC router; this in-process form is the
library-level mechanism (and the virtual-device test target:
tests/test_replicated.py drives 2 replicas × tp=2 over 4 devices).
"""

from __future__ import annotations

import hashlib

import numpy as np

from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)


class ReplicatedEngine:
    def __init__(
        self,
        engines: list[Engine],
        prefix_affinity: bool = False,
        affinity_slack: int = 4,
    ) -> None:
        if not engines:
            raise ValueError("need at least one engine replica")
        self.engines = engines
        self.prefix_affinity = prefix_affinity
        self.affinity_slack = affinity_slack
        self._ticket = 0
        self._submitted = 0  # monotonic, unlike the live-ticket map
        # global ticket -> (replica index, replica-local ticket)
        self._where: dict[int, tuple[int, int]] = {}

    # ------------------------------------------------------------ building

    @classmethod
    def build(
        cls,
        params,
        config,
        n_replicas: int,
        meshes: list | None = None,
        prefix_affinity: bool = False,
        affinity_slack: int = 4,
        max_queue: int | None = None,
        **batcher_kw,
    ) -> "ReplicatedEngine":
        """N fresh replicas from one host copy of the params.

        ``meshes`` places each replica (one mesh per replica — single-device
        meshes for plain dp, tp meshes over disjoint device subsets for
        dp × tp). Default: one single-device mesh per replica over the
        first ``n_replicas`` devices, i.e. pure data parallelism."""
        import jax
        from jax.sharding import Mesh

        if meshes is None:
            devices = jax.devices()
            if len(devices) < n_replicas:
                raise ValueError(
                    f"{n_replicas} replicas need {n_replicas} devices, "
                    f"have {len(devices)}"
                )
            meshes = [
                Mesh(np.array(devices[i : i + 1]), ("tp",))
                for i in range(n_replicas)
            ]
        if len(meshes) != n_replicas:
            raise ValueError(
                f"got {len(meshes)} meshes for {n_replicas} replicas"
            )
        engines = [
            Engine(
                ContinuousBatcher(params, config, mesh=mesh, **batcher_kw),
                max_queue=max_queue,
            )
            for mesh in meshes
        ]
        return cls(
            engines,
            prefix_affinity=prefix_affinity,
            affinity_slack=affinity_slack,
        )

    # ------------------------------------------------------------- routing

    def _outstanding(self, i: int) -> int:
        # O(1): queue depth + occupied rows. (Engine.stats would work but
        # iterates every ticket ever submitted — wrong cost for a routing
        # hot path.)
        engine = self.engines[i]
        return (
            engine.pending
            + int(engine.batcher.active.sum())
            + len(engine.batcher.prefill_state)
        )

    def _route_order(self, prompt: np.ndarray) -> list[int]:
        """Replica indices in routing-preference order: least-outstanding
        first (affinity-preferred first when it's within the slack); later
        entries are the fallbacks when a replica's queue bound rejects."""
        loads = [self._outstanding(i) for i in range(len(self.engines))]
        order = sorted(range(len(self.engines)), key=lambda i: loads[i])
        if self.prefix_affinity:
            page = self.engines[0].batcher.page_size
            digest = hashlib.blake2b(
                prompt[:page].tobytes(), digest_size=8
            ).digest()
            preferred = int.from_bytes(digest, "big") % len(self.engines)
            if loads[preferred] <= loads[order[0]] + self.affinity_slack:
                order.remove(preferred)
                order.insert(0, preferred)
        return order

    def _route(self, prompt: np.ndarray) -> int:
        return self._route_order(prompt)[0]

    # -------------------------------------------------------------- intake

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        sampling: SamplingParams | None = None,
        **engine_kwargs,
    ) -> int:
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        # A full queue on the routed replica must not reject a request
        # another replica could take: try in preference order. Validation
        # errors (ValueError/NotImplementedError) propagate immediately —
        # they fail identically on every replica.
        last_full: RuntimeError | None = None
        for replica in self._route_order(prompt):
            try:
                local = self.engines[replica].submit(
                    prompt, max_new_tokens, sampling=sampling,
                    **engine_kwargs,
                )
            except RuntimeError as e:  # queue full on this replica
                last_full = e
                continue
            ticket = self._ticket
            self._ticket += 1
            self._where[ticket] = (replica, local)
            self._submitted += 1
            return ticket
        raise RuntimeError(
            f"every replica's queue is full ({last_full})"
        ) from last_full

    # --------------------------------------------------------------- step

    def step(self) -> None:
        for engine in self.engines:
            engine.step()

    def run_to_completion(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if all(
                engine.pending == 0 and not engine.batcher.busy
                for engine in self.engines
            ):
                return
            self.step()
        raise RuntimeError("run_to_completion exceeded max_steps")

    # ------------------------------------------------------------- results

    def _local(self, ticket: int) -> tuple[Engine, int]:
        if ticket not in self._where:
            raise KeyError(f"unknown ticket {ticket}")
        replica, local = self._where[ticket]
        return self.engines[replica], local

    def replica_of(self, ticket: int) -> int:
        """Which replica a ticket landed on (observability/testing)."""
        if ticket not in self._where:
            raise KeyError(f"unknown ticket {ticket}")
        return self._where[ticket][0]

    def is_done(self, ticket: int) -> bool:
        engine, local = self._local(ticket)
        return engine.is_done(local)

    def result(self, ticket: int) -> list[int]:
        engine, local = self._local(ticket)
        return engine.result(local)

    def result_logprobs(self, ticket: int) -> list[float]:
        engine, local = self._local(ticket)
        return engine.result_logprobs(local)

    def finish_reason(self, ticket: int) -> str:
        engine, local = self._local(ticket)
        return engine.finish_reason(local)

    def ticket_error(self, ticket: int) -> str | None:
        engine, local = self._local(ticket)
        return engine.ticket_error(local)

    def partial_result(self, ticket: int) -> list[int]:
        engine, local = self._local(ticket)
        return engine.partial_result(local)

    def new_tokens(self, ticket: int) -> list[int]:
        engine, local = self._local(ticket)
        return engine.new_tokens(local)

    def cancel(self, ticket: int) -> None:
        engine, local = self._local(ticket)
        engine.cancel(local)

    def release(self, ticket: int) -> None:
        engine, local = self._local(ticket)
        engine.release(local)
        del self._where[ticket]

    # -------------------------------------------------------------- stats

    @property
    def pending(self) -> int:
        return sum(engine.pending for engine in self.engines)

    @property
    def stats(self) -> dict:
        """Aggregate counters plus a per-replica breakdown."""
        per = [engine.stats for engine in self.engines]
        agg = {
            "replicas": len(per),
            "queued": sum(s["queued"] for s in per),
            "active_rows": sum(s["active_rows"] for s in per),
            "requests_submitted": self._submitted,  # monotonic
            "live_tickets": len(self._where),  # shrinks on release
            "per_replica": per,
        }
        return agg
