"""Continuous batching over the paged KV cache.

The serving loop the paged cache exists for: requests of heterogeneous
lengths share one decode batch and one physical page pool. A request is
admitted into a free batch row the moment one exists (no waiting for the
whole batch to drain — "continuous" as opposed to static batching), its
prompt is prefilled into freshly allocated pages, and every ``step()``
advances ALL active rows by one token through a single compiled
``decode_step_paged`` program. Finished rows (EOS or budget) free their
pages immediately for the next admission.

TPU-first split of responsibilities:

- **Device**: one jitted fixed-shape program per step — [max_batch]-wide
  regardless of how many rows are live (idle rows compute into a reserved
  scratch page and are ignored). Shapes never depend on occupancy, so the
  program compiles once.
- **Host**: integer bookkeeping only — the free-page stack, block tables,
  row admission/retirement. Mutating a block table or recycling pages is
  numpy work between steps, never a re-trace.

Greedy decoding matches ``Transformer.generate_cached`` token-for-token
per request (pinned by tests/test_serving.py) — batching other requests
alongside cannot change a request's output, which is the correctness bar
for continuous batching.

That bar applies to every ``config.moe_exact`` config — dense, or MoE
with ``moe_dropless`` + ``moe_group_size=1``.
Capacity-based MoE routing pools couple whatever tokens share a forward
pass (an inherent property of the GShard scheme — tests/test_moe.py
documents that even solo decode-vs-forward only matches drop-free), so
capacity-routed MoE requests here route against their batch-mates and the
padded admission prompt: outputs are deterministic per pool state but not
pinned equal to solo decode. Speculative mode and the prefix cache refuse
capacity-routed MoE because their guarantees are exactness claims; plain
serving keeps it usable under the same documented caveat as the rest of
the decode family (pinned deterministic by tests/test_serving_stops.py).
With ``moe_dropless`` (worst-case expert capacity: no token can ever be
evicted) plus per-token routing groups (``moe_group_size=1``, making pool
size a mere batch dim of the expert einsums) routing is bitwise per-token
independent, the solo-equality pin holds (tests/test_serving.py), and
every serving feature accepts the config. The price is every token paying
all E experts' MLPs — an inference-exactness configuration, not a
training one.

Sampling is PER REQUEST (temperature / top-k / top-p / seed — the
heterogeneity serving actually needs) and runs host-side on the step's
logits: the device program stays one fixed-shape greedy-agnostic forward,
while each row draws from its own seeded ``numpy`` Generator — fully
deterministic per request and independent of what shares the batch.

The reference has no model serving at all (SURVEY §2); within this rebuild
the batcher is the library-level analogue of the service's warm sandbox
pool: admit, run isolated, recycle.
"""

from __future__ import annotations

import functools
import hashlib
import time
from collections import OrderedDict, deque
from contextlib import nullcontext
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    decode_step_paged,
    decode_window_paged,
    forward,
    prefill_chunked,
)
from bee_code_interpreter_tpu.ops.paged_kv_cache import (
    alloc_paged_cache,
    seed_from_contiguous,
    seed_prefill,
)
from bee_code_interpreter_tpu.parallel.mesh import mesh_shape_key
from bee_code_interpreter_tpu.utils.jitwatch import TrackedJit

# physical page 0 is the scratch page: idle rows' block tables point at it,
# so their (masked, ignored) reads and writes never touch a live request's
# pages; the allocator never hands it out.
_SCRATCH_PAGE = 0


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decoding knobs — the same semantics as
    ``transformer.sample_logits`` (greedy at temperature 0; otherwise
    categorical over temperature-scaled logits with top-k, then
    smallest-set-above-top-p filtering, always keeping at least the top
    token), drawn from a per-request seeded generator so a request's
    output never depends on its batch-mates.

    ``stop_sequences`` are token-id sequences: generation retires the
    moment the output ends with any of them, and the matched sequence is
    TRIMMED from the result (the common serving-API contract; ``eos_id``
    stays in the output by comparison). ``logprobs=True`` records the
    model's log-probability of each emitted token — under the UNFILTERED
    distribution (log-softmax of the raw logits row), so a sampled
    token's report doesn't change with top-k/top-p settings — and that
    stays true under bias/constraints: the report is always the MODEL's
    probability of the emitted token, however the sampler was steered.

    ``logit_bias`` maps token id -> additive bias on the raw logits
    before selection (the OpenAI-style knob: strongly negative bans a
    token, strongly positive forces it). ``allowed_tokens`` is the
    grammar hook: a callable receiving the tokens GENERATED SO FAR for
    this request (prompt excluded) and returning the iterable of token
    ids currently permitted, or None for "unconstrained this step" —
    everything else is masked to -inf. A grammar/JSON engine plugs in by
    closing over its own parser state. Both run host-side per row; the
    device program stays constraint-agnostic and fixed-shape."""

    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int = 0
    stop_sequences: tuple[tuple[int, ...], ...] = ()
    logprobs: bool = False
    logit_bias: tuple[tuple[int, float], ...] = ()
    allowed_tokens: object = None  # Callable[[list[int]], Iterable[int] | None]

    def __post_init__(self) -> None:
        # same fail-fast rule as sample_logits: validated regardless of
        # temperature, so a greedy-tested config can't blow up later
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        # normalize so callers can pass lists/dicts; frozen dataclass needs
        # object.__setattr__ for the canonicalized copies
        object.__setattr__(
            self, "stop_sequences",
            tuple(tuple(int(t) for t in s) for s in self.stop_sequences),
        )
        if any(len(s) == 0 for s in self.stop_sequences):
            raise ValueError("stop sequences must be non-empty")
        bias = self.logit_bias
        if isinstance(bias, dict):
            bias = tuple(sorted(bias.items()))
        object.__setattr__(
            self, "logit_bias",
            tuple((int(t), float(b)) for t, b in bias),
        )
        if self.allowed_tokens is not None and not callable(
            self.allowed_tokens
        ):
            raise ValueError("allowed_tokens must be callable or None")

    @property
    def steered(self) -> bool:
        """True when selection needs the full logits row on host (bias or
        constraint active) even for a greedy request."""
        return bool(self.logit_bias) or self.allowed_tokens is not None


def logprob_of(logits: np.ndarray, token: int) -> float:
    """log P(token) under the raw (unfiltered) logits row — stable
    log-softmax in f64, the one copy both the plain and speculative steps
    use so reported logprobs cannot drift between paths."""
    lg = logits.astype(np.float64)
    m = lg.max()
    return float(lg[token] - m - np.log(np.exp(lg - m).sum()))


def filtered_probs_host(
    logits: np.ndarray, params: SamplingParams
) -> np.ndarray:
    """The numpy mirror of ``transformer.filter_logits`` + softmax for one
    row — pure host math so the decode loop never dispatches per-row jax
    ops through a (possibly tunneled) device. Tie semantics match the
    device filter exactly (top-k keeps >= kth; nucleus order is a stable
    descending argsort; top token always kept) — pinned by
    tests/test_serving.py::test_host_filter_parity_with_device."""
    lg = logits.astype(np.float64) / params.temperature
    if params.top_k is not None:
        kth = np.partition(lg, -params.top_k)[-params.top_k]
        lg = np.where(lg < kth, -np.inf, lg)
    if params.top_p is not None:
        order = np.argsort(-lg, kind="stable")
        probs = np.exp(lg[order] - lg[order[0]])
        probs /= probs.sum()
        keep = np.cumsum(probs) - probs < params.top_p  # smallest set > p
        keep[0] = True  # at least the top token (device-filter parity:
        # top_p <= 0 would otherwise mask the whole vocab into NaNs)
        lg[order[~keep]] = -np.inf
    probs = np.exp(lg - lg.max())
    return probs / probs.sum()


def sample_host(
    logits: np.ndarray,  # [V] f32
    params: SamplingParams,
    rng: np.random.Generator,
) -> int:
    """One host-side draw mirroring ``sample_logits`` for a single row."""
    if params.temperature <= 0.0:
        return int(np.argmax(logits))
    probs = filtered_probs_host(logits, params)
    return int(rng.choice(logits.shape[0], p=probs))


class ConstraintExhausted(Exception):
    """The ``allowed_tokens`` constraint permits no continuation — a
    grammar reaching its terminal state. NORMAL control flow, not an
    error: the batcher retires the request with finish reason
    'constraint' (empty output if it happens at admission)."""


class CapacityError(RuntimeError):
    """``submit`` found no free row / not enough free pages RIGHT NOW —
    transient backpressure, retryable after a ``step`` frees capacity.
    Subclasses RuntimeError for callers that catch broadly, but exists so
    the serving engine can requeue on capacity alone: jaxlib's
    XlaRuntimeError also subclasses RuntimeError, and a device failure
    during admission prefill must reach the error-ticket path, not spin
    in the queue forever."""


def choose_host(
    logits: np.ndarray,  # [V] f32 — RAW model logits for this row
    params: SamplingParams,
    rng: np.random.Generator,
    generated: list[int],
) -> int:
    """Full per-row selection: apply ``logit_bias`` and the
    ``allowed_tokens`` constraint to a copy of the raw row, then greedy
    argmax or the ``sample_host`` draw. ``generated`` is this request's
    output so far (prompt excluded) — the constraint callable's input.
    Raises ConstraintExhausted when the constraint returns an empty set
    (grammar complete), ValueError on out-of-vocab ids."""
    if params.steered:
        logits = logits.astype(np.float64, copy=True)
        for token, bias in params.logit_bias:
            logits[token] += bias
        if params.allowed_tokens is not None:
            allowed = params.allowed_tokens(list(generated))
            if allowed is not None:
                idx = np.fromiter(
                    (int(t) for t in allowed), dtype=np.int64
                )
                if idx.size == 0:
                    raise ConstraintExhausted(
                        "allowed_tokens permits no continuation"
                    )
                if (idx < 0).any() or (idx >= logits.shape[0]).any():
                    raise ValueError(
                        "allowed_tokens returned out-of-vocab token ids"
                    )
                mask = np.full(logits.shape, -np.inf)
                mask[idx] = 0.0
                logits = logits + mask
    return sample_host(logits, params, rng)


def rejection_sample_commit(
    proposals,  # gamma draft proposals, x_g ~ q_dists[g]
    q_dists,  # gamma FILTERED draft distributions [V]
    p_fn,  # g -> FILTERED target distribution [V], g in [0, gamma]
    rng: np.random.Generator,
) -> tuple[list[int], int]:
    """Leviathan et al. rejection sampling for one verify window: accept
    proposal x with probability min(1, p(x)/q(x)); the first rejection
    resamples from normalize(max(p - q, 0)); a fully-accepted window
    draws its bonus token from the last target distribution. Returns
    (committed tokens, accepted proposal count). Target distributions
    come through ``p_fn`` LAZILY — a rejection at position k never pays
    for the filters beyond k+1. Acceptance uses strict ``<`` so a token
    outside the target's filtered support (p(x) == 0) can never commit,
    whatever ``rng.random()`` returns.

    The guarantee — each committed token is distributed EXACTLY per its
    target distribution, whatever the draft proposed — is pinned
    distributionally by tests/test_speculative_sampling.py against this
    function directly (end-to-end token marginals mix too many
    conditionals for statistical power)."""
    commit: list[int] = []
    n = 0
    for g, x in enumerate(proposals):
        p_dist, q_dist = p_fn(g), q_dists[g]
        x = int(x)
        if q_dist[x] > 0 and rng.random() < min(
            1.0, float(p_dist[x] / q_dist[x])
        ):
            commit.append(x)
            n += 1
            continue
        resid = np.maximum(p_dist - q_dist, 0.0)
        total = float(resid.sum())
        if total <= 0.0:  # p == q pointwise: resample from p directly
            resid, total = p_dist, float(p_dist.sum())
        commit.append(int(rng.choice(resid.shape[0], p=resid / total)))
        return commit, n
    p_last = p_fn(len(proposals))
    commit.append(int(rng.choice(p_last.shape[0], p=p_last)))
    return commit, n


class ContinuousBatcher:
    """Admit → step → collect loop over ``decode_step_paged``.

    ``max_batch`` bounds concurrent requests; ``n_pages``/``page_size``
    size the shared pool; ``max_pages_per_seq`` is the block-table width
    (the static gather width per step, so it bounds prompt+generation
    length at ``max_pages_per_seq * page_size``).
    """

    def __init__(
        self,
        params,
        config: TransformerConfig,
        *,
        max_batch: int = 8,
        n_pages: int = 64,
        page_size: int = 16,
        max_pages_per_seq: int = 8,
        eos_id: int | None = None,
        draft_params=None,
        draft_config: TransformerConfig | None = None,
        gamma: int = 4,
        prefix_cache: bool = False,
        adapters: list | None = None,
        lora_scale: float = 1.0,
        mesh=None,
        metrics=None,
        monitor=None,
    ) -> None:
        """``draft_params``/``draft_config`` switch the batcher into
        SPECULATIVE mode: every step, the draft proposes ``gamma`` greedy
        tokens per active row (its own paged pool, same pages), the target
        scores each row's window in ONE ``decode_window_paged`` pass, and
        each row commits its own accept length — per-row cursors mean no
        lockstep minimum across the batch (the continuous-batching
        advantage over ``speculative_generate``'s static batch). Greedy
        rows carry the exact draft-verify guarantee (pinned by
        tests/test_serving.py); sampled rows decode via REJECTION
        SAMPLING (see ``_step_speculative_sampled``) — distributed
        exactly as plain sampled decoding from the target. Bias and
        allowed_tokens constraints remain unsupported in speculative
        mode.

        ``prefix_cache=True`` turns on vLLM-style prompt prefix caching:
        full prompt pages are content-addressed by chain hash and shared
        across requests (refcounted, LRU-evicted under pool pressure, kept
        alive past retirement for repeat prompts), and a hit admits through
        a suffix-only prefill — per-request outputs are unchanged, pinned
        by tests/test_prefix_cache.py.

        ``adapters`` turns on MULTI-LoRA serving (S-LoRA style): a list of
        LoRA pytrees (``models/lora.py``, attention-projection targets)
        stacked into one device bank; ``submit(adapter=i)`` serves request
        rows under adapter i — heterogeneous adapters decode together in
        one compiled program, the shared base weights streaming from HBM
        once for the whole batch. Adapter admissions prefill through the
        page-aligned window path (lora- AND quantization-aware — adapters
        serve on a weight-only-int8 base too); decode applies the delta
        unmerged per row; both use ``lora_scale`` (alpha/rank). The
        prefix cache keys pages by (adapter, tokens), so requests under
        different adapters never share K/V. Pinned equal to solo decode
        on the merged params by tests/test_multilora_serving.py.

        ``mesh`` turns on TENSOR-PARALLEL serving: params shard under the
        Megatron specs (``transformer.shard_params``) and the K/V page
        pool shards its head axis over the mesh's ``tp`` axis; the decode
        /prefill/window programs compile under GSPMD, which inserts the
        tp collectives (row-parallel psum, vocab-sharded logits gather)
        — the host-side scheduling loop is unchanged. Requires
        ``kv_heads % tp == 0`` (and the draft's, in speculative mode);
        block tables and token streams stay replicated. The solo-equality
        bar holds WITHIN a mesh (row independence is sharding-invariant);
        cross-mesh token equality additionally holds in the pinned test
        configs but reduction-order ulps make it environment-pinned, not
        guaranteed (tests/test_serving_mesh.py)."""
        self.params = params
        self.mesh = mesh
        # duck-typed observability.DeviceMonitor (compile/retrace tracking
        # + per-mesh-shape step telemetry); injected via
        # DeviceMonitor.attach -> set_device_monitor. None keeps every
        # tracked-jit call a single falsy check. The shape key tags step
        # records so multi-shape fleets aggregate per mesh.
        self._device_monitor = None
        self._mesh_key = mesh_shape_key(mesh)
        if mesh is not None:
            from bee_code_interpreter_tpu.models.transformer import (
                shard_params,
            )

            tp = mesh.shape.get("tp", 1)
            if config.kv_heads % tp:
                raise ValueError(
                    f"kv_heads {config.kv_heads} not divisible by tp={tp}"
                )
            sp = mesh.shape.get("sp", 1)
            if sp > 1 and page_size % sp:
                # padded admission widths are page multiples; the sp
                # attention chunks the sequence axis sp ways, so every
                # admission width must divide
                raise ValueError(
                    f"page_size {page_size} not divisible by sp={sp} "
                    "(sp admission chunks the padded prompt)"
                )
            if sp > 1 and config.sp_attention == "ulysses":
                # Ulysses all-to-alls the HEAD axis: validate its
                # divisibility at construction, not at the first submit's
                # jit trace (a server must refuse a config it can never
                # admit under)
                for name, heads in (
                    ("n_heads", config.n_heads),
                    ("kv_heads", config.kv_heads),
                ):
                    if heads % sp:
                        raise ValueError(
                            f"{name} {heads} not divisible by sp={sp} "
                            "(ulysses sp admission shards heads)"
                        )
            if draft_config is not None and draft_config.kv_heads % tp:
                raise ValueError(
                    f"draft kv_heads {draft_config.kv_heads} not divisible "
                    f"by tp={tp}"
                )
            self.params = shard_params(params, config, mesh)
        self.config = config
        self.page_size = page_size
        self.eos_id = eos_id
        self.max_len = max_pages_per_seq * page_size
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.gamma = gamma
        if prefix_cache and not config.moe_exact:
            # capacity-based MoE routing pools couple tokens that share a
            # forward pass: the suffix-only prefill routes W tokens where
            # the full prefill routes L, so shared-prefix K/V would stop
            # being the K/V an unshared admission computes — the same
            # routing-pool hazard beam/speculative refuse
            # (tests/test_beam.py::test_moe_routing_pool_coupling_demonstrated).
            # moe_exact (dropless + per-token groups) removes the coupling
            # bitwise, so those configs pass.
            raise NotImplementedError(
                "prefix_cache requires a moe_exact config — dense, or MoE "
                "with moe_dropless + moe_group_size=1 (capacity routing "
                "pools differ between suffix-only and full prefill)"
            )
        self.prefix_cache_enabled = prefix_cache
        self.lora_scale = float(lora_scale)
        # only the stacked bank is kept: holding the original adapter
        # pytrees too would double adapter memory for the server's life
        self.n_adapters = len(adapters) if adapters else 0
        if adapters:
            from bee_code_interpreter_tpu.models.lora import stack_lora_bank

            self.lora_bank = stack_lora_bank(list(adapters))
            unknown = set(self.lora_bank) - {"wq", "wk", "wv", "wo"}
            if unknown:
                raise ValueError(
                    f"serving adapters target {sorted(unknown)}; the decode "
                    "path supports attention projections (wq/wk/wv/wo) only"
                )
        else:
            self.lora_bank = None
        self.row_adapter = np.zeros(max_batch, dtype=np.int32)
        if (draft_params is None) != (draft_config is None):
            raise ValueError(
                "speculative mode needs BOTH draft_params and draft_config"
            )
        if draft_config is not None:
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError("target and draft must share a vocabulary")
            if not config.moe_exact:
                # same routing-pool hazard speculative_generate refuses:
                # tests/test_beam.py::test_moe_routing_pool_coupling_demonstrated
                # (moe_exact targets route per-token independently, so the
                # verify window and plain decode agree bitwise)
                raise NotImplementedError(
                    "speculative serving requires a moe_exact target — "
                    "dense, or MoE with moe_dropless + moe_group_size=1"
                )
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
        self.cache = alloc_paged_cache(config, n_pages, page_size)
        if mesh is not None:
            self.cache = self._shard_pool(self.cache)
        self.block_table = np.full(
            (max_batch, max_pages_per_seq), _SCRATCH_PAGE, dtype=np.int32
        )
        self.pos = np.zeros(max_batch, dtype=np.int32)
        self.active = np.zeros(max_batch, dtype=bool)
        self.current = np.zeros((max_batch, 1), dtype=np.int32)
        self.budget = np.zeros(max_batch, dtype=np.int32)
        # rows are recycled; request ids are forever — results are keyed by
        # the id submit() returned, not by the row that happened to host it
        self.row_request = np.full(max_batch, -1, dtype=np.int64)
        self.results: dict[int, list[int]] = {}
        self.results_logprobs: dict[int, list[float]] = {}
        self.done: dict[int, bool] = {}
        # request -> eos | stop | length | constraint | error | cancelled
        self.finish: dict[int, str] = {}
        self.errors: dict[int, str] = {}  # request -> repr of callable error
        self.row_sampling: list[SamplingParams | None] = [None] * max_batch
        self.row_rng: list[np.random.Generator | None] = [None] * max_batch
        self._next_request_id = 0
        self.n_tokens_generated = 0
        self.free_pages = list(range(n_pages - 1, _SCRATCH_PAGE, -1))
        # Prefix cache (vLLM-style, host-side bookkeeping only): pages
        # holding a FULL page of prompt K/V are content-addressed by the
        # chain hash of their tokens-so-far and shared across requests via
        # refcounts; refcount-0 cached pages park in an LRU instead of the
        # free list and are evicted only under pool pressure, so a repeat
        # prompt arriving after the first finished still hits. Only pages
        # fully inside [0, L) are ever shared — the decode cursor starts at
        # L, so shared pages are write-free by construction.
        self.page_ref = np.zeros(n_pages, dtype=np.int32)
        self.prefix_index: dict[bytes, int] = {}
        self.page_hash: dict[int, bytes] = {}
        self.evictable: OrderedDict[int, None] = OrderedDict()
        self.prefix_stats = {
            "lookups": 0, "hits": 0, "pages_reused": 0, "evictions": 0,
        }
        # row -> in-progress interleaved admission (see submit's
        # interleave_admission): the row is occupied but not yet active
        self.prefill_state: dict[int, dict] = {}
        # donate the pool: without aliasing, every decoded token would pay
        # a full page-pool HBM copy (precedent: make_train_step's donation)
        self._decode = self._track(
            jax.jit(
                functools.partial(
                    decode_step_paged,
                    config=config,
                    lora_scale=self.lora_scale,
                ),
                donate_argnums=(3,),
            ),
            "decode_step_paged",
        )
        # Admission prefill. With a mesh the full forward runs under it —
        # in particular an ``sp`` axis shards the attention over the
        # sequence axis (ring or Ulysses per ``config.sp_attention``, via
        # transformer.forward), which is the LONG-CONTEXT admission path:
        # prefill activation memory and attention FLOPs spread across sp,
        # then the K/V reshards into the (tp-sharded) page pool. Decode
        # itself stays single-token and ignores sp. ``prefill_chunk``
        # remains the single-chip activation-memory tool; sp admission is
        # the multi-chip one.
        self._prefill = self._track(
            jax.jit(
                functools.partial(
                    forward, config=config, return_kv=True, mesh=mesh
                )
            ),
            "prefill_forward",
        )
        # chunked admission compiles once per (total_len, chunk, L) shape —
        # without the jit the remainder window would dispatch op-by-op
        # eagerly on every submit
        self._prefill_chunked = self._track(
            jax.jit(
                functools.partial(prefill_chunked, config=config),
                static_argnames=("total_len", "chunk"),
            ),
            "prefill_chunked",
        )
        # suffix-only admission windows (prefix-cache hits); compiles once
        # per page-aligned window width, bounded by max_pages_per_seq
        self._window = self._track(
            jax.jit(
                functools.partial(
                    decode_window_paged,
                    config=config,
                    lora_scale=self.lora_scale,
                ),
                donate_argnums=(3,),
            ),
            "decode_window_paged",
        )
        if draft_config is not None:
            # the draft's own paged pool, addressed by the SAME block
            # tables/pages (one allocation covers both models' K/V)
            self.draft_cache = alloc_paged_cache(
                draft_config, n_pages, page_size
            )
            if mesh is not None:
                self.draft_params = shard_params(
                    draft_params, draft_config, mesh
                )
                self.draft_cache = self._shard_pool(self.draft_cache)
            self._draft_decode = self._track(
                jax.jit(
                    functools.partial(decode_step_paged, config=draft_config),
                    donate_argnums=(3,),
                ),
                "draft_decode_step_paged",
            )
            self._draft_prefill = self._track(
                jax.jit(
                    functools.partial(
                        forward, config=draft_config, return_kv=True, mesh=mesh
                    )
                ),
                "draft_prefill_forward",
            )
            # the verify pass IS a window over the target pool — one jit
            # wrapper (self._window) so a suffix-admission width that
            # happens to equal gamma+1 reuses the compiled program
            self._verify = self._window
            self._draft_window = self._track(
                jax.jit(
                    functools.partial(
                        decode_window_paged, config=draft_config
                    ),
                    donate_argnums=(3,),
                ),
                "draft_decode_window_paged",
            )

        # Serving-engine instrumentation (docs/observability.md): ``metrics``
        # is a utils.metrics Registry; None keeps the batcher metrics-free
        # (zero overhead on the hot loop). TTFT and inter-token latency are
        # the serving-quality numbers (Orca-style per-stage visibility);
        # occupancy/pages/tokens-per-second are the capacity ones.
        self._metrics = metrics
        # ``monitor`` is a duck-typed observability.ServingMonitor (per-
        # request lifecycle traces + step records + wide events); usually
        # injected via monitor.attach(engine) -> set_monitor. None keeps
        # every hook site a single falsy check.
        self._monitor = monitor
        # Lifetime telemetry counters the monitor's step records difference.
        # Deliberately NOT serving state (excluded from _HOST_STATE, like
        # the metrics cursors): a restored snapshot starts its telemetry
        # from this process's zero.
        self._pages_allocated = 0
        self._pages_released = 0
        self._prefill_tokens = 0
        self._spec_accepted = 0
        self._spec_rejected = 0
        self._t_submit: float | None = None
        if metrics is not None:
            from bee_code_interpreter_tpu.utils.metrics import (
                TOKEN_LATENCY_BUCKETS,
            )

            self._ttft_seconds = metrics.histogram(
                "bci_serving_ttft_seconds",
                "Time from submit to a request's first generated token",
                buckets=TOKEN_LATENCY_BUCKETS,
            )
            self._inter_token_seconds = metrics.histogram(
                "bci_serving_inter_token_seconds",
                "Per-row latency between consecutive generated tokens",
                buckets=TOKEN_LATENCY_BUCKETS,
            )
            self._step_seconds = metrics.histogram(
                "bci_serving_step_seconds",
                "Wall time of one batcher step",
                buckets=TOKEN_LATENCY_BUCKETS,
            )
            self._tokens_total = metrics.counter(
                "bci_serving_tokens_total",
                "Tokens generated across all requests",
            )
            metrics.gauge(
                "bci_serving_active_rows",
                "Batch rows currently decoding",
                lambda: int(self.active.sum()),
            )
            metrics.gauge(
                "bci_serving_batch_occupancy",
                "Fraction of batch rows decoding (0-1)",
                lambda: float(self.active.sum()) / float(self.active.shape[0]),
            )
            metrics.gauge(
                "bci_serving_free_pages",
                "KV-cache pages on the free list",
                lambda: len(self.free_pages),
            )
            metrics.gauge(
                "bci_serving_tokens_per_second",
                "Decode throughput over the recent step window",
                self._tokens_per_second,
            )
            self._tokens_counted = 0
            # (monotonic time, cumulative tokens) samples; the rate gauge
            # reads the spread so a scrape never pays more than a subtraction
            self._rate_samples: deque[tuple[float, int]] = deque(maxlen=512)

    # throughput gauge window: samples older than this are dropped at read
    # time, and a gauge whose newest sample is older reads 0 — an idle
    # server must not report its last burst's rate forever
    _RATE_WINDOW_S = 30.0

    def _tokens_per_second(self) -> float:
        s = self._rate_samples
        if len(s) < 2:
            return 0.0
        now = time.monotonic()
        if now - s[-1][0] > self._RATE_WINDOW_S:
            return 0.0
        while len(s) > 2 and now - s[0][0] > self._RATE_WINDOW_S:
            s.popleft()
        (t0, n0), (t1, n1) = s[0], s[-1]
        return (n1 - n0) / (t1 - t0) if t1 > t0 else 0.0

    def _sync_token_counter(self) -> None:
        """Advance the Prometheus counter to the lifetime token total —
        exact whichever path (step, submit-time activation, interleaved
        finalization) produced the tokens."""
        delta = self.n_tokens_generated - self._tokens_counted
        if delta > 0:
            self._tokens_total.inc(delta)
            self._tokens_counted = self.n_tokens_generated

    def set_monitor(self, monitor) -> None:
        """Attach (or detach, with None) a lifecycle monitor
        (observability.ServingMonitor.attach calls this). Requests already
        in flight are not traced retroactively."""
        self._monitor = monitor

    def _track(self, fn, name: str) -> TrackedJit:
        """Wrap a jit entry point so an attached device monitor sees its
        compilations. The monitor resolves per call, so attach/detach
        works after construction and the unmonitored path pays one None
        check."""
        return TrackedJit(fn, name, lambda: self._device_monitor)

    def set_device_monitor(self, monitor) -> None:
        """Attach (or detach, with None) a compile/step telemetry monitor
        (observability.DeviceMonitor.attach calls this). Programs compiled
        before attachment are not reported retroactively."""
        self._device_monitor = monitor

    def kv_telemetry(self) -> dict:
        """KV-cache pool telemetry (docs/observability.md "Serving
        observability"): page accounting + slot-level internal
        fragmentation from ``ops.paged_kv_cache.pool_telemetry``, plus the
        prefix-chain reuse counters. Pure host bookkeeping — safe on every
        scrape."""
        from bee_code_interpreter_tpu.ops.paged_kv_cache import pool_telemetry

        out = pool_telemetry(
            block_table=self.block_table,
            pos=self.pos,
            active=self.active,
            page_ref=self.page_ref,
            page_size=self.page_size,
            free_pages=len(self.free_pages),
            parked_pages=len(self.evictable),
            scratch_page=_SCRATCH_PAGE,
        )
        lookups = self.prefix_stats["lookups"]
        hits = self.prefix_stats["hits"]
        out["prefix"] = {
            **self.prefix_stats,
            "misses": lookups - hits,
            "hit_ratio": hits / lookups if lookups else 0.0,
            "indexed_pages": len(self.prefix_index),
            "enabled": self.prefix_cache_enabled,
        }
        out["pages_allocated_total"] = self._pages_allocated
        out["pages_released_total"] = self._pages_released
        return out

    # ----------------------------------------------------- snapshot/resume

    _HOST_STATE = (
        "block_table", "pos", "active", "current", "budget", "row_request",
        "row_adapter", "page_ref", "results", "results_logprobs", "done",
        "finish", "errors", "row_sampling", "row_rng", "_next_request_id",
        "n_tokens_generated", "free_pages", "prefix_index", "page_hash",
        "prefix_stats", "prefill_state",
    )

    def _geometry(self) -> dict:
        """The ONE compatibility contract between a snapshot and the
        batcher restoring it: everything that changes what in-flight rows
        mean. eos_id/gamma/lora_scale/prefix-cache mode are behavioral, not
        just shapes — e.g. a different gamma changes how far past budget
        speculative rows may write, and a different eos_id changes when
        restored rows retire."""
        return {
            "config": self.config,
            "draft_config": self.draft_config,
            "n_pages": int(self.page_ref.shape[0]),
            "page_size": self.page_size,
            "max_batch": int(self.active.shape[0]),
            "max_pages_per_seq": int(self.block_table.shape[1]),
            "n_adapters": self.n_adapters,
            "eos_id": self.eos_id,
            "gamma": self.gamma,
            "lora_scale": self.lora_scale,
            "prefix_cache": self.prefix_cache_enabled,
        }

    def state_dict(self) -> dict:
        """Everything needed to resume serving mid-decode on a fresh
        batcher — the preemption-recovery primitive for serving the way
        ``utils/checkpoint.py`` is for training (preemptible TPU slices
        make this a first-class need). Device pools come back as host
        numpy; host bookkeeping is copied (numpy arrays, request maps,
        per-row rng states). The receiving batcher must be constructed
        with the same config and pool geometry — ``load_state_dict``
        verifies. NOTE for disk persistence: the dict pickles cleanly
        unless a live request carries a callable ``allowed_tokens``
        constraint (functions don't serialize; seed/bias/stop-based
        sampling all do).
        """
        import copy

        # copy=True: the decode jits DONATE the pool buffer, so a zero-copy
        # view (np.asarray can return one on CPU) would alias memory the
        # very next step() invalidates — the periodic-checkpoint pattern
        # must leave the snapshot owning its bytes
        snap_leaf = lambda x: np.array(x, copy=True)  # noqa: E731
        device = {"cache": jax.tree.map(snap_leaf, self.cache)}
        if self.draft_config is not None:
            device["draft_cache"] = jax.tree.map(snap_leaf, self.draft_cache)
        host = {
            name: copy.deepcopy(getattr(self, name))
            for name in self._HOST_STATE
        }
        host["evictable"] = list(self.evictable)  # LRU order, oldest first
        return {"device": device, "host": host, "meta": self._geometry()}

    def load_state_dict(self, state: dict) -> None:
        """Adopt a snapshot taken by ``state_dict``. Decode then continues
        exactly where the snapshot stopped (pinned by
        tests/test_serving.py::test_snapshot_resume_*): same tokens, same
        logprobs, same page accounting."""
        import copy

        meta = state["meta"]
        mine = self._geometry()
        if set(meta) != set(mine):
            raise ValueError(
                "snapshot geometry keys differ from this build's "
                f"({sorted(set(meta) ^ set(mine))}) — version skew"
            )
        for key, want in meta.items():
            if mine[key] != want:
                raise ValueError(
                    f"snapshot geometry mismatch on {key!r}: snapshot has "
                    f"{want}, this batcher has {mine[key]}"
                )
        cache = {
            k: jnp.asarray(v) for k, v in state["device"]["cache"].items()
        }
        self.cache = self._shard_pool(cache) if self.mesh is not None else cache
        if self.draft_config is not None:
            draft = {
                k: jnp.asarray(v)
                for k, v in state["device"]["draft_cache"].items()
            }
            self.draft_cache = (
                self._shard_pool(draft) if self.mesh is not None else draft
            )
        for name in self._HOST_STATE:
            setattr(self, name, copy.deepcopy(state["host"][name]))
        self.evictable = OrderedDict(
            (page, None) for page in state["host"]["evictable"]
        )
        # Metrics are per-process, not serving state: realign the counter
        # cursor so the restored lifetime total doesn't replay into
        # Prometheus, clear the throughput window, and drop TTFT anchors —
        # they are time.monotonic() values from the SNAPSHOTTING process's
        # clock, meaningless (possibly negative) against ours.
        self._t_submit = None
        for rec in self.prefill_state.values():
            rec.pop("t_submit", None)
        if self._metrics is not None:
            self._tokens_counted = self.n_tokens_generated
            self._rate_samples.clear()
        # Telemetry counters are per-process too, but the adopted page_ref
        # table changes what "held" means here: realign so the step
        # records' held_pages (allocated - released) keeps equaling the
        # pool scan's ref>0 count from this point on.
        self._pages_allocated = self._pages_released + int(
            (self.page_ref > 0).sum()
        )

    def _shard_pool(self, pool: dict) -> dict:
        """Shard a page pool's kv-head axis over the mesh's tp axis (axis 2
        of [n_layers, n_pages, kvh, ps, dh]; the int8 scale planes share
        the leading dims, so the one spec covers every leaf). A mesh
        without a tp axis replicates the pool — matching param_specs'
        whichever-axes-exist stance."""
        from jax.sharding import NamedSharding, PartitionSpec

        tp = "tp" if "tp" in self.mesh.axis_names else None
        spec = NamedSharding(
            self.mesh, PartitionSpec(None, None, tp, None, None)
        )
        return {k: jax.device_put(v, spec) for k, v in pool.items()}

    # ------------------------------------------------------------- admission
    def has_free_row(self) -> bool:
        free = ~self.active
        for row in self.prefill_state:
            free[row] = False
        return bool(free.any())

    @property
    def busy(self) -> bool:
        """Rows decoding OR admissions still interleaving — the loop-until
        condition for ``run_to_completion`` at every layer."""
        return bool(self.active.any()) or bool(self.prefill_state)

    def validate_request(
        self,
        prompt,
        max_new_tokens: int,
        sampling: SamplingParams | None = None,
        adapter: int | None = None,
        interleave_admission: int | None = None,
    ) -> int:
        """Capacity-independent request validation; returns the page count
        the request will need. The ONE copy of the admission arithmetic:
        ``submit`` calls it first, and the serving engine
        (models/engine.py) calls it at intake so a queued request can
        never explode minutes later on an error the caller could have
        seen at submit. Anything that passes here can fail admission only
        TRANSIENTLY (rows/pages busy — CapacityError), never permanently.
        """
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        L = int(prompt.shape[0])
        if L < 1:
            raise ValueError("prompt must be non-empty")
        if interleave_admission is not None and (
            interleave_admission < self.page_size
            or interleave_admission % self.page_size
        ):
            raise ValueError(
                f"interleave_admission must be a positive multiple of "
                f"page_size ({self.page_size}), got {interleave_admission}"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if adapter is not None:
            if self.lora_bank is None:
                raise ValueError(
                    "no adapters configured (pass adapters= at construction)"
                )
            if not 0 <= adapter < self.n_adapters:
                raise ValueError(
                    f"adapter {adapter} out of range "
                    f"(have {self.n_adapters})"
                )
        speculative = self.draft_params is not None
        if speculative and sampling is not None and sampling.steered:
            raise ValueError(
                "speculative serving cannot apply logit_bias/allowed_tokens "
                "(draft-verify commits the target's unsteered argmax tokens)"
            )
        # speculative rounds write draft/verify K/V past the budget before
        # truncation — those slots must be OWNED pages (a scratch-page read
        # inside the still-visible window would corrupt the verify). An
        # active row's cursor is at most L + budget - 2 (rows at budget
        # retire), so the deepest window write is cursor + gamma:
        # overshoot = gamma - 1 slots beyond L + budget.
        overshoot = self.gamma - 1 if speculative else 0
        total = L + max_new_tokens + overshoot
        if total > self.max_len:
            raise ValueError(
                f"prompt+generation ({total}, incl. speculative overshoot "
                f"{overshoot}) exceeds the block table's budget "
                f"({self.max_len})"
            )
        n_need = -(-total // self.page_size)  # ceil
        usable = self.page_ref.shape[0] - 1  # minus the scratch page
        if n_need > usable:
            raise ValueError(
                f"request needs {n_need} pages but the pool only has "
                f"{usable} (a permanent misfit, not backpressure)"
            )
        return n_need

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        sampling: SamplingParams | None = None,
        prefill_chunk: int | None = None,
        adapter: int | None = None,
        interleave_admission: int | None = None,
    ) -> int:
        """Prefill ``prompt`` into freshly allocated pages and return a
        REQUEST id (stable across row recycling). ``sampling`` defaults to
        greedy; a fixed seed makes the request fully deterministic. Raises
        if no free row or not enough free pages (callers queue and retry
        after a step frees capacity).

        ``interleave_admission`` (a page-multiple window width) admits the
        prompt INCREMENTALLY: submit allocates the row and pages but runs
        no model; each subsequent ``step`` advances the prefill by one
        window BEFORE decoding, so other rows keep producing tokens while
        a long prompt admits (Sarathi-style chunked-prefill interleaving —
        a one-shot admission stalls the whole batch for its prefill). The
        windows are exactly the suffix-admission program family, so the
        result is identical to the blocking admission; until the prefill
        completes the request has no tokens and the row's block-table
        entry stays on the scratch page (decode steps cannot touch the
        half-written pages).

        ``prefill_chunk`` admits through ``prefill_chunked`` instead of the
        one-shot O(L²) forward — activation memory bounded by the chunk,
        the long-prompt admission path. The chunked cache is built in the
        pool's own layout and copied into pages VERBATIM (int8 rows are
        quantized once, never re-quantized), so a chunked admission decodes
        exactly like prefill_chunked + contiguous decode. Trade-off: each
        distinct (full-chunks, remainder) shape compiles once, vs the
        padded one-shot path's max_pages_per_seq-bounded compile count.

        ``adapter`` serves this request under the i-th LoRA adapter the
        batcher was constructed with (None = the base model)."""
        t_submit = time.monotonic()  # TTFT anchor (metrics only)
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        n_need = self.validate_request(
            prompt, max_new_tokens, sampling=sampling, adapter=adapter,
            interleave_admission=interleave_admission,
        )
        L = int(prompt.shape[0])
        # internal index: 0 is the all-zeros base adapter in the bank
        adapter_internal = 0 if adapter is None else adapter + 1
        speculative = self.draft_params is not None
        occupied = self.active.copy()
        for r in self.prefill_state:
            occupied[r] = True
        free_rows = np.flatnonzero(~occupied)
        if free_rows.size == 0:
            raise CapacityError(
                "no free batch row (step() until one frees)"
            )
        # Prefix match BEFORE allocating: matched pages come from the index
        # (a ref, not an allocation). The match is capped at (L-1)//ps full
        # pages so at least one suffix token remains — the admission must
        # still produce last-prompt-token logits to sample from.
        matched = 0
        hashes: list[bytes] = []
        shared: list[int] = []
        if self.prefix_cache_enabled:
            self.prefix_stats["lookups"] += 1
            hashes, shared = self._prefix_match(prompt, adapter_internal)
            matched = len(shared)
        # acquire refs on shared pages BEFORE measuring availability: a
        # matched page parked in the evictable LRU must neither count
        # toward the fresh-page budget nor be pickable by the allocator's
        # eviction. Refs are released if the capacity check then fails.
        for page in shared:
            if self.page_ref[page] == 0:
                # reviving a parked page re-enters "held": count it as an
                # allocation so the churn counters stay symmetric with
                # _release_page's 1 -> 0 accounting (held == alloc - rel)
                self._pages_allocated += 1
            self.page_ref[page] += 1
            self.evictable.pop(page, None)
        available = len(self.free_pages) + len(self.evictable)
        if n_need - matched > available:
            for page in reversed(shared):
                self._release_page(page)
            raise CapacityError(
                f"page pool exhausted ({n_need - matched} needed, "
                f"{available} free)"
            )
        if matched:
            self.prefix_stats["hits"] += 1
            self.prefix_stats["pages_reused"] += matched
        row = int(free_rows[0])
        pages = shared + [self._alloc_page() for _ in range(n_need - matched)]
        # The request id is born HERE, once admission is committed (row and
        # pages secured): the lifecycle monitor needs it before the prefill
        # runs, and both the blocking and interleaved paths share it.
        req = self._next_request_id
        self._next_request_id += 1
        if self._monitor is not None:
            self._monitor.on_submit(
                req,
                prompt_tokens=L,
                max_new_tokens=max_new_tokens,
                pages=n_need,
                prefix_pages=matched,
                adapter=adapter,
                speculative=speculative,
                interleaved=interleave_admission is not None,
            )

        if interleave_admission is not None:
            # Deferred admission: no model runs now. The block-table row
            # stays on the scratch page so interleaved decode steps can't
            # write into the half-filled pages; the windows carry their
            # own table (see _advance_prefills). Speculative draft pages
            # zero now for the same reason the blocking path zeros them.
            if speculative:
                # only the FRESH pages: matched prefix pages hold valid
                # draft K/V that other rows may be sharing right now
                fresh_arr = jnp.asarray(pages[matched:], dtype=jnp.int32)
                self.draft_cache = {
                    name: x.at[:, fresh_arr].set(0)
                    for name, x in self.draft_cache.items()
                }
            start = matched * self.page_size
            suffix = np.zeros(
                (-(-(L - start) // self.page_size)) * self.page_size,
                dtype=np.int32,
            )
            suffix[: L - start] = prompt[start:]
            bt_row = np.full(
                (1, self.block_table.shape[1]), _SCRATCH_PAGE, dtype=np.int32
            )
            bt_row[0, :n_need] = pages
            self.results[req] = []
            self.done[req] = False
            self.prefill_state[row] = {
                "req": req, "prompt": prompt, "pages": pages,
                "hashes": hashes, "suffix": suffix, "pos": start,
                "start": start, "L": L,
                "bt_row": bt_row, "width": interleave_admission,
                "sampling": sampling, "max_new_tokens": max_new_tokens,
                "adapter_internal": adapter_internal,
                "speculative": speculative, "last_row": None,
                "t_submit": t_submit,
            }
            return req

        self.block_table[row, :] = _SCRATCH_PAGE
        self.block_table[row, :n_need] = pages

        # Admission runs under the request's serving trace (when a monitor
        # is attached): a compile forced by a new prefill shape lands as an
        # ``xla.compile`` span inside THIS request's span tree, so the TTFT
        # it inflated is explained where the operator looks for it
        # (observability/device.py).
        admit_ctx = (
            self._monitor.exemplar_context(req)
            if self._monitor is not None
            else nullcontext()
        )
        with admit_ctx:
            return self._blocking_admit(
                row, prompt, pages, hashes, matched, L, n_need, sampling,
                max_new_tokens, adapter_internal, speculative,
                prefill_chunk, req, t_submit,
            )

    def _blocking_admit(
        self, row, prompt, pages, hashes, matched, L, n_need, sampling,
        max_new_tokens, adapter_internal, speculative, prefill_chunk,
        req, t_submit,
    ) -> int:
        """The blocking admission tail of ``submit``: run the prefill,
        release pages on failure, activate the row. Split out so ``submit``
        can activate the request's trace around the whole region."""
        try:
            if matched or adapter_internal > 0:
                # Window-prefill admissions: shared-prefix hits AND every
                # adapter admission (matched == 0 makes the whole prompt
                # the suffix). decode_window_paged is lora- and
                # quantization-aware, so ONE mechanism covers every
                # combination — including adapters on a weight-only-int8
                # base, which the old merge_lora-based admission could
                # not serve. Base rows (adapter_internal == 0) without a
                # hit keep the one-shot forward + bulk seeding
                # (_full_admit): the same program family as
                # generate_cached's prefill, which the solo-equality pins
                # rely on bitwise at bf16.
                # Zero only the FRESH draft pages — matched pages hold
                # valid draft prefix K/V other rows may be sharing.
                if speculative:
                    fresh_arr = jnp.asarray(pages[matched:], dtype=jnp.int32)
                    self.draft_cache = {
                        name: x.at[:, fresh_arr].set(0)
                        for name, x in self.draft_cache.items()
                    }
                last_row = self._suffix_admit(
                    row, prompt, matched, speculative, prefill_chunk,
                    adapter_internal,
                )
            else:
                last_row = self._full_admit(
                    prompt, pages, L, speculative, prefill_chunk
                )
        except BaseException as e:
            # a failed admission (prefill OOM, bad sampling params, ...)
            # must not leak its pages: the row never activated, so nothing
            # else will ever return them to the pool. Shared pages drop the
            # acquired ref (back to the LRU if nobody else holds them);
            # fresh ones go straight back to the free list. (Unlike
            # mid-decode, a user-callable error here PROPAGATES: submit is
            # synchronous and the caller never receives the request id.)
            self.block_table[row, :] = _SCRATCH_PAGE
            for page in reversed(pages):
                self._release_page(page)
            if self._monitor is not None:
                self._monitor.on_done(req, "error", tokens=0, error=repr(e))
            raise
        self._prefill_tokens += L - matched * self.page_size
        self._t_submit = t_submit
        return self._activate_row(
            row, last_row, prompt, pages, hashes, L, sampling,
            max_new_tokens, adapter_internal, req=req, propagate=True,
        )

    def _activate_row(
        self, row, last_row, prompt, pages, hashes, L, sampling,
        max_new_tokens, adapter_internal, req, propagate=False,
    ) -> int:
        """Admission epilogue, shared by the blocking path and interleaved
        finalization: register prefix pages, sample the first token,
        activate the row. ``req`` was allocated by ``submit``;
        ``propagate`` re-raises first-token failures (the blocking path —
        the caller never received the id) instead of recording them on the
        ticket (interleaved finalization — submit returned long ago)."""
        sampling = sampling or SamplingParams()
        try:
            # rng construction INSIDE the protected region: a bad seed
            # must release the pages like any other first-token failure
            rng = np.random.default_rng(sampling.seed)
            first = choose_host(last_row, sampling, rng, [])
        except ConstraintExhausted:
            # the constraint permits no FIRST token: the request is
            # complete with an empty output (grammar terminal at step 0) —
            # a finished request, not an error; pages go straight back
            self.block_table[row, :] = _SCRATCH_PAGE
            for page in reversed(pages):
                self._release_page(page)
            self.results[req] = []
            if sampling.logprobs:
                self.results_logprobs[req] = []
            self.done[req] = True
            self.finish[req] = "constraint"
            if self._monitor is not None:
                self._monitor.on_done(req, "constraint", tokens=0)
            return req
        except BaseException as _activation_error:
            # user-callable failure at the first token: release the pages
            # either way; blocking submit PROPAGATES, interleaved
            # finalization records the error on the ticket
            self.block_table[row, :] = _SCRATCH_PAGE
            for page in reversed(pages):
                self._release_page(page)
            if self._monitor is not None:
                self._monitor.on_done(
                    req, "error", tokens=0, error=repr(_activation_error)
                )
            if propagate:
                raise
            self.done[req] = True
            self.finish[req] = "error"
            if sampling.logprobs:
                self.results_logprobs[req] = []
            self.errors[req] = repr(_activation_error)
            return req
        if self.prefix_cache_enabled:
            # index every page fully inside [0, L): those pages are
            # write-free for the rest of this request's life (the decode
            # cursor starts at L), so their K/V is shareable from now on.
            # Matched pages re-register as a no-op; last-writer-wins when
            # two in-flight admissions computed the same chunk.
            for j in range(L // self.page_size):
                page = int(pages[j])
                prev = self.prefix_index.get(hashes[j])
                if prev == page:
                    continue
                if prev is not None:
                    # displaced duplicate (two in-flight admissions computed
                    # the same chunk): drop its cache identity so the
                    # index/page_hash bijection holds; if it was parked
                    # awaiting reuse, nothing can hit it anymore — free it
                    self.page_hash.pop(prev, None)
                    if prev in self.evictable:
                        del self.evictable[prev]
                        self.free_pages.append(prev)
                self.prefix_index[hashes[j]] = page
                self.page_hash[page] = hashes[j]
        self.pos[row] = L
        self.current[row, 0] = first
        self.budget[row] = max_new_tokens
        self.row_adapter[row] = adapter_internal
        self.row_request[row] = req
        self.row_sampling[row] = sampling
        self.row_rng[row] = rng
        self.results[req] = [first]
        self.n_tokens_generated += 1
        if self._monitor is not None:
            # first token exists: the prefill span closes, TTFT is fixed,
            # and the decode span opens — BEFORE the metric observation so
            # the exemplar context below finds the live record.
            self._monitor.on_first_token(req)
        if self._metrics is not None:
            if self._t_submit is not None:
                # Observed under the request's serving trace (when a
                # monitor is attached) so the OpenMetrics exemplar on
                # bci_serving_ttft_seconds names the same trace_id the wide
                # event and /v1/traces carry.
                ctx = (
                    self._monitor.exemplar_context(req)
                    if self._monitor is not None
                    else nullcontext()
                )
                with ctx:
                    self._ttft_seconds.observe(
                        time.monotonic() - self._t_submit
                    )
                self._t_submit = None
            self._sync_token_counter()
        if sampling.logprobs:
            self.results_logprobs[req] = [logprob_of(last_row, first)]
        self.done[req] = False
        self.active[row] = True
        self._retire_if_done(row)
        return req

    def _advance_prefills(self) -> None:
        """One window of interleaved admission per prefilling row, run at
        the top of every ``step`` — the windows are the suffix-admission
        program family over the record's OWN block table (the global table
        keeps the row on the scratch page until activation)."""
        for row in sorted(self.prefill_state):
            rec = self.prefill_state[row]
            # suffix-relative offset of the next window (pos is absolute;
            # the suffix array starts at the absolute position rec["start"],
            # i.e. right after any prefix-cache hit — NOT at L minus the
            # padded suffix length)
            done_tokens = rec["pos"] - rec["start"]
            win = rec["suffix"][done_tokens: done_tokens + rec["width"]]
            bt_row = jnp.asarray(rec["bt_row"])
            win_arr = jnp.asarray(win[None, :])
            pos_arr = jnp.asarray([rec["pos"]], dtype=np.int32)
            t_win = time.monotonic()
            # under the request's trace (monitor attached): a compile
            # forced by a new window width attributes to THIS request
            win_ctx = (
                self._monitor.exemplar_context(rec["req"])
                if self._monitor is not None
                else nullcontext()
            )
            with win_ctx:
                logits, self.cache = self._window(
                    self.params, win_arr, pos_arr, self.cache, bt_row,
                    **self._lora_kwargs(np.array([rec["adapter_internal"]])),
                )
                if rec["speculative"]:
                    _, self.draft_cache = self._draft_window(
                        self.draft_params, win_arr, pos_arr,
                        self.draft_cache, bt_row,
                    )
            idx = rec["L"] - 1 - rec["pos"]  # last REAL token in window?
            if 0 <= idx < win.shape[0]:
                rec["last_row"] = np.asarray(logits[0, idx], dtype=np.float32)
            rec["pos"] += int(win.shape[0])
            self._prefill_tokens += int(win.shape[0])
            if self._monitor is not None:
                self._monitor.on_prefill_window(
                    rec["req"],
                    tokens=int(win.shape[0]),
                    duration_s=time.monotonic() - t_win,
                )
            if done_tokens + rec["width"] >= len(rec["suffix"]):
                # prefill complete: publish the pages and activate
                del self.prefill_state[row]
                n_need = len(rec["pages"])
                self.block_table[row, :] = _SCRATCH_PAGE
                self.block_table[row, :n_need] = rec["pages"]
                self._t_submit = rec.get("t_submit")
                self._activate_row(
                    row, rec["last_row"], rec["prompt"], rec["pages"],
                    rec["hashes"], rec["L"], rec["sampling"],
                    rec["max_new_tokens"], rec["adapter_internal"],
                    req=rec["req"],
                )

    # ------------------------------------------------- admission sub-paths
    def _full_admit(self, prompt, pages, L, speculative, prefill_chunk):
        """Whole-prompt BASE admission (no prefix hit, no adapters — those
        route through ``_suffix_admit``): one-shot or chunked prefill into
        this row's pages; returns the last prompt token's logits row."""
        n_prompt_pages = -(-L // self.page_size)
        pages_arr = jnp.asarray(pages[:n_prompt_pages], dtype=jnp.int32)
        # the prompt padded to a whole number of pages — shared by the
        # one-shot target prefill and the draft prefill (one copy: a
        # divergent pad between the two would desync their caches)
        Lp = n_prompt_pages * self.page_size
        padded = np.zeros(Lp, dtype=np.int32)
        padded[:L] = prompt
        # zero the DRAFT pool's allocated pages: recycled pages hold a
        # previous request's K/V, and only speculative drafting can
        # read a not-yet-written slot inside its visible window (the
        # full-accept gap below) — zeros make that read deterministic
        # and pool-history-independent, matching the contiguous
        # speculative_generate's zero-initialized cache. The target
        # pool needs no zeroing: plain decode and the verify only read
        # slots already written (prefill-seeded or appended by the
        # very window doing the reading; the rest are masked), so
        # zeroing it would just copy the whole pool per admission.
        if speculative:
            all_pages = jnp.asarray(pages, dtype=jnp.int32)
            self.draft_cache = {
                name: x.at[:, all_pages].set(0)
                for name, x in self.draft_cache.items()
            }
        if prefill_chunk is not None:
            # bounded-memory admission: the chunked prefill builds the
            # cache in the pool's layout; copy its leaves verbatim
            last_logits, contig = self._prefill_chunked(
                self.params, prompt[None, :],
                total_len=n_prompt_pages * self.page_size,
                chunk=prefill_chunk,
            )
            self.cache = seed_from_contiguous(
                self.cache, pages_arr,
                {name: x[:, 0] for name, x in contig.items()},
            )
            last_row = np.asarray(last_logits[0], dtype=np.float32)
        else:
            # one-shot prefill: exact O(L^2) forward, then the shared
            # one-scatter-per-leaf page seeding (seed_prefill — the
            # equality tests call the same function, so the tested
            # path IS this path). The padded prompt bounds the compile
            # count: pad tokens are causal-masked for every row < L,
            # so logits[L-1] and K/V[:L] are exact, and distinct
            # prompt lengths share a program per page count instead of
            # one per length.
            logits, (k_pre, v_pre) = self._prefill(
                self.params, padded[None, :]
            )
            self.cache = seed_prefill(
                self.cache, pages_arr,
                k_pre[:, 0, :, :L, :], v_pre[:, 0, :, :L, :],
            )
            last_row = np.asarray(logits[0, L - 1, :], dtype=np.float32)
        if speculative:
            # draft prefill into ITS pool at the same pages (the draft
            # is small — the padded one-shot prefill is fine even when
            # the target admission was chunked)
            _, (dk, dv) = self._draft_prefill(
                self.draft_params, padded[None, :]
            )
            self.draft_cache = seed_prefill(
                self.draft_cache, pages_arr,
                dk[:, 0, :, :L, :], dv[:, 0, :, :L, :],
            )
        return last_row

    def _suffix_admit(self, row, prompt, matched, speculative, prefill_chunk,
                      adapter_internal=0):
        """Window-prefill admission — prefix-cache hits (``matched`` > 0:
        only the suffix runs through the model) AND every adapter
        admission (``matched`` == 0: the whole prompt is the suffix) — as
        consecutive ``decode_window_paged`` windows that append suffix K/V
        into the row's fresh pages while attending to the shared prefix
        through the block table — the paged analogue of chunked prefill
        (``prefill_chunk`` bounds the window width the same way).

        Windows are page-aligned (every width a multiple of page_size), so
        the compile count stays bounded by max_pages_per_seq — the same
        bound as the padded one-shot path. Pad tokens in the final window
        write garbage K/V at positions >= L, which is safe for the same
        reason the speculative window's rejected drafts are: those slots
        sit beyond the cursor, are causally invisible until the cursor
        reaches them, and every decode write lands before the read that
        could see it. In speculative mode the draft pool replays the same
        windows so both caches stay in lockstep.

        Returns the last prompt token's logits row."""
        ps = self.page_size
        L = int(prompt.shape[0])
        start = matched * ps
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {prefill_chunk}")
        chunk_pages = (
            max(1, prefill_chunk // ps) if prefill_chunk is not None
            else self.block_table.shape[1]
        )
        suffix = np.zeros((-(-(L - start) // ps)) * ps, dtype=np.int32)
        suffix[: L - start] = prompt[start:]
        bt_row = jnp.asarray(self.block_table[row:row + 1])
        last_row = None
        pos = start
        for off in range(0, len(suffix), chunk_pages * ps):
            win = suffix[off: off + chunk_pages * ps]
            win_arr = jnp.asarray(win[None, :])
            pos_arr = jnp.asarray([pos], dtype=jnp.int32)
            logits, self.cache = self._window(
                self.params, win_arr, pos_arr, self.cache, bt_row,
                **self._lora_kwargs(np.array([adapter_internal])),
            )
            if speculative:
                _, self.draft_cache = self._draft_window(
                    self.draft_params, win_arr, pos_arr,
                    self.draft_cache, bt_row,
                )
            idx = L - 1 - pos  # last REAL token's index within this window
            if 0 <= idx < win.shape[0]:
                last_row = np.asarray(logits[0, idx], dtype=np.float32)
            pos += int(win.shape[0])
        return last_row

    # ------------------------------------------------------------ multi-LoRA
    def _lora_kwargs(self, adapter_rows: np.ndarray) -> dict:
        """Extra kwargs for the paged decode/window programs when a lora
        bank is configured; empty (the untouched base path) otherwise."""
        if self.lora_bank is None:
            return {}
        return {
            "lora_bank": self.lora_bank,
            "adapter_idx": jnp.asarray(adapter_rows, dtype=jnp.int32),
        }

    # -------------------------------------------------- prefix-cache pages
    def _prefix_match(
        self, prompt: np.ndarray, adapter_internal: int
    ) -> tuple[list[bytes], list[int]]:
        """(chain hashes, currently-matched prefix pages) for a would-be
        submission — the ONE copy of the match walk, shared by ``submit``
        and ``prefix_credit``. The match is capped at (L-1)//ps full pages
        so at least one suffix token remains."""
        hashes = self._chain_hashes(prompt, adapter_internal)
        shared: list[int] = []
        limit = min(len(hashes), (int(prompt.shape[0]) - 1) // self.page_size)
        for i in range(limit):
            page = self.prefix_index.get(hashes[i])
            if page is None:
                break
            shared.append(page)
        return hashes, shared

    def prefix_credit(self, prompt, adapter: int | None = None) -> int:
        """Full prompt pages a submission would reuse from the prefix
        index RIGHT NOW (0 with the cache off) — capacity planners
        (models/engine.py) subtract this from a request's page need so
        backpressure doesn't stall admissions the batcher would accept."""
        if not self.prefix_cache_enabled:
            return 0
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        adapter_internal = 0 if adapter is None else adapter + 1
        return len(self._prefix_match(prompt, adapter_internal)[1])

    def _chain_hashes(self, prompt: np.ndarray,
                      adapter_internal: int = 0) -> list[bytes]:
        """Chain hash after each FULL page of the prompt: ``hashes[i]``
        commits to tokens [0, (i+1)*page_size) — a page is reusable only
        when its entire history matches, which is what makes shared K/V
        position-exact (prefixes always align at position 0). The adapter
        index salts the chain: K/V under different LoRA adapters are
        different values, so they must never share pages."""
        h = hashlib.blake2b(digest_size=16)
        h.update(int(adapter_internal).to_bytes(8, "little"))
        out: list[bytes] = []
        ps = self.page_size
        for i in range(len(prompt) // ps):
            h.update(prompt[i * ps:(i + 1) * ps].astype(np.int32).tobytes())
            out.append(h.digest())
        return out

    def _alloc_page(self) -> int:
        """One fresh page: free list first, then LRU eviction of a
        refcount-0 cached prefix page (its index entry dies with it).
        Callers check capacity up front, so exhaustion here is a bug."""
        if self.free_pages:
            page = self.free_pages.pop()
        else:
            page, _ = self.evictable.popitem(last=False)  # LRU victim
            h = self.page_hash.pop(page, None)
            if h is not None and self.prefix_index.get(h) == page:
                del self.prefix_index[h]
            self.prefix_stats["evictions"] += 1
        self.page_ref[page] = 1
        self._pages_allocated += 1
        return page

    def _release_page(self, page: int) -> None:
        """Drop one reference. At refcount 0 an indexed prefix page parks
        in the LRU (K/V kept for future hits); anything else is freed."""
        self.page_ref[page] -= 1
        if self.page_ref[page] > 0:
            return
        self._pages_released += 1  # leaves "held" (parks or frees below)
        h = self.page_hash.get(page)
        if h is not None and self.prefix_index.get(h) == page:
            self.evictable[page] = None  # MRU end
        else:
            self.page_hash.pop(page, None)
            self.free_pages.append(page)

    # ----------------------------------------------------------------- step
    def step(self) -> None:
        """Advance every active row — by one token (plain mode, one
        compiled program), or by its own accept length (speculative
        mode). Interleaved admissions advance one window first, so their
        prefill and the batch's decode share the step cadence.

        With a metrics registry configured, each step also observes its
        wall time, the per-row inter-token latency (step time scaled by how
        many tokens each row committed — one in plain mode, the accept
        length in speculative mode), and the throughput window the
        tokens-per-second gauge reads. With a lifecycle monitor attached,
        each step additionally lands one step record (occupancy, token
        counts, speculative accepts, page churn — see
        docs/observability.md "Serving observability")."""
        if (
            self._metrics is None
            and self._monitor is None
            and self._device_monitor is None
        ):
            self._step_inner()
            return
        rows_before = int(np.count_nonzero(self.active))
        prefilling_before = len(self.prefill_state)
        tokens_before = self.n_tokens_generated
        prefill_before = self._prefill_tokens
        spec_acc_before = self._spec_accepted
        spec_rej_before = self._spec_rejected
        alloc_before = self._pages_allocated
        released_before = self._pages_released
        t0 = time.monotonic()
        self._step_inner()
        t1 = time.monotonic()
        produced = self.n_tokens_generated - tokens_before
        if self._metrics is not None:
            self._step_seconds.observe(t1 - t0)
            if produced:
                if rows_before:
                    self._inter_token_seconds.observe(
                        (t1 - t0) * rows_before / produced
                    )
                self._rate_samples.append((t1, self.n_tokens_generated))
            self._sync_token_counter()
        if self._device_monitor is not None:
            # per-mesh-shape step timing (observability/device.py): the
            # aggregate behind the tokens/sec-vs-mesh-shape curve
            self._device_monitor.record_step(
                (t1 - t0) * 1000.0, shape=self._mesh_key
            )
        if self._monitor is not None:
            # occupancy is deliberately NOT a field: it is active_rows /
            # max_batch, and the step path builds this record thousands of
            # times a second — derivable values are the reader's job
            self._monitor.on_step(
                {
                    "duration_ms": (t1 - t0) * 1000.0,
                    "mesh": self._mesh_key,
                    "active_rows": rows_before,
                    "active_rows_after": int(np.count_nonzero(self.active)),
                    "prefilling_rows": prefilling_before,
                    "max_batch": int(self.active.shape[0]),
                    "decode_tokens": produced,
                    "prefill_tokens": self._prefill_tokens - prefill_before,
                    "spec_accepted": self._spec_accepted - spec_acc_before,
                    "spec_rejected": self._spec_rejected - spec_rej_before,
                    "pages_allocated": self._pages_allocated - alloc_before,
                    "pages_released": self._pages_released - released_before,
                    "free_pages": len(self.free_pages),
                    "parked_pages": len(self.evictable),
                    # allocated-minus-released IS the held count (a release
                    # is counted exactly when a page's refcount hits 0):
                    # integer math instead of a page_ref scan per step
                    "held_pages": self._pages_allocated - self._pages_released,
                }
            )

    def _step_inner(self) -> None:
        if self.prefill_state:
            self._advance_prefills()
        if not self.active.any():
            return
        if self.draft_params is not None:
            self._step_speculative()
            return
        logits, self.cache = self._decode(
            self.params,
            jnp.asarray(self.current),
            jnp.asarray(self.pos),
            self.cache,
            jnp.asarray(self.block_table),
            **self._lora_kwargs(self.row_adapter),
        )
        active_rows = np.flatnonzero(self.active)
        any_sampled = any(
            self.row_sampling[row].temperature > 0.0 for row in active_rows
        )
        # the common all-greedy-no-logprobs case reduces on device and
        # moves B int32s; the full [max_batch, V] logits cross to host only
        # when some active row samples, records logprobs, or is steered by
        # bias/constraints
        need_rows = any_sampled or any(
            self.row_sampling[row].logprobs or self.row_sampling[row].steered
            for row in active_rows
        )
        # ...and the device argmax + its [B] pull only runs when some
        # active row actually decodes greedily (sampled/steered rows pick
        # from lg): an all-sampled batch was paying an argmax kernel and a
        # host sync per token for an array nobody read — found by the
        # jaxlint host-sync audit (docs/analysis.md "Accelerator lint"),
        # A/B'd with serving_bench(temperature>0)
        need_greedy = any(
            self.row_sampling[row].temperature <= 0.0
            and not self.row_sampling[row].steered
            for row in active_rows
        )
        greedy = (
            np.asarray(jnp.argmax(logits[:, -1, :], axis=-1), dtype=np.int32)
            if need_greedy else None
        )
        lg = (
            np.asarray(logits[:, -1, :], dtype=np.float32)
            if need_rows else None
        )
        for row in active_rows:
            sp = self.row_sampling[row]
            req_row = int(self.row_request[row])
            if sp.temperature > 0.0 or sp.steered:
                try:
                    nxt = choose_host(
                        lg[row], sp, self.row_rng[row], self.results[req_row]
                    )
                except ConstraintExhausted:
                    # grammar terminal state: the request is complete as-is
                    self._retire(int(row), "constraint")
                    continue
                except Exception as e:
                    # a buggy user callable must not wedge the whole batch
                    # (request isolation is continuous batching's promise):
                    # the row retires with the error recorded, batch-mates
                    # keep decoding
                    self.errors[req_row] = repr(e)
                    self._retire(int(row), "error")
                    continue
            else:
                nxt = int(greedy[row])
            self.pos[row] += 1
            self.current[row, 0] = nxt
            self.results[req_row].append(nxt)
            self.n_tokens_generated += 1
            if sp.logprobs:
                self.results_logprobs[req_row].append(
                    logprob_of(lg[row], nxt)
                )
            self._retire_if_done(int(row))

    def _step_speculative(self) -> None:
        """One draft-propose / target-verify / per-row-commit round.

        The draft runs γ paged decode steps (each one compiled program over
        the whole batch); the target scores every row's (current + drafts)
        window in ONE ``decode_window_paged``; each row then commits its
        own accepted prefix plus a correction token — rows never wait for
        each other (no lockstep minimum). Rejected draft positions stay in
        both pools as stale K/V, invisible behind each row's cursor until
        overwritten — the same no-rewind masking argument as
        ``speculative_generate``, applied per row.

        An all-greedy batch runs the exact argmax draft-verify with the
        draft loop fully on device; the moment any active row samples, the
        round routes through ``_step_speculative_sampled`` (rejection
        sampling, host-in-the-loop proposals) for the whole batch — greedy
        rows keep argmax semantics there, token for token.

        Known draft-quality (not correctness) gap, shared with the
        contiguous ``speculative_generate``: on a fully-accepted round the
        DRAFT pool never receives K/V for the last accepted draft token
        (the loop feeds it forward without appending), so later draft
        steps see zeros at that slot (pages are zeroed at admission —
        deterministic, pool-history-independent). The target verify is
        unaffected; only draft acceptance on those rows can dip."""
        active_rows = np.flatnonzero(self.active)
        if any(
            self.row_sampling[row].temperature > 0.0 for row in active_rows
        ):
            self._step_speculative_sampled(active_rows)
            return
        bt = jnp.asarray(self.block_table)
        pos_dev = jnp.asarray(self.pos)
        cur = jnp.asarray(self.current)

        drafts = []
        tok, p = cur, pos_dev
        for _ in range(self.gamma):
            lg, self.draft_cache = self._draft_decode(
                self.draft_params, tok, p, self.draft_cache, bt
            )
            tok = jnp.argmax(lg[:, -1:, :], axis=-1).astype(jnp.int32)
            drafts.append(tok)
            p = p + 1
        drafts_dev = jnp.concatenate(drafts, axis=1)  # [B, gamma]

        window = jnp.concatenate([cur, drafts_dev], axis=1)  # [B, gamma+1]
        t_logits, self.cache = self._verify(
            self.params, window, pos_dev, self.cache, bt,
            **self._lora_kwargs(self.row_adapter),
        )
        t_pred = np.asarray(
            jnp.argmax(t_logits, axis=-1), dtype=np.int32
        )  # [B, gamma+1]
        drafts_np = np.asarray(drafts_dev, dtype=np.int32)
        # full verify logits cross to host only when some row records
        # logprobs (commit[j]'s distribution is t_logits[row, j] — the
        # target's prediction for the token following window position j)
        t_np = (
            np.asarray(t_logits, dtype=np.float32)
            if any(self.row_sampling[row].logprobs for row in active_rows)
            else None
        )

        for row in active_rows:
            match = drafts_np[row] == t_pred[row, : self.gamma]
            n = int(np.argmin(match)) if not match.all() else self.gamma
            commit = [*drafts_np[row, :n].tolist(), int(t_pred[row, n])]
            self._commit_row(row, commit, n, t_np)

    def _commit_row(self, row, commit, n, t_np) -> None:
        """Land one speculative round's committed tokens for a row —
        per-token stop checks, logprobs off the verify logits, cursor
        advance by accepted+1, retirement. The ONE copy shared by the
        greedy and sampled rounds so their semantics cannot drift."""
        sp = self.row_sampling[row]
        req = int(self.row_request[row])
        self._spec_accepted += n
        self._spec_rejected += self.gamma - n
        if self._monitor is not None:
            self._monitor.on_commit(
                req, accepted=n, rejected=self.gamma - n
            )
        out = self.results[req]
        lp = self.results_logprobs.get(req) if sp.logprobs else None
        for j, tok_committed in enumerate(commit):
            out.append(int(tok_committed))
            self.n_tokens_generated += 1
            if lp is not None:
                lp.append(logprob_of(t_np[row, j], int(tok_committed)))
            if self._done_reason(row, out) is not None:
                break  # later commits would exceed the stop — drop them
        self.pos[row] += n + 1
        self.current[row, 0] = int(commit[-1])
        self._retire_if_done(row)

    def _step_speculative_sampled(self, active_rows) -> None:
        """Speculative round with SAMPLED rows: rejection sampling
        (Leviathan et al., "Fast Inference from Transformers via
        Speculative Decoding"). Per position, with p and q the row's
        FILTERED target/draft distributions (temperature + top-k/top-p
        applied to both via the one ``filtered_probs_host``):

        - the proposal x ~ q is accepted with probability min(1, p(x)/q(x));
        - the first rejection resamples from normalize(max(p - q, 0));
        - a fully-accepted window draws its bonus token from the target's
          last distribution.

        The committed stream is distributed exactly as plain sampled
        decoding from the target — the distributional pin lives in
        tests/test_speculative_sampling.py; same-seed determinism and
        batch-mate isolation are pinned there too. Greedy rows in the
        same batch keep the exact argmax draft-verify semantics.

        Proposals are sampled host-side from each draft step's logits
        with the row's own seeded generator, so the draft loop pays one
        device->host [B, V] transfer per gamma — the target still scores
        the whole window in ONE pass, which is the speedup that matters."""
        bt = jnp.asarray(self.block_table)
        pos_dev = jnp.asarray(self.pos)
        cur = jnp.asarray(self.current)
        B = self.current.shape[0]
        gamma = self.gamma

        drafts_np = np.zeros((B, gamma), dtype=np.int32)
        q_dists: dict[int, list] = {int(r): [] for r in active_rows}
        tok, p = cur, pos_dev
        for g in range(gamma):
            lg, self.draft_cache = self._draft_decode(
                self.draft_params, tok, p, self.draft_cache, bt
            )
            lg_np = np.asarray(lg[:, -1, :], dtype=np.float32)
            # one transfer per step: greedy + idle rows propose host argmax
            drafts_np[:, g] = lg_np.argmax(-1).astype(np.int32)
            for row in active_rows:
                sp = self.row_sampling[row]
                if sp.temperature > 0.0:
                    q = filtered_probs_host(lg_np[row], sp)
                    drafts_np[row, g] = int(
                        self.row_rng[row].choice(q.shape[0], p=q)
                    )
                    q_dists[int(row)].append(q)
                else:
                    q_dists[int(row)].append(None)
            tok = jnp.asarray(drafts_np[:, g: g + 1])
            p = p + 1

        window = jnp.concatenate([cur, jnp.asarray(drafts_np)], axis=1)
        t_logits, self.cache = self._verify(
            self.params, window, pos_dev, self.cache, bt,
            **self._lora_kwargs(self.row_adapter),
        )
        t_np = np.asarray(t_logits, dtype=np.float32)  # [B, gamma+1, V]

        for row in active_rows:
            sp = self.row_sampling[row]
            rng = self.row_rng[row]
            if sp.temperature <= 0.0:
                preds = t_np[row].argmax(-1).astype(np.int32)
                match = drafts_np[row] == preds[:gamma]
                n = int(np.argmin(match)) if not match.all() else gamma
                commit = [*drafts_np[row, :n].tolist(), int(preds[n])]
            else:
                commit, n = rejection_sample_commit(
                    drafts_np[row].tolist(),
                    q_dists[int(row)],
                    lambda g, row=row, sp=sp: filtered_probs_host(
                        t_np[row, g], sp
                    ),
                    rng,
                )
            self._commit_row(row, commit, n, t_np)

    def _done_reason(self, row: int, out: list[int]) -> tuple[str, int] | None:
        """(finish_reason, tokens_to_trim) once a row's output is complete,
        else None — the ONE copy of the stop logic, shared by the plain
        retire path and the speculative commit loop so the two cannot
        drift. Precedence: eos (the model's own stop, kept in the output),
        then a stop sequence (trimmed from the output), then the length
        budget."""
        if self.eos_id is not None and out and out[-1] == self.eos_id:
            return "eos", 0
        sp = self.row_sampling[row]
        if sp is not None:
            for s in sp.stop_sequences:
                if len(out) >= len(s) and tuple(out[-len(s):]) == s:
                    return "stop", len(s)
        if len(out) >= self.budget[row]:
            return "length", 0
        return None

    def _retire_if_done(self, row: int) -> None:
        verdict = self._done_reason(row, self.results[int(self.row_request[row])])
        if verdict is not None:
            self._retire(row, *verdict)

    def _retire(self, row: int, reason: str, trim: int = 0) -> None:
        """Retire a row unconditionally: trim, record the finish reason,
        free the row and its pages. The _retire_if_done path and the
        constraint-terminal/callable-error paths all land here."""
        req = int(self.row_request[row])
        out = self.results[req]
        if trim:
            del out[len(out) - trim:]
            lp = self.results_logprobs.get(req)
            if lp is not None:
                del lp[len(lp) - trim:]
        self.finish[req] = reason
        self.active[row] = False
        self.done[req] = True
        self.row_request[row] = -1
        self.row_sampling[row] = None
        self.row_rng[row] = None
        self.row_adapter[row] = 0
        used = set(self.block_table[row].tolist()) - {_SCRATCH_PAGE}
        for page in sorted(used, reverse=True):
            self._release_page(page)
        self.block_table[row, :] = _SCRATCH_PAGE
        # pos stays for inspection; scratch-page writes are masked
        if self._monitor is not None:
            self._monitor.on_done(
                req, reason, tokens=len(out), error=self.errors.get(req)
            )

    # -------------------------------------------------------------- results
    @property
    def stats(self) -> dict:
        """Operator counters — occupancy, page accounting, lifetime
        totals, prefix-cache stats. Cheap to read every scrape; a serving
        loop exports these however it likes (the service's Prometheus
        registry, logs, ...)."""
        return {
            "active_rows": int(self.active.sum()),
            "prefilling_rows": len(self.prefill_state),
            "max_batch": int(self.active.shape[0]),
            "free_pages": len(self.free_pages),
            "parked_pages": len(self.evictable),
            "held_pages": int((self.page_ref > 0).sum()),
            "requests_submitted": self._next_request_id,
            "requests_finished": sum(1 for v in self.done.values() if v),
            "tokens_generated": self.n_tokens_generated,
            "prefix_cache": dict(self.prefix_stats),
        }

    def is_done(self, request_id: int) -> bool:
        return self.done.get(request_id, False)

    def result(self, request_id: int) -> list[int]:
        """Generated tokens for a request (first token included). Results
        are held until ``release`` — a long-running server should release
        each consumed result or host memory grows with request count."""
        if request_id not in self.results:
            if self.done.get(request_id):
                raise KeyError(f"request {request_id} was released")
            raise KeyError(f"unknown request {request_id}")
        if not self.done[request_id]:
            raise RuntimeError(f"request {request_id} still decoding")
        return list(self.results[request_id])

    def result_logprobs(self, request_id: int) -> list[float]:
        """Per-token log-probabilities for a finished request that was
        submitted with ``SamplingParams(logprobs=True)`` — same length and
        order as ``result`` (trimmed stop sequences drop their logprobs
        too). Unfiltered-distribution semantics: see SamplingParams."""
        if request_id not in self.done:
            raise KeyError(f"unknown request {request_id}")
        if request_id not in self.results_logprobs:
            if self.done[request_id] and request_id not in self.results:
                raise KeyError(f"request {request_id} was released")
            raise KeyError(
                f"request {request_id} did not record logprobs "
                "(submit with SamplingParams(logprobs=True))"
            )
        if not self.done[request_id]:
            raise RuntimeError(f"request {request_id} still decoding")
        return list(self.results_logprobs[request_id])

    def request_error(self, request_id: int) -> str | None:
        """repr of the user-callable exception that retired a request with
        finish reason 'error', else None. Survives ``release``."""
        return self.errors.get(request_id)

    def finish_reason(self, request_id: int) -> str:
        """'eos' | 'stop' | 'length' | 'constraint' | 'error' |
        'cancelled' for a
        finished request; survives ``release`` (a string per request,
        like the done-flag)."""
        if request_id not in self.finish:
            if self.done.get(request_id) is False:
                raise RuntimeError(f"request {request_id} still decoding")
            raise KeyError(f"unknown request {request_id}")
        return self.finish[request_id]

    def cancel(self, request_id: int) -> None:
        """Abort a still-decoding request: its row and pages free
        immediately (the next admission can use them), the tokens
        generated so far stay readable via ``result``, and
        ``finish_reason`` reports 'cancelled'. Cancelling a finished or
        released request is a no-op (the cancel raced completion — the
        caller shouldn't have to care who won); an id the batcher never
        issued raises KeyError like every other request API."""
        for row in np.flatnonzero(self.active):
            if int(self.row_request[row]) == request_id:
                self._retire(int(row), "cancelled")
                return
        for row, rec in list(self.prefill_state.items()):
            if rec["req"] == request_id:
                # admission still interleaving: free the pages (shared
                # ones drop their ref), keep the empty result readable
                del self.prefill_state[row]
                for page in reversed(rec["pages"]):
                    self._release_page(page)
                self.done[request_id] = True
                self.finish[request_id] = "cancelled"
                if rec["sampling"] is not None and rec["sampling"].logprobs:
                    self.results_logprobs[request_id] = []
                if self._monitor is not None:
                    self._monitor.on_done(request_id, "cancelled", tokens=0)
                return
        if request_id not in self.done:
            raise KeyError(f"unknown request {request_id}")

    def preempt(self, request_id: int) -> bool:
        """Evict a request whose INTERLEAVED admission is still prefilling:
        its pages free immediately and the request is erased as if never
        submitted (the id is dead; the caller re-submits the same prompt
        later and the prefill recomputes — vLLM-style recompute preemption,
        restricted to the pre-first-token window where recomputation is
        trivially exact because there is nothing else to reproduce).
        Returns False once the request has produced a token (decoding),
        finished, or is unknown — callers that need to stop a decoding
        request want :meth:`cancel`, which keeps its partial output."""
        for row, rec in list(self.prefill_state.items()):
            if rec["req"] == request_id:
                del self.prefill_state[row]
                for page in reversed(rec["pages"]):
                    self._release_page(page)
                self.results.pop(request_id, None)
                self.done.pop(request_id, None)
                if self._monitor is not None:
                    self._monitor.on_preempt(request_id)
                return True
        return False

    def release(self, request_id: int) -> None:
        """Drop a finished request's stored result (pages were already
        recycled at retirement; this frees the host-side token list). The
        done-flag and finish reason are kept — small per-request scalars —
        so ``is_done``/``finish_reason`` stay observable and a poller
        can't spin forever on a released id; ``result`` then reports
        'released', not 'unknown'."""
        if request_id in self.done and not self.done[request_id]:
            raise RuntimeError(f"request {request_id} still decoding")
        self.results.pop(request_id, None)
        self.results_logprobs.pop(request_id, None)

    def run_to_completion(self, max_steps: int = 10_000) -> None:
        for _ in range(max_steps):
            if not self.busy:
                return
            self.step()
        raise RuntimeError("run_to_completion exceeded max_steps")
