"""MNIST MLP under data parallelism — the BASELINE.json "JAX MNIST training
snippet (jax.grad + data parallelism across 8 v5e chips)" config.

Deliberately simple: an MLP, cross-entropy, SGD with momentum, and a jitted
train step whose batch is sharded over the mesh's ``dp`` axis. XLA inserts the
gradient all-reduce — there is no hand-written collective here, which is
exactly the point of the sharding-first design (vs the pmap-era pattern of
explicit psum in the loss).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MnistMlp:
    def __init__(
        self,
        hidden_sizes: tuple[int, ...] = (512, 256),
        n_classes: int = 10,
        input_dim: int = 784,
        mesh: Mesh | None = None,
    ) -> None:
        self.sizes = (input_dim, *hidden_sizes, n_classes)
        self.mesh = mesh

    def init(self, key: jax.Array) -> list[dict[str, jax.Array]]:
        params = []
        for i, (n_in, n_out) in enumerate(zip(self.sizes[:-1], self.sizes[1:])):
            key, sub = jax.random.split(key)
            layer = {
                "w": jax.random.normal(sub, (n_in, n_out)) * (2.0 / n_in) ** 0.5,
                "b": jnp.zeros((n_out,)),
            }
            if self.mesh is not None:  # replicated params, dp-sharded batch
                layer = jax.tree.map(
                    lambda x: jax.device_put(x, NamedSharding(self.mesh, P())), layer
                )
            params.append(layer)
        return params

    def apply(self, params, x: jax.Array) -> jax.Array:
        for layer in params[:-1]:
            x = jax.nn.relu(x @ layer["w"] + layer["b"])
        return x @ params[-1]["w"] + params[-1]["b"]

    def loss(self, params, batch) -> jax.Array:
        logits = self.apply(params, batch["image"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()

    def batch_sharding(self) -> NamedSharding | None:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P("dp"))

    def make_train_step(self, learning_rate: float = 0.1):
        optimizer = optax.sgd(learning_rate, momentum=0.9)

        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(self.loss)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        return jax.jit(train_step, donate_argnums=(0, 1)), optimizer
