"""Load HuggingFace Llama-family checkpoints into this framework.

The migration path for real weights: ``transformers`` ships the checkpoint
ecosystem, this framework ships the TPU-first runtime — the loader maps an
HF ``LlamaForCausalLM`` (or its state dict) onto our param pytree and
config, after which every path in the library (mesh-sharded forward,
KV-cached decode, paged serving, speculative, LoRA, checkpoints) serves
the real model.

The mapping is exact, not approximate — our transformer IS Llama
semantics:

- RoPE: the half-split rotate convention (``[x1·cos − x2·sin,
  x1·sin + x2·cos]`` with freqs paired (i, i+d/2)) matches HF's
  ``rotate_half`` application term for term.
- RMSNorm (x/rms·scale, f32 accumulation), SwiGLU (silu(gate)·up·down),
  pre-norm residual order, 1/sqrt(head_dim) score scaling, no biases.
- Weight layout: torch ``Linear.weight`` is [out, in]; our einsums take
  [in, out] — every projection transposes. Heads are laid out
  [head·head_dim + j] on the out axis in both, so no permutation is
  needed beyond the transpose.

Logits parity against ``transformers``' own forward is pinned to 1e-4 by
tests/test_hf_loader.py — the strongest correctness statement the
transformer family has, and the reason this module lives next to the
model code rather than in an example.

Scope honestly stated: rms_norm eps is fixed at 1e-5 in our kernel-shared
``rms_norm`` (Llama-2/3 checkpoints use 1e-5); checkpoints with a
different eps are refused rather than silently mis-normed. Attention
biases and non-default rope scaling configs are refused the same way.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models.transformer import TransformerConfig

Params = dict[str, Any]


def config_from_hf(hf_config, dtype=jnp.bfloat16) -> TransformerConfig:
    """Our TransformerConfig for an HF ``LlamaConfig``. Refuses silently
    unloadable settings instead of approximating them."""
    eps = getattr(hf_config, "rms_norm_eps", 1e-5)
    if abs(eps - 1e-5) > 1e-12:
        raise ValueError(
            f"rms_norm_eps {eps} unsupported (our rms_norm fixes 1e-5, "
            "the Llama-2/3 value); refusing a silently mis-normed load"
        )
    if getattr(hf_config, "attention_bias", False):
        raise ValueError("attention_bias checkpoints are not supported")
    if getattr(hf_config, "mlp_bias", False):
        raise ValueError("mlp_bias checkpoints are not supported")
    act = getattr(hf_config, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise ValueError(
            f"hidden_act {act!r} unsupported (our MLP is SwiGLU/silu); "
            "refusing a silently wrong load"
        )
    scaling = getattr(hf_config, "rope_scaling", None)
    rope_scaling = 1.0
    if scaling is not None:
        kind = scaling.get("rope_type", scaling.get("type"))
        if kind != "linear":
            raise ValueError(
                f"rope_scaling type {kind!r} unsupported (only linear "
                "position interpolation maps onto our rope scaling)"
            )
        rope_scaling = float(scaling["factor"])
    derived_head_dim = hf_config.hidden_size // hf_config.num_attention_heads
    explicit_head_dim = getattr(hf_config, "head_dim", None)
    if explicit_head_dim not in (None, derived_head_dim):
        # our attention derives head_dim from hidden_size // n_heads; a
        # checkpoint with a non-derived head_dim (increasingly common in
        # HF Llama-family configs) would otherwise pass construction and
        # fail later with an opaque reshape error
        raise ValueError(
            f"head_dim {explicit_head_dim} != hidden_size // "
            f"num_attention_heads ({derived_head_dim}); non-derived head "
            "dims unsupported — refusing a silently wrong load"
        )
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        dtype=dtype,
    )


def _to_numpy(tensor) -> np.ndarray:
    if hasattr(tensor, "detach"):  # torch tensor
        return tensor.detach().to("cpu").float().numpy()
    return np.asarray(tensor, dtype=np.float32)


def load_llama_params(
    model_or_state_dict, hf_config=None, dtype=jnp.bfloat16
) -> tuple[Params, TransformerConfig]:
    """(params, config) for an HF ``LlamaForCausalLM`` or its state dict.

    Params are f32 masters (matching ``init_params``' convention — compute
    casts to ``config.dtype`` at use). Tied word embeddings are honored:
    a missing ``lm_head.weight`` falls back to the embedding transposed.
    """
    if hf_config is None:
        hf_config = getattr(model_or_state_dict, "config", None)
        if hf_config is None:
            raise ValueError(
                "pass hf_config when loading from a bare state dict"
            )
    config = config_from_hf(hf_config, dtype=dtype)
    sd = (
        model_or_state_dict
        if isinstance(model_or_state_dict, dict)
        else model_or_state_dict.state_dict()
    )

    def get(name: str) -> np.ndarray:
        if name in sd:
            return _to_numpy(sd[name])
        raise KeyError(
            f"{name} missing from the state dict — not a Llama-family "
            f"checkpoint? (have e.g. {sorted(sd)[:4]})"
        )

    embed = get("model.embed_tokens.weight")  # [V, D]
    if "lm_head.weight" in sd:
        lm_head = _to_numpy(sd["lm_head.weight"]).T  # [D, V]
    else:  # tie_word_embeddings
        lm_head = embed.T.copy()

    layers: dict[str, list[np.ndarray]] = {
        k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                        "w_gate", "w_up", "w_down")
    }
    for i in range(config.n_layers):
        p = f"model.layers.{i}"
        layers["ln1"].append(get(f"{p}.input_layernorm.weight"))
        layers["wq"].append(get(f"{p}.self_attn.q_proj.weight").T)
        layers["wk"].append(get(f"{p}.self_attn.k_proj.weight").T)
        layers["wv"].append(get(f"{p}.self_attn.v_proj.weight").T)
        layers["wo"].append(get(f"{p}.self_attn.o_proj.weight").T)
        layers["ln2"].append(get(f"{p}.post_attention_layernorm.weight"))
        layers["w_gate"].append(get(f"{p}.mlp.gate_proj.weight").T)
        layers["w_up"].append(get(f"{p}.mlp.up_proj.weight").T)
        layers["w_down"].append(get(f"{p}.mlp.down_proj.weight").T)

    params: Params = {
        "embed": jnp.asarray(embed, jnp.float32),
        "layers": {
            name: jnp.asarray(np.stack(mats), jnp.float32)
            for name, mats in layers.items()
        },
        "ln_f": jnp.asarray(get("model.norm.weight"), jnp.float32),
        "lm_head": jnp.asarray(lm_head, jnp.float32),
    }
    return params, config
