"""ResNet-style vision model family, TPU-first.

Complements the BASELINE.json vision payload (examples/resnet50-torch-xla.py
drives torch-xla *through the sandbox*) with a native-JAX path a sandboxed
agent can import directly. Design choices are TPU choices, not a port of the
torchvision graph:

- **NHWC layout** end-to-end — the layout XLA:TPU convolutions are native
  in (no transposes at every conv like NCHW would cost).
- **bf16 compute, f32 master params** — convs ride the MXU at full rate;
  the softmax/cross-entropy head stays f32.
- **GroupNorm instead of BatchNorm**: normalization is per-sample, so there
  is no cross-device batch-statistics psum in the forward and no mutable
  running-stats state threaded through train/eval — the whole model stays a
  pure function of (params, x), SPMD-sharding over ``dp``/``fsdp`` without
  the sync-BN machinery data-parallel BatchNorm needs.
- **Static everything**: stage layout fixed at trace time. Blocks are
  unrolled (heterogeneous channel widths/strides rule out a single scanned
  body; at ResNet depths the HLO stays small — the transformer, 32+ uniform
  layers, is where the scan-over-layers trick lives).

``ResNetConfig.resnet50()`` matches the classic 50-layer bottleneck shape
(3-4-6-3, width 64, 1000 classes); ``tiny()`` is the test/dry-run size.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)  # resnet50 bottleneck depths
    width: int = 64  # stem channels; stage c is width * 2**c (x4 expanded)
    norm_groups: int = 32
    dtype: Any = jnp.bfloat16

    @classmethod
    def resnet50(cls) -> "ResNetConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "ResNetConfig":
        """Test/dry-run size (2 stages, 8-wide stem)."""
        return cls(num_classes=10, stage_sizes=(1, 1), width=8, norm_groups=4)


# ---------------------------------------------------------------- primitives


def conv(x: jax.Array, w: jax.Array, stride: int = 1) -> jax.Array:
    """NHWC x HWIO -> NHWC, SAME padding."""
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def group_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, groups: int) -> jax.Array:
    """Per-sample normalization over (H, W, C/groups); f32 statistics.
    ``groups`` is clamped to the largest divisor of C not exceeding it, so
    any channel count works (C=48 with groups=32 normalizes in 16 groups
    rather than crashing the reshape)."""
    N, H, W, C = x.shape
    g = min(groups, C)
    while C % g:
        g -= 1
    xf = x.astype(jnp.float32).reshape(N, H, W, g, C // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * lax.rsqrt(var + 1e-5)
    xf = xf.reshape(N, H, W, C)
    return (xf * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


# ------------------------------------------------------------------- weights


def _block_stride(stage: int, block: int) -> int:
    """Downsampling policy — THE single source for both init (which decides
    projection shortcuts from it) and forward (which convolves with it)."""
    return 2 if (block == 0 and stage > 0) else 1


def _conv_init(key, kh, kw, c_in, c_out):
    fan_in = kh * kw * c_in
    return jax.random.normal(key, (kh, kw, c_in, c_out), jnp.float32) * math.sqrt(
        2.0 / fan_in
    )


def _norm_init(c):
    return {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}


def _block_init(key, c_in, c_mid, stride):
    """Bottleneck: 1x1 reduce -> 3x3 (stride) -> 1x1 expand (x4)."""
    ks = jax.random.split(key, 4)
    c_out = 4 * c_mid
    p = {
        "conv1": _conv_init(ks[0], 1, 1, c_in, c_mid), "n1": _norm_init(c_mid),
        "conv2": _conv_init(ks[1], 3, 3, c_mid, c_mid), "n2": _norm_init(c_mid),
        "conv3": _conv_init(ks[2], 1, 1, c_mid, c_out), "n3": _norm_init(c_out),
    }
    if stride != 1 or c_in != c_out:
        p["proj"] = _conv_init(ks[3], 1, 1, c_in, c_out)
        p["nproj"] = _norm_init(c_out)
    return p


def init_params(config: ResNetConfig, key: jax.Array) -> Params:
    c = config
    keys = jax.random.split(key, 2 + len(c.stage_sizes))
    params: Params = {
        "stem": _conv_init(keys[0], 7, 7, 3, c.width),
        "stem_norm": _norm_init(c.width),
    }
    c_in = c.width
    for s, depth in enumerate(c.stage_sizes):
        c_mid = c.width * (2 ** s)
        bkeys = jax.random.split(keys[1 + s], depth)
        blocks = []
        for b in range(depth):
            blocks.append(_block_init(bkeys[b], c_in, c_mid, _block_stride(s, b)))
            c_in = 4 * c_mid
        params[f"stage{s}"] = blocks
    params["fc"] = {
        "w": jax.random.normal(keys[-1], (c_in, c.num_classes), jnp.float32)
        / math.sqrt(c_in),
        "b": jnp.zeros((c.num_classes,), jnp.float32),
    }
    return params


# ------------------------------------------------------------------- forward


def _block_apply(x, p, config, stride):
    g = config.norm_groups
    dt = config.dtype
    y = jax.nn.relu(group_norm(conv(x, p["conv1"].astype(dt)), **p["n1"], groups=g))
    y = jax.nn.relu(
        group_norm(conv(y, p["conv2"].astype(dt), stride), **p["n2"], groups=g)
    )
    y = group_norm(conv(y, p["conv3"].astype(dt)), **p["n3"], groups=g)
    shortcut = x
    if "proj" in p:
        shortcut = group_norm(
            conv(x, p["proj"].astype(dt), stride), **p["nproj"], groups=g
        )
    return jax.nn.relu(y + shortcut)


def forward(
    params: Params,
    images: jax.Array,  # [N, H, W, 3] (any float dtype)
    config: ResNetConfig,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Returns logits [N, num_classes] (f32)."""
    c = config
    x = images.astype(c.dtype)

    def constrain(x):
        if mesh is None:
            return x
        from bee_code_interpreter_tpu.parallel.mesh import batch_axes

        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(batch_axes(mesh), None, None, None))
        )

    x = constrain(x)
    x = conv(x, params["stem"].astype(c.dtype), stride=2)
    x = jax.nn.relu(group_norm(x, **params["stem_norm"], groups=c.norm_groups))
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for s, depth in enumerate(c.stage_sizes):
        for b in range(depth):
            x = constrain(
                _block_apply(x, params[f"stage{s}"][b], c, _block_stride(s, b))
            )
    x = x.mean(axis=(1, 2)).astype(jnp.float32)  # global average pool
    return x @ params["fc"]["w"] + params["fc"]["b"]


# ---------------------------------------------------------------- train step


def loss_fn(params, batch, config, mesh=None):
    logits = forward(params, batch["images"], config, mesh)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]
    ).mean()


class ResNet:
    """Config + mesh bundle mirroring models.transformer.Transformer."""

    def __init__(self, config: ResNetConfig, mesh: Mesh | None = None) -> None:
        self.config = config
        self.mesh = mesh

    def init(self, key: jax.Array) -> Params:
        return init_params(self.config, key)

    def apply(self, params: Params, images: jax.Array) -> jax.Array:
        return forward(params, images, self.config, self.mesh)

    def make_train_step(self, optimizer=None):
        optimizer = optimizer or optax.sgd(0.1, momentum=0.9)

        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, self.config, self.mesh
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return jax.jit(train_step, donate_argnums=(0, 1))

    def batch_sharding(self) -> NamedSharding | None:
        if self.mesh is None:
            return None
        from bee_code_interpreter_tpu.parallel.mesh import batch_axes

        return NamedSharding(self.mesh, P(batch_axes(self.mesh)))
