"""Bundled TPU-native model family for the sandbox runtime.

The reference ships no models (it is a code-execution service; SURVEY.md §2) —
these exist as the sandbox's first-class numerical payloads: the BASELINE.json
benchmark configs (MNIST MLP under data parallelism, a llama-style transformer
under dp×tp×sp) and the flagship model behind __graft_entry__.py / bench.py.
"""

from bee_code_interpreter_tpu.models.transformer import (  # noqa: F401
    Transformer,
    TransformerConfig,
)
from bee_code_interpreter_tpu.models.mnist import MnistMlp  # noqa: F401
from bee_code_interpreter_tpu.models.vision import (  # noqa: F401
    ResNet,
    ResNetConfig,
)
from bee_code_interpreter_tpu.models.vit import (  # noqa: F401
    ViT,
    ViTConfig,
)
from bee_code_interpreter_tpu.models.speculative import (  # noqa: F401
    speculative_generate,
)
from bee_code_interpreter_tpu.models.beam import beam_search  # noqa: F401
from bee_code_interpreter_tpu.models.serving import (  # noqa: F401
    ContinuousBatcher,
    SamplingParams,
)
from bee_code_interpreter_tpu.models.engine import Engine  # noqa: F401
from bee_code_interpreter_tpu.models.replicated import (  # noqa: F401
    ReplicatedEngine,
)
from bee_code_interpreter_tpu.models.text import TextEngine  # noqa: F401
from bee_code_interpreter_tpu.models.hf_loader import (  # noqa: F401
    config_from_hf,
    load_llama_params,
)
