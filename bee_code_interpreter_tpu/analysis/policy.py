"""Config-declared workload policy + the edge analyzer (docs/analysis.md).

The :class:`PolicyEngine` evaluates one :class:`~.inspect.SourceInspection`
against operator-declared rules (``APP_POLICY_DENY_IMPORTS``,
``APP_POLICY_DENY_CALLS``, … — comma-separated, parsed here) plus built-in
call *shapes* the lists can name:

- ``fork_in_loop``  — ``os.fork``/``os.forkpty`` inside a loop body
- ``raw_socket``    — direct socket construction/connection
- ``subprocess``    — any ``subprocess.*`` entry point or the ``os`` spawn
                      family (``os.system``, ``os.popen``, ``os.exec*``,
                      ``os.spawn*``)

Severities: ``deny`` findings reject the request at the edge (HTTP 422 /
gRPC INVALID_ARGUMENT — a client fault, SLI-good on both transports, per
the convention docs/observability.md "SLOs" establishes); ``warn`` findings
annotate the response and count a metric, but the execution proceeds.

:class:`WorkloadAnalyzer` is the piece the API edges hold: one call runs
the single AST pass, evaluates policy, predicts deps, and accounts all of
it (``analysis`` stage span, ``bci_analysis_seconds``,
``bci_analysis_rejections_total{rule}``,
``bci_analysis_dep_predictions_total``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from bee_code_interpreter_tpu.analysis.inspect import (
    SourceInspection,
    inspect_source,
)
from bee_code_interpreter_tpu.observability import span

# bci_analysis_seconds buckets: the gate budget is sub-millisecond (the
# acceptance bound is < 1ms p50 added to the warm path), so the default
# request buckets (50ms+) would put every observation in the first bucket.
ANALYSIS_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
)

_FORK_CALLS = frozenset({"os.fork", "os.forkpty"})
_RAW_SOCKET_CALLS = frozenset(
    {"socket.socket", "socket.create_connection", "socket.socketpair"}
)
_OS_EXEC_PREFIXES = ("os.exec", "os.spawn", "os.posix_spawn")

# --- cost classification (docs/analysis.md "Cost classes") ----------------
#: The closed label set of ``bci_analysis_cost_class_total{class}`` and the
#: ``cost_class`` hint on spans / wide events / ``ExecuteResponse.analysis``.
COST_CLASSES = ("cheap", "loopy", "io_heavy", "install_heavy", "accelerator")
#: Cost classes the cost-aware admission gate (APP_ADMISSION_COST_AWARE)
#: treats as heavy-lane work.
HEAVY_COST_CLASSES = frozenset({"io_heavy", "install_heavy", "accelerator"})

#: Top-level imports that mark a submission as ACCELERATOR-bound: the ML
#: frameworks the image pins (runtime/dep_guess.SKIP's accelerator block —
#: importing them never predicts a pip install, so this check is the only
#: signal) plus the wider framework family. Checked against the import set
#: the one AST pass already collected — a jax-free submission pays a set
#: intersection, nothing else (the <1 ms gate budget, bench-asserted).
ACCELERATOR_IMPORTS = frozenset(
    {
        "jax", "jaxlib", "libtpu", "flax", "optax", "orbax", "chex",
        "haiku", "pallas", "torch", "torch_xla", "functorch", "triton",
        "tensorflow", "keras", "cupy",
    }
)

#: Blocking-I/O call sites (alias-resolved names/prefixes): their presence
#: upgrades a workload to ``io_heavy`` — wall-clock the sandbox will spend
#: off-CPU, which the router/admission should not weigh like a hot loop.
_IO_CALLS = frozenset(
    {
        "open",
        "os.system",
        "os.popen",
        "time.sleep",
        "urllib.request.urlopen",
        "socket.create_connection",
    }
)
_IO_PREFIXES = ("requests.", "subprocess.", "http.client.", "urllib3.")


def classify_cost(inspection: SourceInspection) -> str:
    """One of :data:`COST_CLASSES` for an analyzable submission, by
    dominant predicted expense — except ``accelerator``, which is checked
    FIRST because it is a PLACEMENT signal, not an expense rank: a
    jax/torch submission belongs on a TPU-capable replica whatever else
    it does (the ``/v1/fleet`` cost-mix export is the router's view), and
    the image-pinned frameworks never appear in ``predicted_deps`` so no
    other class can witness them. Then: a pip install dwarfs everything
    (``install_heavy``), blocking I/O dwarfs compute (``io_heavy``),
    nested loops mark compute-bound work (``loopy``), the rest is
    ``cheap``. Single-pass over facts the inspection already collected —
    the hint must fit inside the gate's <1 ms budget."""
    if inspection.imports & ACCELERATOR_IMPORTS:
        return "accelerator"
    if inspection.predicted_deps:
        return "install_heavy"
    for c in inspection.calls:
        if c.name in _IO_CALLS or c.name.startswith(_IO_PREFIXES):
            return "io_heavy"
    if inspection.max_loop_depth >= 2:
        return "loopy"
    return "cheap"


def _shape_fork_in_loop(inspection: SourceInspection) -> list[int]:
    return [c.line for c in inspection.calls if c.name in _FORK_CALLS and c.in_loop]


def _shape_raw_socket(inspection: SourceInspection) -> list[int]:
    return [c.line for c in inspection.calls if c.name in _RAW_SOCKET_CALLS]


def _shape_subprocess(inspection: SourceInspection) -> list[int]:
    return [
        c.line
        for c in inspection.calls
        if c.name.startswith("subprocess.")
        or c.name in ("os.system", "os.popen")
        or c.name.startswith(_OS_EXEC_PREFIXES)
    ]


# Shape name → detector returning the offending line numbers. Shape names
# are valid entries in the call-policy lists alongside dotted call names.
SHAPES = {
    "fork_in_loop": _shape_fork_in_loop,
    "raw_socket": _shape_raw_socket,
    "subprocess": _shape_subprocess,
}


@dataclass(frozen=True)
class Finding:
    """One policy hit. ``rule`` is CATEGORICAL — it becomes the Prometheus
    label on ``bci_analysis_rejections_total`` and is bounded by the size
    of the operator's policy lists, never by request content."""

    rule: str  # "import:socket" | "call:os.fork" | "shape:subprocess" | "path:/etc" | "syntax"
    severity: str  # "deny" | "warn"
    message: str

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity, "message": self.message}


def split_patterns(raw: str | None) -> tuple[str, ...]:
    """Comma-separated config string → pattern tuple (the same spelling
    convention as ``APP_SLO_LATENCY_MS``)."""
    if not raw:
        return ()
    return tuple(p.strip() for p in raw.split(",") if p.strip())


def _import_matches(pattern: str, imported: str) -> bool:
    """``socket`` matches ``socket`` and ``socket.anything``; dotted
    patterns (``google.auth``) match that subtree only."""
    return imported == pattern or imported.startswith(pattern + ".")


def _call_matches(pattern: str, call: str) -> bool:
    """Exact dotted name, or a ``pkg.*`` prefix wildcard."""
    if pattern.endswith(".*"):
        return call.startswith(pattern[:-1])
    return call == pattern


def _path_matches(pattern: str, literal: str) -> bool:
    # Normalize the pattern so "/etc/" and "/etc" declare the same rule:
    # either spelling matches the bare directory literal AND everything
    # under it (per path component — /etcetera stays unmatched).
    base = pattern.rstrip("/")
    if not base:  # pattern "/" — every absolute path literal is under it
        return True
    return literal == base or literal.startswith(base + "/")


class PolicyEngine:
    """Declared rules evaluated over one inspection. Construction validates
    nothing beyond shape-name spelling — an unknown shape in a call list is
    treated as a dotted name, which simply never matches; the analyze CLI
    (scripts/analyze.py) is the place to eyeball a policy."""

    def __init__(
        self,
        deny_imports: tuple[str, ...] = (),
        warn_imports: tuple[str, ...] = (),
        deny_calls: tuple[str, ...] = (),
        warn_calls: tuple[str, ...] = (),
        deny_paths: tuple[str, ...] = (),
        warn_paths: tuple[str, ...] = (),
        dynamic_import: str = "warn",
    ) -> None:
        self.deny_imports = tuple(deny_imports)
        self.warn_imports = tuple(warn_imports)
        self.deny_calls = tuple(deny_calls)
        self.warn_calls = tuple(warn_calls)
        self.deny_paths = tuple(deny_paths)
        self.warn_paths = tuple(warn_paths)
        # What an import whose target the dataflow layer could NOT
        # constant-fold means: "warn" (default — fail-open, annotated +
        # counted), "deny", or "off". Resolved dynamic imports are not
        # this rule's business: they hit deny_imports/warn_imports like
        # static imports (docs/analysis.md "Dataflow layer").
        self.dynamic_import = (
            dynamic_import if dynamic_import in ("off", "warn", "deny") else "warn"
        )

    @classmethod
    def from_config(cls, config) -> "PolicyEngine":
        return cls(
            deny_imports=split_patterns(config.policy_deny_imports),
            warn_imports=split_patterns(config.policy_warn_imports),
            deny_calls=split_patterns(config.policy_deny_calls),
            warn_calls=split_patterns(config.policy_warn_calls),
            deny_paths=split_patterns(config.policy_deny_paths),
            warn_paths=split_patterns(config.policy_warn_paths),
            dynamic_import=config.policy_dynamic_import,
        )

    @property
    def declared(self) -> bool:
        # dynamic_import="deny" counts as a declared policy: an
        # unanalyzable source could hide exactly the imports it denies, so
        # it must fail closed like any other deny rule. The "warn" DEFAULT
        # does not — it would flip every policy-less deployment's
        # unanalyzable handling from admit to refuse.
        return any(
            (
                self.deny_imports, self.warn_imports, self.deny_calls,
                self.warn_calls, self.deny_paths, self.warn_paths,
                self.dynamic_import == "deny",
            )
        )

    def unanalyzable_findings(self, reason: str) -> list[Finding]:
        """What an unanalyzable submission (parse blew a limit, or the
        source exceeds the analyzable-size bound) means under THIS policy:
        fail-closed when any rule is declared — a degenerate program must
        not become a policy bypass — nothing otherwise. Shared by the
        analyzer and the scripts/analyze.py dry run, so they can never
        disagree."""
        if not self.declared:
            return []
        return [
            Finding(
                rule="unanalyzable",
                severity="deny",
                message=(
                    f"source could not be analyzed ({reason}); a policy is "
                    "declared, so it cannot be admitted unchecked"
                ),
            )
        ]

    def evaluate(self, inspection: SourceInspection) -> list[Finding]:
        findings: list[Finding] = []
        for severity, imports, calls, paths in (
            ("deny", self.deny_imports, self.deny_calls, self.deny_paths),
            ("warn", self.warn_imports, self.warn_calls, self.warn_paths),
        ):
            for pattern in imports:
                hits = sorted(
                    i for i in inspection.imports if _import_matches(pattern, i)
                )
                # Dynamic imports whose target constant-folded resolve
                # against the SAME lists as static imports — `x =
                # __import__; x("socket")` must not outrun
                # deny_imports=socket (docs/analysis.md "Dataflow layer").
                dyn_hits = sorted(
                    m
                    for m in inspection.dynamic_imports
                    if _import_matches(pattern, m) and m not in hits
                )
                if hits or dyn_hits:
                    spelled = hits + [
                        f"{m} (dynamic, line(s) "
                        f"{', '.join(str(n) for n in sorted(inspection.dynamic_imports[m]))})"
                        for m in dyn_hits
                    ]
                    findings.append(
                        Finding(
                            rule=f"import:{pattern}",
                            severity=severity,
                            message=f"import of {', '.join(spelled)} is not allowed",
                        )
                    )
            for pattern in calls:
                if pattern in SHAPES:
                    lines = SHAPES[pattern](inspection)
                    if lines:
                        findings.append(
                            Finding(
                                rule=f"shape:{pattern}",
                                severity=severity,
                                message=(
                                    f"call shape {pattern} at line(s) "
                                    f"{', '.join(str(n) for n in sorted(lines))}"
                                ),
                            )
                        )
                    continue
                lines = sorted(
                    c.line
                    for c in inspection.calls
                    if _call_matches(pattern, c.name)
                )
                if lines:
                    findings.append(
                        Finding(
                            rule=f"call:{pattern}",
                            severity=severity,
                            message=(
                                f"call to {pattern} at line(s) "
                                f"{', '.join(str(n) for n in lines)}"
                            ),
                        )
                    )
            for pattern in paths:
                hits = sorted(
                    p
                    for p in inspection.path_literals
                    if _path_matches(pattern, p)
                )
                if hits:
                    findings.append(
                        Finding(
                            rule=f"path:{pattern}",
                            severity=severity,
                            message=(
                                f"path literal(s) under {pattern}: "
                                f"{', '.join(hits)}"
                            ),
                        )
                    )
        if self.dynamic_import != "off" and inspection.dynamic_import_sites:
            detail = "; ".join(
                f"line {line}: {reason}"
                for line, reason in inspection.dynamic_import_sites
            )
            findings.append(
                Finding(
                    rule="dynamic_import",
                    severity=self.dynamic_import,
                    message=(
                        f"import target cannot be resolved statically "
                        f"({detail}); the policy cannot vouch for what it "
                        "loads"
                    ),
                )
            )
        return findings


@dataclass
class AnalysisVerdict:
    """What one edge analysis decided. Exactly one of three outcomes:
    ``syntax_error`` set (fail-fast as a normal exit_code=1 response),
    ``denials`` non-empty (reject 422/INVALID_ARGUMENT), or proceed —
    possibly with warnings annotated and deps predicted.

    ``predicted_deps`` distinguishes "no claim" (``None`` — the source
    was unanalyzable, the sandbox must run its own scan) from the
    positive claim "scanned, install exactly this" (a list, possibly
    empty). ``cost_class`` is the scheduling hint (one of
    :data:`COST_CLASSES`; ``None`` when the source never analyzed)."""

    syntax_error: str | None
    denials: list[Finding]
    warnings: list[Finding]
    predicted_deps: list[str] | None
    cost_class: str | None = None

    def annotation(self) -> dict | None:
        """The response-side ``analysis`` block: warnings, the dep
        prediction, and the ``cost_class`` hint. Present on every
        successfully analyzed execution since the cost hint landed
        (docs/analysis.md "Cost classes"); absent only when the analyzer
        had nothing at all to say (unanalyzable / gate disabled)."""
        out: dict = {}
        if self.warnings:
            out["warnings"] = [f.to_dict() for f in self.warnings]
        if self.predicted_deps:
            out["predicted_deps"] = list(self.predicted_deps)
        if self.cost_class is not None:
            out["cost_class"] = self.cost_class
        return out or None

    def denial_detail(self) -> str:
        return "; ".join(f"{f.rule}: {f.message}" for f in self.denials)


class WorkloadAnalyzer:
    """The pre-flight gate both API edges run before any sandbox is
    touched. One instance per process (the composition root builds it from
    config and shares it, like the tracer)."""

    # Analysis is sub-ms for real submissions but runs ON the event loop;
    # parsing a multi-MB body would stall every in-flight request, so
    # longer sources are "unanalyzable" without ever being parsed.
    DEFAULT_MAX_SOURCE_BYTES = 262_144

    def __init__(
        self,
        policy: PolicyEngine | None = None,
        metrics=None,
        max_source_bytes: int | None = None,
    ) -> None:
        self._policy = policy or PolicyEngine()
        self._max_source_bytes = (
            max_source_bytes
            if max_source_bytes is not None
            else self.DEFAULT_MAX_SOURCE_BYTES
        )
        self._seconds = None
        self._rejections_total = None
        self._warnings_total = None
        self._dep_predictions_total = None
        self._dynamic_imports_total = None
        self._cost_class_total = None
        # Running per-class tallies, exported on GET /v1/fleet for the
        # fleet router's placement view (docs/fleet.md): what MIX of work
        # this replica has been analyzing, cheap scrape-free reads.
        self.cost_class_counts: dict[str, int] = {c: 0 for c in COST_CLASSES}
        if metrics is not None:
            self._seconds = metrics.histogram(
                "bci_analysis_seconds",
                "Edge static-analysis latency per submission",
                buckets=ANALYSIS_BUCKETS,
            )
            self._rejections_total = metrics.counter(
                "bci_analysis_rejections_total",
                "Submissions refused at the edge (syntax fail-fast + policy deny), by rule",
            )
            self._warnings_total = metrics.counter(
                "bci_analysis_warnings_total",
                "Policy warn findings annotated on responses, by rule",
            )
            self._dep_predictions_total = metrics.counter(
                "bci_analysis_dep_predictions_total",
                "PyPI dependencies predicted at the edge and shipped to the sandbox",
            )
            self._dynamic_imports_total = metrics.counter(
                "bci_analysis_dynamic_imports_total",
                "Dynamic-import sites seen by the dataflow layer, by action "
                "(resolved / warn / deny)",
            )
            self._cost_class_total = metrics.counter(
                "bci_analysis_cost_class_total",
                "Analyzed submissions by predicted workload cost class",
            )

    @classmethod
    def from_config(cls, config, metrics=None) -> "WorkloadAnalyzer | None":
        """The instance the composition root wires, or None when the edge
        gate is switched off (``APP_ANALYSIS_ENABLED=false``)."""
        if not config.analysis_enabled:
            return None
        return cls(
            policy=PolicyEngine.from_config(config),
            metrics=metrics,
            max_source_bytes=config.analysis_max_source_bytes,
        )

    @property
    def policy(self) -> PolicyEngine:
        return self._policy

    def analyze(self, source_code: str) -> AnalysisVerdict:
        """One submission through parse → policy → dep prediction, traced
        as the ``analysis`` stage and timed into ``bci_analysis_seconds``."""
        t0 = time.monotonic()
        with span("analysis") as s:
            # The bound is BYTES (what actually arrived on the wire), so
            # UTF-8-heavy source can't pack ~4x the limit into a passing
            # char count. A char count over the bound is already over
            # (UTF-8 is >= 1 byte/char) — multi-MB bodies are never
            # encoded just to be measured.
            source_bytes = (
                len(source_code)
                if len(source_code) > self._max_source_bytes
                else len(source_code.encode("utf-8", "surrogatepass"))
            )
            if source_bytes > self._max_source_bytes:
                inspection = SourceInspection(
                    analysis_error=(
                        f"source is at least {source_bytes} bytes of "
                        f"UTF-8, over the {self._max_source_bytes}-byte "
                        "analysis bound"
                    )
                )
            else:
                inspection = inspect_source(source_code)
            if inspection.syntax_error is not None:
                verdict = AnalysisVerdict(
                    syntax_error=inspection.syntax_error,
                    denials=[],
                    warnings=[],
                    predicted_deps=[],
                )
            elif inspection.analysis_error is not None:
                # The edge can make NO claim about this source (parse blew
                # a limit, or it is over the size bound): fail-closed under
                # a declared policy, else proceed to the sandbox with
                # prediction None so the in-pod scan runs as before the
                # gate existed.
                verdict = AnalysisVerdict(
                    syntax_error=None,
                    denials=self._policy.unanalyzable_findings(
                        inspection.analysis_error
                    ),
                    warnings=[],
                    predicted_deps=None,
                )
            else:
                findings = self._policy.evaluate(inspection)
                verdict = AnalysisVerdict(
                    syntax_error=None,
                    denials=[f for f in findings if f.severity == "deny"],
                    warnings=[f for f in findings if f.severity == "warn"],
                    predicted_deps=inspection.predicted_deps,
                    cost_class=classify_cost(inspection),
                )
            if s is not None:
                if verdict.syntax_error is not None:
                    s.attributes["analysis.outcome"] = "syntax_error"
                elif verdict.denials:
                    s.attributes["analysis.outcome"] = "deny"
                    s.attributes["analysis.rules"] = ",".join(
                        f.rule for f in verdict.denials
                    )
                elif inspection.analysis_error is not None:
                    s.attributes["analysis.outcome"] = "unanalyzable"
                else:
                    s.attributes["analysis.outcome"] = "ok"
                if verdict.warnings:
                    s.attributes["analysis.warnings"] = ",".join(
                        f.rule for f in verdict.warnings
                    )
                if verdict.predicted_deps:
                    s.attributes["analysis.predicted_deps"] = ",".join(
                        verdict.predicted_deps
                    )
                if verdict.cost_class is not None:
                    # analysis.* span attributes are lifted into the wide
                    # event's `analysis` block by the flight recorder, so
                    # the hint lands there for free.
                    s.attributes["analysis.cost_class"] = verdict.cost_class
        if self._seconds is not None:
            self._seconds.observe(time.monotonic() - t0)
        if self._rejections_total is not None:
            if verdict.syntax_error is not None:
                self._rejections_total.inc(rule="syntax")
            for f in verdict.denials:
                self._rejections_total.inc(rule=f.rule)
        if self._warnings_total is not None:
            for f in verdict.warnings:
                self._warnings_total.inc(rule=f.rule)
        if self._dep_predictions_total is not None and verdict.predicted_deps:
            self._dep_predictions_total.inc(len(verdict.predicted_deps))
        if verdict.cost_class is not None:
            self.cost_class_counts[verdict.cost_class] += 1
            if self._cost_class_total is not None:
                # "class" is a Python keyword, hence the dict spelling
                self._cost_class_total.inc(**{"class": verdict.cost_class})
        if self._dynamic_imports_total is not None:
            resolved_sites = sum(
                len(lines) for lines in inspection.dynamic_imports.values()
            )
            if resolved_sites:
                self._dynamic_imports_total.inc(resolved_sites, action="resolved")
            if inspection.dynamic_import_sites:
                action = self._policy.dynamic_import
                if action != "off":
                    self._dynamic_imports_total.inc(
                        len(inspection.dynamic_import_sites), action=action
                    )
        return verdict
