"""Cross-transport API-contract + exception-surface lint (docs/analysis.md
"Contract lint").

The service serves the SAME surface over two transports — aiohttp HTTP and
grpc.aio — plus the FleetRouter's proxy edge, and the repo's most repeated
review-hardening bug class is drift between them: an exception mapped to a
clean status on one transport escaping as UNKNOWN/500 on the other, a
query parameter parsed with different int/bool semantics per edge, an SLI
verdict transports disagree on, a `Retry-After` hint one hop strips. This
module holds that surface by construction, with two faces:

**Face 1 — surface extraction.** One AST pass over the edge files
(``api/http_server.py``, ``api/grpc_server.py``, ``fleet/app.py``,
``fleet/router.py``, ``api/models.py``) produces a machine-readable
surface model: every HTTP route (method, path, SSE vs unary, query-param
coercions, resilience/drain/SLI scope, the statuses it can emit, the
exception→status mapping its handlers implement), every gRPC method
(streaming kind, request shape, status codes, trailers), the router's
proxied surface and header-passthrough contract, and the pydantic
request/response models. ``scripts/analyze.py --surface`` dumps it; the
dump is checked in as ``docs/api_surface.json`` and enforced by a tier-1
golden test, so ANY surface change is an explicit, reviewed diff. The
same model is served as the ``surface`` section of ``/v1/debug/bundle``
(and its gRPC twin) so operators and the FleetRouter can read the route
table instead of hardcoding it.

**Face 2 — contract rules**, held at zero unexplained violations with the
asynclint suppression contract (justified entries only; stale ones FAIL):

- ``route-twin-missing``    every surfaced HTTP route must be declared a
  twin of a gRPC method (or carry a transport-specific exemption, e.g.
  ``GET /metrics``), and vice versa; a twin/exemption naming a surface
  that no longer exists is itself a violation — the map can only shrink
  honestly.
- ``status-mapping-drift``  per twin pair, the canonical status table
  (422/400→INVALID_ARGUMENT, 404→NOT_FOUND, 429→RESOURCE_EXHAUSTED with
  a ``retry-after-s`` trailer, 500→INTERNAL, 501→UNIMPLEMENTED,
  503→UNAVAILABLE, 504→DEADLINE_EXCEEDED) must hold in both directions —
  the ``InvalidSessionRequest``→UNKNOWN bug class (PR 7) as a rule.
- ``sli-parity``            twin pairs must agree on whether they run
  under the resilience ladder (admission + deadline + SLI sampling) and
  on drain exemption — the mid-stream-death SLI split (PR 7) as a rule.
- ``param-coercion-drift``  a parameter spelled on both transports must
  be coerced identically (int vs float vs truthy-string) and bounded
  identically (a negative ``limit`` 400s on HTTP, so it must
  INVALID_ARGUMENT on gRPC) — the ``bool("0")`` inversion (PR 9) as a
  rule.
- ``exception-escapes-as-500`` an exception type raisable in a handler
  body — its own ``raise`` statements plus one level into in-corpus
  callees, resolved through import aliases and parameter/attribute type
  annotations (the jaxlint cross-file precedent) — that no enclosing
  ``except`` arm, resilience-ladder arm, or declared mapping catches
  escapes as a generic 500/UNKNOWN (the NUL-ValueError bug class, PR 6).
- ``undocumented-route``    every surfaced route and RPC must appear in
  docs/ — an operator cannot reason about a surface they cannot find.

Approximation stance matches the engine underneath (dataflow.py): the
status/code sets over-approximate (every spelled status counts, reachable
or not), exception resolution under-approximates (a receiver the alias/
annotation pass cannot type makes no claim) — a finding is a real shape
in the edge code, and the suppression list is where a real-but-sanctioned
shape records its justification.
"""

from __future__ import annotations

import ast
import json
import re
import threading
from dataclasses import dataclass, field
from pathlib import Path

from bee_code_interpreter_tpu.analysis.asynclint import (
    PACKAGE_ROOT,
    Suppression,
    Violation,
)
from bee_code_interpreter_tpu.analysis.inspect import (
    collect_aliases,
    resolve_call_name,
)

#: The edge files the extractor reads, keyed by the surface scope each
#: belongs to (package-root-relative).
EDGE_FILES: dict[str, str] = {
    "http": "api/http_server.py",
    "grpc": "api/grpc_server.py",
    "router": "fleet/app.py",
}
ROUTER_CORE_FILE = "fleet/router.py"
MODELS_FILE = "api/models.py"

#: The canonical HTTP-status → gRPC-code table (docs/analysis.md "Contract
#: lint"). Forward: an HTTP status a handler emits requires the mapped
#: code on its twin. Reverse (CANONICAL_CODE_TO_STATUSES): a code the twin
#: emits requires one of the mapped statuses on the HTTP side.
CANONICAL_STATUS_TO_CODE: dict[int, str] = {
    400: "INVALID_ARGUMENT",
    404: "NOT_FOUND",
    422: "INVALID_ARGUMENT",
    429: "RESOURCE_EXHAUSTED",
    500: "INTERNAL",
    501: "UNIMPLEMENTED",
    503: "UNAVAILABLE",
    504: "DEADLINE_EXCEEDED",
}
CANONICAL_CODE_TO_STATUSES: dict[str, tuple[int, ...]] = {
    "INVALID_ARGUMENT": (400, 422),
    "NOT_FOUND": (404,),
    "RESOURCE_EXHAUSTED": (429,),
    "INTERNAL": (500,),
    "UNIMPLEMENTED": (501,),
    "UNAVAILABLE": (503,),
    "DEADLINE_EXCEEDED": (504,),
}
#: Codes that must ride with a trailing-metadata hint when emitted — the
#: gRPC spelling of the shed contract's Retry-After header.
TRAILER_REQUIRED: dict[str, str] = {"RESOURCE_EXHAUSTED": "retry-after-s"}

#: aiohttp's raisable response classes by leaf name → status.
AIOHTTP_RAISE_STATUS: dict[str, int] = {
    "HTTPBadRequest": 400,
    "HTTPUnauthorized": 401,
    "HTTPForbidden": 403,
    "HTTPNotFound": 404,
    "HTTPTooManyRequests": 429,
    "HTTPUnprocessableEntity": 422,
    "HTTPInternalServerError": 500,
    "HTTPNotImplemented": 501,
    "HTTPServiceUnavailable": 503,
    "HTTPGatewayTimeout": 504,
}

#: The resilience-ladder entry points: a handler that (transitively)
#: calls one runs under admission + deadline + SLI sampling, inherits the
#: ladder's statuses/codes/trailers, and has the ladder's exception arms
#: applied to everything raisable in its body.
LADDER_NAMES = frozenset(
    {"with_resilience", "_with_resilience", "_resilience_scope"}
)
#: Exceptions the shared ladder maps to clean statuses on both edges.
LADDER_CAUGHT = frozenset(
    {"AdmissionRejected", "DeadlineExceeded", "BreakerOpenError"}
)
#: Leaf names that are mapped/benign wherever they escape: cancellation
#: unwinds, abort IS the mapping, aiohttp HTTP* carry their own status,
#: and abstract-stub/interpreter-exit noise makes no contract claim.
MAPPED_EXCEPTIONS = frozenset(
    {"CancelledError", "AbortError", "StopAsyncIteration"}
)
BENIGN_EXCEPTIONS = frozenset(
    {"NotImplementedError", "AssertionError", "KeyboardInterrupt", "SystemExit"}
)

#: Helper spellings both edges use for the ("1","true","yes","on")
#: truthy-string coercion.
TRUTHY_HELPERS = frozenset({"_truthy_query", "_truthy"})

_GRPC_HANDLER_KINDS = {
    "unary_unary_rpc_method_handler": "unary",
    "unary_stream_rpc_method_handler": "server_streaming",
    "stream_unary_rpc_method_handler": "client_streaming",
    "stream_stream_rpc_method_handler": "bidi_streaming",
}

_HTTP_ADD_METHODS = {
    "add_get": "GET",
    "add_post": "POST",
    "add_put": "PUT",
    "add_patch": "PATCH",
    "add_delete": "DELETE",
}

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


# --------------------------------------------------------------------------
# surface model
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class QueryParam:
    """One request parameter as a transport coerces it: ``kind`` is the
    parse applied at the edge (int/float/truthy/str), ``bounded`` whether
    a negative value is rejected (compared against 0 somewhere in the
    handler)."""

    kind: str
    bounded: bool


@dataclass
class HttpRoute:
    method: str
    path: str
    handler: str
    file: str
    line: int
    scope: str = "http"  # "http" (api edge) or "router" (fleet proxy edge)
    sse: bool = False
    resilient: bool = False
    allow_draining: bool = False
    statuses: set[int] = field(default_factory=set)
    params: dict[str, QueryParam] = field(default_factory=dict)
    response_models: set[str] = field(default_factory=set)
    exception_statuses: dict[str, tuple[int, ...]] = field(default_factory=dict)

    @property
    def key(self) -> str:
        prefix = "router:" if self.scope == "router" else ""
        return f"{prefix}{self.method} {self.path}"


@dataclass
class GrpcMethod:
    service: str  # short service name (last dotted component)
    method: str
    file: str
    line: int
    streaming: str = "unary"
    request: str = "json-bytes"
    resilient: bool = False
    allow_draining: bool = False
    codes: set[str] = field(default_factory=set)
    trailers: set[str] = field(default_factory=set)
    params: dict[str, QueryParam] = field(default_factory=dict)
    exception_codes: dict[str, tuple[str, ...]] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.service}.{self.method}"


@dataclass
class Surface:
    http_path: str = EDGE_FILES["http"]
    grpc_path: str = EDGE_FILES["grpc"]
    http: list[HttpRoute] = field(default_factory=list)
    grpc: list[GrpcMethod] = field(default_factory=list)
    router: list[HttpRoute] = field(default_factory=list)
    router_headers: dict[str, list[str]] = field(default_factory=dict)
    models: dict[str, dict] = field(default_factory=dict)
    files_scanned: int = 0
    #: (file, handler-or-method, line, exception, via) tuples the
    #: exception-surface pass could not prove caught — rule input.
    escapes: list[tuple[str, str, int, str, str]] = field(default_factory=list)

    def http_by_key(self) -> dict[str, HttpRoute]:
        return {r.key: r for r in [*self.http, *self.router]}

    def grpc_by_key(self) -> dict[str, GrpcMethod]:
        return {m.key: m for m in self.grpc}


@dataclass(frozen=True)
class Twin:
    """One declared HTTP↔gRPC pair: the HTTP key (``"POST /v1/execute"``)
    and the gRPC method key(s) (``"CodeInterpreterService.Execute"``) that
    serve the same operation. A route split across two RPCs (buffered
    Execute + streaming ExecuteStream) lists both; checks run against the
    union of the twins' codes/trailers/params."""

    http: str
    grpc: tuple[str, ...]


@dataclass(frozen=True)
class Exemption:
    """One declared transport-specific surface with the reason it has no
    twin. ``surface`` is an exact HTTP/gRPC key, or a ``prefix*`` glob
    (``"router:*"`` — the whole proxy edge is single-transport by
    design). A stale exemption fails like a stale suppression."""

    surface: str
    reason: str

    def matches(self, key: str) -> bool:
        if self.surface.endswith("*"):
            return key.startswith(self.surface[:-1])
        return key == self.surface


#: The declared twin map for THIS repo's surface. Every entry is checked
#: against the extracted model both ways: an entry naming a route/method
#: that stopped existing is a route-twin-missing violation.
TWINS: tuple[Twin, ...] = (
    Twin(
        "POST /v1/execute",
        (
            "CodeInterpreterService.Execute",
            "CodeInterpreterService.ExecuteStream",
        ),
    ),
    Twin("POST /v1/parse-custom-tool", ("CodeInterpreterService.ParseCustomTool",)),
    Twin(
        "POST /v1/execute-custom-tool",
        ("CodeInterpreterService.ExecuteCustomTool",),
    ),
    Twin("POST /v1/sessions", ("SessionService.CreateSession",)),
    Twin("GET /v1/sessions", ("SessionService.ListSessions",)),
    Twin(
        "POST /v1/sessions/{session_id}/execute",
        (
            "SessionService.ExecuteInSession",
            "CodeInterpreterService.ExecuteStream",
        ),
    ),
    Twin(
        "POST /v1/sessions/{session_id}/checkpoint",
        ("SessionService.Checkpoint",),
    ),
    Twin(
        "POST /v1/sessions/{session_id}/rollback", ("SessionService.Rollback",)
    ),
    Twin("DELETE /v1/sessions/{session_id}", ("SessionService.DeleteSession",)),
    Twin("GET /v1/fleet", ("FleetService.GetFleet",)),
    Twin("GET /v1/fleet/events", ("FleetService.GetFleetEvents",)),
    Twin("GET /v1/slo", ("ObservabilityService.GetSlo",)),
    Twin("GET /v1/tenants", ("ObservabilityService.GetTenants",)),
    Twin("GET /v1/autoscale", ("ObservabilityService.GetAutoscale",)),
    Twin("GET /v1/serving", ("ObservabilityService.GetServing",)),
    Twin(
        "GET /v1/serving/requests",
        ("ObservabilityService.GetServingRequests",),
    ),
    Twin("GET /v1/events", ("ObservabilityService.GetEvents",)),
    Twin("GET /v1/accelerator", ("ObservabilityService.GetAccelerator",)),
    Twin("GET /v1/debug/bundle", ("ObservabilityService.GetDebugBundle",)),
    Twin("GET /v1/debug/tasks", ("ObservabilityService.GetTasks",)),
    Twin("GET /v1/debug/pprof", ("ObservabilityService.GetPprof",)),
)

#: Declared transport-specific surfaces — the honest single-transport
#: remainder, each with its reason.
EXEMPTIONS: tuple[Exemption, ...] = (
    Exemption(
        "GET /healthz",
        "the gRPC liveness surface is the standard grpc.health.v1 protocol "
        "(Health.Check/Watch), not a JSON twin",
    ),
    Exemption(
        "GET /metrics",
        "the Prometheus/OpenMetrics scrape surface is pull-based HTTP by "
        "definition",
    ),
    Exemption(
        "GET /v1/traces",
        "trace inspection is an HTTP-only debug API "
        "(docs/observability.md); traces export to OTLP for non-HTTP "
        "consumers",
    ),
    Exemption(
        "GET /v1/traces/{trace_id}",
        "trace inspection is an HTTP-only debug API (see GET /v1/traces)",
    ),
    Exemption(
        "POST /v1/profile",
        "on-demand jax.profiler capture is an HTTP-only operator surface "
        "(docs/observability.md 'Profiling workflow')",
    ),
    Exemption(
        "Health.Check",
        "standard grpc.health.v1 protocol; GET /healthz is the HTTP "
        "analogue",
    ),
    Exemption(
        "Health.Watch",
        "standard grpc.health.v1 protocol (streaming watch has no HTTP "
        "analogue; /healthz is polled)",
    ),
    Exemption(
        "ServerReflection.ServerReflectionInfo",
        "standard gRPC reflection protocol; descriptor discovery has no "
        "HTTP meaning",
    ),
    Exemption(
        "router:*",
        "the FleetRouter proxy edge is a single-transport HTTP tier by "
        "design (docs/fleet.md); it forwards to replicas that serve both "
        "transports",
    ),
)

#: The shipped suppression budget — same contract as the other self-lints
#: (asynclint/concurrencylint/jaxlint): every entry names WHY the flagged
#: shape is sound, and a stale entry fails tests/test_contractlint.py.
#: The audit's drift DEFECTS were fixed, not suppressed (CHANGES.md
#: PR 15); what remains sanctioned is one deliberate defensive shape.
SUPPRESSIONS: tuple[Suppression, ...] = (
    Suppression(
        path="api/http_server.py",
        rule="status-mapping-drift",
        contains="twin of GET /v1/events emits UNIMPLEMENTED",
        reason=(
            "GetEvents keeps a defensive UNIMPLEMENTED arm for a bare "
            "ObservabilityServicer embedding, but the arm is unreachable "
            "through GrpcServer, which — exactly like create_http_server "
            "— always wires a FlightRecorder, so the deployed twin of "
            "GET /v1/events can never answer UNIMPLEMENTED where HTTP "
            "lacks a 501"
        ),
    ),
    Suppression(
        path="api/http_server.py",
        rule="status-mapping-drift",
        contains="twin of GET /v1/debug/bundle emits UNIMPLEMENTED",
        reason=(
            "GetDebugBundle keeps a defensive UNIMPLEMENTED arm for a "
            "bare ObservabilityServicer embedding, but GrpcServer always "
            "wires the same debug-bundle fallback create_http_server "
            "has, so the deployed twin of GET /v1/debug/bundle can never "
            "answer UNIMPLEMENTED where HTTP lacks a 501"
        ),
    ),
)


@dataclass
class ContractReport:
    violations: list[Violation] = field(default_factory=list)
    suppressed: list[tuple[Violation, Suppression]] = field(default_factory=list)
    stale_suppressions: list[Suppression] = field(default_factory=list)
    surface: Surface = field(default_factory=Surface)

    @property
    def files_scanned(self) -> int:
        return self.surface.files_scanned

    @property
    def clean(self) -> bool:
        return not self.violations and not self.stale_suppressions

    def summary(self) -> str:
        lines = [str(v) for v in self.violations]
        lines += [
            f"stale suppression ({s.path} [{s.rule}]): no matching violation"
            for s in self.stale_suppressions
        ]
        return "\n".join(lines) or "clean"


# --------------------------------------------------------------------------
# per-function facts (statuses, codes, trailers, params, call edges)
# --------------------------------------------------------------------------


def _leaf(name: str | None) -> str | None:
    return name.rsplit(".", 1)[-1] if name else None


def _exc_leaf_names(expr: ast.expr | None) -> set[str]:
    """Leaf class names an ``except`` clause catches (tuple-aware)."""
    if expr is None:
        return {"BaseException"}  # bare except
    out: set[str] = set()
    elts = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
    for e in elts:
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, ast.Attribute):
            out.add(e.attr)
    return out


def _const_status(call: ast.Call) -> int | None:
    """The constant ``status=`` keyword of a response constructor, or 200
    when absent; None when spelled but not a constant (proxied
    passthrough — no claim)."""
    for kw in call.keywords:
        if kw.arg == "status":
            if isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, int
            ):
                return kw.value.value
            return None
    return 200


def _abort_code(call: ast.Call, aliases: dict[str, str]) -> str | None:
    """``context.abort(grpc.StatusCode.X, …)`` → ``"X"``."""
    func = call.func
    if not (isinstance(func, ast.Attribute) and func.attr == "abort"):
        return None
    if not call.args:
        return None
    name = resolve_call_name(call.args[0], aliases)
    if name and "StatusCode." in name:
        return name.rsplit(".", 1)[-1]
    return None


def _trailer_keys(call: ast.Call) -> set[str]:
    """String keys inside a ``set_trailing_metadata(((k, v), …))`` call."""
    out: set[str] = set()
    for arg in [*call.args, *[kw.value for kw in call.keywords]]:
        for node in ast.walk(arg):
            if isinstance(node, (ast.Tuple, ast.List)) and len(node.elts) == 2:
                key = node.elts[0]
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    out.add(key.value)
    return out


def _is_param_receiver(expr: ast.expr, aliases: dict[str, str]) -> bool:
    """Is this the thing request parameters are read off? ``request.query``
    (any base spelled ``.query``), a local named ``query``/``body`` (the
    edge convention for both the aiohttp multidict and the JSON-bytes
    dict), or a direct ``json.loads(…)`` of the raw request."""
    if isinstance(expr, ast.Attribute) and expr.attr == "query":
        return True
    if isinstance(expr, ast.Name) and expr.id in ("query", "body"):
        return True
    if isinstance(expr, ast.Call):
        name = resolve_call_name(expr.func, aliases)
        if name and name.endswith("json.loads"):
            return True
    return False


class _ParamReads:
    """Request-parameter reads in one function: node-identity → param
    name, so coercion classification can ask 'does this int(...) wrap a
    read of p?'."""

    def __init__(self) -> None:
        self.nodes: dict[int, str] = {}  # id(read node) -> param
        self.params: set[str] = set()
        self.bound: dict[str, set[str]] = {}  # local name -> params it holds

    def note(self, node: ast.AST, param: str) -> None:
        self.nodes[id(node)] = param
        self.params.add(param)

    def params_in(self, expr: ast.AST) -> set[str]:
        """Params read anywhere inside ``expr`` — directly or through a
        local the read was bound to."""
        out: set[str] = set()
        for node in ast.walk(expr):
            hit = self.nodes.get(id(node))
            if hit is not None:
                out.add(hit)
            if isinstance(node, ast.Name) and node.id in self.bound:
                out.update(self.bound[node.id])
        return out


@dataclass
class _FuncFacts:
    """Everything one function definition (nested defs included — a
    handler's ``run`` closure is part of its surface) contributes."""

    node: ast.AST
    name: str
    statuses: set[int] = field(default_factory=set)
    codes: set[str] = field(default_factory=set)
    trailers: set[str] = field(default_factory=set)
    sse: bool = False
    calls: set[str] = field(default_factory=set)  # bare callee names
    allow_draining: bool = False
    params: dict[str, QueryParam] = field(default_factory=dict)
    exception_statuses: dict[str, set[int]] = field(default_factory=dict)
    exception_codes: dict[str, set[str]] = field(default_factory=dict)
    response_models: set[str] = field(default_factory=set)


_TRUTHY_TUPLE = frozenset({"1", "true", "yes", "on"})


def _collect_func_facts(
    func: ast.AST, aliases: dict[str, str]
) -> _FuncFacts:
    facts = _FuncFacts(node=func, name=getattr(func, "name", "<fn>"))
    reads = _ParamReads()
    # pass 1: parameter reads + the locals they are bound to
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if (
                node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and _is_param_receiver(node.func.value, aliases)
            ):
                reads.note(node, node.args[0].value)
        elif isinstance(node, ast.Subscript):
            if (
                _is_param_receiver(node.value, aliases)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
            ):
                reads.note(node, node.slice.value)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if (
                node.func.id in TRUTHY_HELPERS
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                reads.note(node, node.args[1].value)
                facts.params[node.args[1].value] = QueryParam("truthy", False)
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name):
                hit = reads.params_in(node.value)
                if hit:
                    reads.bound.setdefault(target.id, set()).update(hit)
    # pass 2: coercion kinds, truthy membership tests, and 0-bounds
    kinds: dict[str, str] = {}
    bounded: set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id in ("int", "float") and node.args:
                for p in reads.params_in(node.args[0]):
                    kinds.setdefault(p, node.func.id)
        elif isinstance(node, ast.Compare):
            if any(isinstance(op, ast.In) for op in node.ops) and all(
                isinstance(c, ast.Constant) and c.value in _TRUTHY_TUPLE
                for comp in node.comparators
                if isinstance(comp, (ast.Tuple, ast.List))
                for c in comp.elts
            ) and any(
                isinstance(comp, (ast.Tuple, ast.List)) and comp.elts
                for comp in node.comparators
            ):
                for p in reads.params_in(node.left):
                    kinds[p] = "truthy"
            sides = [node.left, *node.comparators]
            has_zero = any(
                isinstance(s, ast.Constant) and s.value == 0 for s in sides
            )
            ordered = any(
                isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                for op in node.ops
            )
            if has_zero and ordered:
                for s in sides:
                    bounded.update(reads.params_in(s))
    for p in reads.params:
        if p in facts.params and facts.params[p].kind == "truthy":
            kind = "truthy"
        else:
            kind = kinds.get(p, "str")
        facts.params[p] = QueryParam(kind, p in bounded)
    # pass 3: statuses / codes / trailers / SSE / call edges / models
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            name = resolve_call_name(node.func, aliases)
            leaf = _leaf(name)
            if leaf == "json_response" or (
                leaf in ("Response", "StreamResponse")
                and name
                and ("web." in name or "aiohttp" in name)
            ):
                status = _const_status(node)
                if status is not None:
                    facts.statuses.add(status)
                if leaf == "StreamResponse":
                    facts.sse = True
            code = _abort_code(node, aliases)
            if code is not None:
                facts.codes.add(code)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "set_trailing_metadata"
            ):
                facts.trailers.update(_trailer_keys(node))
            # call edges by bare name. Attribute calls only follow the
            # underscore-helper convention (`self._resilience_scope`,
            # `s._with_resilience`): a public method on a data object
            # (`custom_tool_executor.execute`) must not alias a same-named
            # handler into this closure.
            if isinstance(node.func, ast.Name):
                facts.calls.add(node.func.id)
            elif isinstance(node.func, ast.Attribute) and node.func.attr.startswith(
                "_"
            ):
                facts.calls.add(node.func.attr)
            if any(
                kw.arg == "allow_draining"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            ):
                facts.allow_draining = True
            # response models: models.ExecuteResponse(...) / api_models.X
            if name and _leaf(name) and name.count(".") >= 1:
                root = name.split(".", 1)[0]
                if root in ("models", "api_models") or ".models." in (
                    aliases.get(root, "") + "."
                ):
                    facts.response_models.add(_leaf(name))
        elif isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            exc_name = (
                resolve_call_name(exc.func, aliases)
                if isinstance(exc, ast.Call)
                else resolve_call_name(exc, aliases)
            )
            exc_leaf = _leaf(exc_name)
            if exc_leaf in AIOHTTP_RAISE_STATUS:
                facts.statuses.add(AIOHTTP_RAISE_STATUS[exc_leaf])
    # pass 4: exception→status mapping per except arm
    for node in ast.walk(func):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            names = _exc_leaf_names(handler.type)
            arm = _FuncFacts(node=handler, name="<arm>")
            for inner in handler.body:
                for sub in ast.walk(inner):
                    if isinstance(sub, ast.Call):
                        subname = resolve_call_name(sub.func, aliases)
                        if _leaf(subname) == "json_response":
                            status = _const_status(sub)
                            if status is not None:
                                arm.statuses.add(status)
                        code = _abort_code(sub, aliases)
                        if code is not None:
                            arm.codes.add(code)
            for exc_name in names:
                if arm.statuses:
                    facts.exception_statuses.setdefault(exc_name, set()).update(
                        arm.statuses
                    )
                if arm.codes:
                    facts.exception_codes.setdefault(exc_name, set()).update(
                        arm.codes
                    )
    return facts


def _top_level_and_module_functions(tree: ast.Module) -> dict[str, list[ast.AST]]:
    """Function defs usable as in-file call-edge targets: module-level
    functions, functions at the immediate body level of a module-level
    function (the create_http_server handler/helper layer), and class
    methods — keyed by bare name. Deeper nesting (a handler's ``run``) is
    part of its parent's own walk and must not be an edge target."""
    table: dict[str, list[ast.AST]] = {}

    def add(node: ast.AST) -> None:
        table.setdefault(node.name, []).append(node)

    for stmt in tree.body:
        if isinstance(stmt, _FUNCTION_NODES):
            add(stmt)
            for inner in ast.iter_child_nodes(stmt):
                if isinstance(inner, _FUNCTION_NODES):
                    add(inner)
        elif isinstance(stmt, ast.ClassDef):
            for inner in stmt.body:
                if isinstance(inner, _FUNCTION_NODES):
                    add(inner)
    return table


@dataclass
class _FileFacts:
    """One edge file's fact base: per-function facts plus the transitive
    closure used to attribute helper statuses/codes to handlers."""

    tree: ast.Module
    path: str
    aliases: dict[str, str]
    table: dict[str, list[ast.AST]]
    facts: dict[int, _FuncFacts]

    def facts_for(self, name: str) -> _FuncFacts | None:
        defs = self.table.get(name)
        if not defs:
            return None
        return self.facts[id(defs[0])]

    def closure(self, name: str) -> _FuncFacts | None:
        """Facts for ``name`` with every unambiguous in-file callee's
        facts folded in (fixpoint over the call graph): the handler view
        with ladder statuses, helper 501s, and SSE bits attributed."""
        start = self.facts_for(name)
        if start is None:
            return None
        merged = _FuncFacts(node=start.node, name=start.name)
        seen: set[str] = set()
        frontier = [name]
        resilient = False
        while frontier:
            current = frontier.pop()
            if current in seen:
                continue
            seen.add(current)
            defs = self.table.get(current)
            if not defs or len(defs) > 1:
                continue  # unknown or ambiguous: no claim
            facts = self.facts[id(defs[0])]
            merged.statuses.update(facts.statuses)
            merged.codes.update(facts.codes)
            merged.trailers.update(facts.trailers)
            merged.sse = merged.sse or facts.sse
            merged.allow_draining = merged.allow_draining or facts.allow_draining
            merged.response_models.update(facts.response_models)
            for exc, statuses in facts.exception_statuses.items():
                merged.exception_statuses.setdefault(exc, set()).update(statuses)
            for exc, codes in facts.exception_codes.items():
                merged.exception_codes.setdefault(exc, set()).update(codes)
            for p, qp in facts.params.items():
                merged.params.setdefault(p, qp)
            for callee in facts.calls:
                if callee in LADDER_NAMES:
                    resilient = True
                if callee not in seen:
                    frontier.append(callee)
        merged.calls = set(seen)
        if resilient:
            merged.calls.add("__resilient__")
        return merged


def _file_facts(tree: ast.Module, path: str) -> _FileFacts:
    aliases = collect_aliases(tree)
    table = _top_level_and_module_functions(tree)
    facts: dict[int, _FuncFacts] = {}
    for defs in table.values():
        for node in defs:
            facts[id(node)] = _collect_func_facts(node, aliases)
    return _FileFacts(
        tree=tree, path=path, aliases=aliases, table=table, facts=facts
    )


# --------------------------------------------------------------------------
# HTTP / router route extraction
# --------------------------------------------------------------------------


def _extract_http_routes(
    ff: _FileFacts, scope: str
) -> list[HttpRoute]:
    routes: list[HttpRoute] = []
    for node in ast.walk(ff.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _HTTP_ADD_METHODS
            and len(node.args) >= 2
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and isinstance(node.args[1], ast.Name)
        ):
            continue
        handler = node.args[1].id
        merged = ff.closure(handler)
        route = HttpRoute(
            method=_HTTP_ADD_METHODS[node.func.attr],
            path=node.args[0].value,
            handler=handler,
            file=ff.path,
            line=node.lineno,
            scope=scope,
        )
        if merged is not None:
            route.sse = merged.sse
            route.resilient = "__resilient__" in merged.calls
            route.allow_draining = merged.allow_draining
            route.statuses = merged.statuses
            route.params = merged.params
            route.response_models = merged.response_models
            route.exception_statuses = {
                exc: tuple(sorted(statuses))
                for exc, statuses in merged.exception_statuses.items()
            }
        routes.append(route)
    routes.sort(key=lambda r: (r.path, r.method))
    return routes


# --------------------------------------------------------------------------
# gRPC registration + servicer extraction
# --------------------------------------------------------------------------


def _module_consts(tree: ast.Module) -> tuple[dict[str, str], dict[str, list[str]], dict[str, dict[str, str]]]:
    """Module-level constants the registrations reference: string consts
    (service names), string sequences (method tuples / dict keys), and
    per-method request-model names off dict values like
    ``{"Execute": (pb.ExecuteRequest, pb.ExecuteResponse)}``."""
    strings: dict[str, str] = {}
    seqs: dict[str, list[str]] = {}
    requests: dict[str, dict[str, str]] = {}
    for stmt in tree.body:
        # AnnAssign covers the typed spelling (`_METHODS: dict[...] = {…}`)
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            target, value = stmt.target, stmt.value
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target, value = stmt.targets[0], stmt.value
        else:
            continue
        if not isinstance(target, ast.Name):
            continue
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            strings[target.id] = value.value
        elif isinstance(value, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in value.elts
        ):
            seqs[target.id] = [e.value for e in value.elts]
        elif isinstance(value, ast.Dict):
            keys = [
                k.value
                for k in value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            ]
            if len(keys) == len(value.keys):
                seqs[target.id] = keys
                models: dict[str, str] = {}
                for k, v in zip(keys, value.values):
                    if isinstance(v, (ast.Tuple, ast.List)) and v.elts:
                        first = v.elts[0]
                        if isinstance(first, ast.Attribute):
                            models[k] = first.attr
                if models:
                    requests[target.id] = models
    return strings, seqs, requests


def _request_model_from_deserializer(expr: ast.expr | None) -> str | None:
    """``pb.ExecuteRequest.FromString`` → ``"ExecuteRequest"``; a plain
    name (``bytes`` / the ``passthrough`` local) → json-bytes; a bare
    ``req_cls.FromString`` (comprehension variable) → None (resolved from
    the methods dict instead)."""
    if expr is None:
        return None
    if isinstance(expr, ast.Name):
        return "json-bytes"
    if isinstance(expr, ast.Attribute) and expr.attr == "FromString":
        owner = expr.value
        if isinstance(owner, ast.Attribute):
            return owner.attr
        if isinstance(owner, ast.Name):
            return None  # comprehension variable: caller resolves per method
    return None


@dataclass
class _Registration:
    service: str
    methods: dict[str, tuple[str, str]]  # name -> (streaming kind, request)


def _handler_ctor_kind(call: ast.Call) -> str | None:
    name = call.func.attr if isinstance(call.func, ast.Attribute) else (
        call.func.id if isinstance(call.func, ast.Name) else None
    )
    return _GRPC_HANDLER_KINDS.get(name or "")


def _deserializer_expr(call: ast.Call) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == "request_deserializer":
            return kw.value
    return None


def _enclosing_function(tree: ast.Module, target: ast.AST) -> ast.AST | None:
    for node in ast.walk(tree):
        if isinstance(node, _FUNCTION_NODES):
            for sub in ast.walk(node):
                if sub is target:
                    return node
    return None


def _resolve_handlers_expr(
    expr: ast.expr,
    enclosing: ast.AST | None,
    strings: dict[str, str],
    seqs: dict[str, list[str]],
    requests: dict[str, dict[str, str]],
) -> dict[str, tuple[str, str]]:
    """The ``{method: rpc_method_handler(...)}`` mapping of one generic
    registration, whatever its spelling: a dict literal, a dict
    comprehension over a module tuple/dict, or a local name assigned one
    of those plus ``handlers["X"] = …`` additions."""
    out: dict[str, tuple[str, str]] = {}
    if isinstance(expr, ast.Dict):
        for k, v in zip(expr.keys, expr.values):
            if not (
                isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and isinstance(v, ast.Call)
            ):
                continue
            kind = _handler_ctor_kind(v) or "unary"
            request = (
                _request_model_from_deserializer(_deserializer_expr(v))
                or "json-bytes"
            )
            out[k.value] = (kind, request)
    elif isinstance(expr, ast.DictComp):
        gen = expr.generators[0]
        names: list[str] = []
        per_method_requests: dict[str, str] = {}
        if isinstance(gen.iter, ast.Name):
            names = seqs.get(gen.iter.id, [])
            per_method_requests = requests.get(gen.iter.id, {})
        elif (
            isinstance(gen.iter, ast.Call)
            and isinstance(gen.iter.func, ast.Attribute)
            and gen.iter.func.attr == "items"
            and isinstance(gen.iter.func.value, ast.Name)
        ):
            names = seqs.get(gen.iter.func.value.id, [])
            per_method_requests = requests.get(gen.iter.func.value.id, {})
        kind = "unary"
        request_default = "json-bytes"
        if isinstance(expr.value, ast.Call):
            kind = _handler_ctor_kind(expr.value) or "unary"
            deser = _request_model_from_deserializer(
                _deserializer_expr(expr.value)
            )
            if deser is not None:
                request_default = deser
        for name in names:
            out[name] = (kind, per_method_requests.get(name, request_default))
    elif isinstance(expr, ast.Name) and enclosing is not None:
        for node in ast.walk(enclosing):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if (
                    isinstance(target, ast.Name)
                    and target.id == expr.id
                    and isinstance(node.value, (ast.Dict, ast.DictComp))
                ):
                    out.update(
                        _resolve_handlers_expr(
                            node.value, enclosing, strings, seqs, requests
                        )
                    )
                elif (
                    isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == expr.id
                    and isinstance(target.slice, ast.Constant)
                    and isinstance(target.slice.value, str)
                    and isinstance(node.value, ast.Call)
                ):
                    kind = _handler_ctor_kind(node.value) or "unary"
                    request = (
                        _request_model_from_deserializer(
                            _deserializer_expr(node.value)
                        )
                        or "json-bytes"
                    )
                    out[target.slice.value] = (kind, request)
    return out


def _extract_registrations(ff: _FileFacts) -> list[_Registration]:
    strings, seqs, requests = _module_consts(ff.tree)
    out: list[_Registration] = []
    for node in ast.walk(ff.tree):
        if not isinstance(node, ast.Call):
            continue
        name = resolve_call_name(node.func, ff.aliases) or ""
        if not name.endswith("method_handlers_generic_handler"):
            continue
        if len(node.args) < 2:
            continue
        service_expr = node.args[0]
        if isinstance(service_expr, ast.Constant) and isinstance(
            service_expr.value, str
        ):
            service = service_expr.value
        elif isinstance(service_expr, ast.Name):
            service = strings.get(service_expr.id, service_expr.id)
        else:
            continue
        enclosing = _enclosing_function(ff.tree, node)
        methods = _resolve_handlers_expr(
            node.args[1], enclosing, strings, seqs, requests
        )
        if methods:
            out.append(
                _Registration(service=service.rsplit(".", 1)[-1], methods=methods)
            )
    return out


def _class_method_defs(tree: ast.Module) -> dict[str, list[tuple[str, ast.AST]]]:
    out: dict[str, list[tuple[str, ast.AST]]] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for inner in node.body:
                if isinstance(inner, _FUNCTION_NODES):
                    out.setdefault(inner.name, []).append((node.name, inner))
    return out


def _extract_grpc_methods(ff: _FileFacts) -> list[GrpcMethod]:
    registrations = _extract_registrations(ff)
    method_defs = _class_method_defs(ff.tree)
    out: list[GrpcMethod] = []
    for registration in registrations:
        for name, (kind, request) in registration.methods.items():
            defs = method_defs.get(name, [])
            line = defs[0][1].lineno if defs else 0
            method = GrpcMethod(
                service=registration.service,
                method=name,
                file=ff.path,
                line=line,
                streaming=kind,
                request=request,
            )
            merged = ff.closure(name)
            if merged is not None:
                method.resilient = "__resilient__" in merged.calls
                method.allow_draining = merged.allow_draining
                method.codes = merged.codes
                method.trailers = merged.trailers
                method.params = merged.params
                method.exception_codes = {
                    exc: tuple(sorted(codes))
                    for exc, codes in merged.exception_codes.items()
                }
            out.append(method)
    out.sort(key=lambda m: (m.service, m.method))
    return out


# --------------------------------------------------------------------------
# models + router-core extraction
# --------------------------------------------------------------------------


def _extract_models(tree: ast.Module) -> dict[str, dict]:
    """Pydantic request/response models: field name → {annotation,
    required} — the wire-shape half of the surface golden."""
    out: dict[str, dict] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        bases = {_leaf(resolve_call_name(b, {})) or "" for b in node.bases}
        if "BaseModel" not in bases:
            continue
        fields: dict[str, dict] = {}
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                fields[stmt.target.id] = {
                    "annotation": ast.unparse(stmt.annotation),
                    "required": stmt.value is None,
                }
        out[node.name] = fields
    return out


def _extract_router_headers(tree: ast.Module) -> dict[str, list[str]]:
    """The proxy's header contract off fleet/router.py's module tuples:
    which request headers are forwarded upstream and which response
    headers survive the hop (Retry-After lives or dies here — the PR 11
    bug class, golden-pinned)."""
    out: dict[str, list[str]] = {}
    labels = {
        "_FORWARD_HEADERS": "forward",
        "_PASSTHROUGH_RESPONSE_HEADERS": "response_passthrough",
    }
    for stmt in tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
            continue
        target = stmt.targets[0]
        if not (isinstance(target, ast.Name) and target.id in labels):
            continue
        if isinstance(stmt.value, (ast.Tuple, ast.List)):
            out[labels[target.id]] = [
                e.value
                for e in stmt.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
    return out


# --------------------------------------------------------------------------
# exception surface (corpus raises + per-handler escape computation)
# --------------------------------------------------------------------------


def _raise_leafs(func: ast.AST, aliases: dict[str, str]) -> set[str]:
    """Leaf names of exceptions a function's own body raises (bare
    re-raises make no claim)."""
    out: set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            name = (
                resolve_call_name(exc.func, aliases)
                if isinstance(exc, ast.Call)
                else resolve_call_name(exc, aliases)
            )
            leaf = _leaf(name)
            if leaf:
                out.add(leaf)
    return out


def _build_raise_corpus(root: Path) -> dict[str, frozenset[str]]:
    """``module.func`` / ``module.Class.method`` → the leaf exception
    names its own body raises, for every file in the package that spells
    ``raise`` at all (the cheap pre-scan discipline). One level deep by
    design: a handler's resolvable callees are checked against THEIR own
    raise statements, not a transitive closure — under-approximating, the
    safe direction for an escape rule with a suppression ledger."""
    corpus: dict[str, frozenset[str]] = {}
    for py in sorted(root.rglob("*.py")):
        try:
            source = py.read_text()
        except OSError:
            continue
        if "raise" not in source:
            continue
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        aliases = collect_aliases(tree)
        dotted_mod = str(py.relative_to(root.parent))[: -len(".py")].replace(
            "/", "."
        )
        for stmt in tree.body:
            if isinstance(stmt, _FUNCTION_NODES):
                raises = _raise_leafs(stmt, aliases)
                if raises:
                    corpus[f"{dotted_mod}.{stmt.name}"] = frozenset(raises)
            elif isinstance(stmt, ast.ClassDef):
                for inner in stmt.body:
                    if isinstance(inner, _FUNCTION_NODES):
                        raises = _raise_leafs(inner, aliases)
                        if raises:
                            corpus[f"{dotted_mod}.{stmt.name}.{inner.name}"] = (
                                frozenset(raises)
                            )
    return corpus


def _annotation_dotted(
    annotation: ast.expr | None, aliases: dict[str, str]
) -> str | None:
    """A parameter annotation resolved to the dotted class it names
    (``code_executor: CodeExecutor`` → the imported class's module path);
    Optional/union/string annotations make no claim."""
    if isinstance(annotation, ast.Name):
        return aliases.get(annotation.id)
    if isinstance(annotation, ast.Attribute):
        return resolve_call_name(annotation, aliases)
    return None


def _receiver_types(ff: _FileFacts) -> dict[int, dict[str, str]]:
    """Per function-def id: {receiver spelling → dotted class}. Two
    sources, both the dataflow layer's alias/value discipline: annotated
    parameters (``"code_executor"``), and self-attributes bound to an
    annotated constructor parameter (``"self._code_executor"``)."""
    out: dict[int, dict[str, str]] = {}

    # annotated params, inherited INTO nested defs: a handler closed over
    # create_http_server's `code_executor: CodeExecutor` parameter reads
    # that annotation exactly like its own (inner shadows win)
    def visit(node: ast.AST, inherited: dict[str, str]) -> None:
        if isinstance(node, _FUNCTION_NODES):
            own: dict[str, str] = {}
            args = node.args
            named = [*args.posonlyargs, *args.args, *args.kwonlyargs]
            for a in named:
                dotted = _annotation_dotted(a.annotation, ff.aliases)
                if dotted is not None:
                    own[a.arg] = dotted
            inherited = {**inherited, **own}
            if inherited:
                out[id(node)] = dict(inherited)
        for child in ast.iter_child_nodes(node):
            visit(child, inherited)

    visit(ff.tree, {})
    # self-attr types per class, from __init__ assignments of annotated
    # params, shared by every method of that class
    for node in ff.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        init = next(
            (
                m
                for m in node.body
                if isinstance(m, _FUNCTION_NODES) and m.name == "__init__"
            ),
            None,
        )
        if init is None:
            continue
        param_types = out.get(id(init), {})
        attr_types: dict[str, str] = {}
        for sub in ast.walk(init):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Attribute)
                and isinstance(sub.targets[0].value, ast.Name)
                and sub.targets[0].value.id == "self"
                and isinstance(sub.value, ast.Name)
                and sub.value.id in param_types
            ):
                attr_types[f"self.{sub.targets[0].attr}"] = param_types[
                    sub.value.id
                ]
        if attr_types:
            for m in node.body:
                if isinstance(m, _FUNCTION_NODES):
                    out.setdefault(id(m), {}).update(attr_types)
    return out


def _receiver_spelling(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return f"self.{expr.attr}"
    return None


def _walk_with_coverage(func: ast.AST):
    """Yield ``(node, covered)`` for every node in the function, where
    ``covered`` is the frozen set of exception leaf names the enclosing
    ``try`` arms HANDLE at that point. An arm handles only if it contains
    no bare ``raise`` (a re-raising arm maps nothing); handler/finally
    bodies and nested defs run outside the try's protection."""
    stack: list[tuple[ast.AST, frozenset[str]]] = [
        (child, frozenset()) for child in ast.iter_child_nodes(func)
    ]
    while stack:
        node, covered = stack.pop()
        yield node, covered
        if isinstance(node, ast.Try):
            caught: set[str] = set()
            for handler in node.handlers:
                handles = not any(
                    isinstance(sub, ast.Raise) and sub.exc is None
                    for sub in ast.walk(handler)
                )
                if handles:
                    caught.update(_exc_leaf_names(handler.type))
            inner = covered | frozenset(caught)
            for child in node.body:
                stack.append((child, inner))
            # the else block runs AFTER the try body completes and its
            # exceptions are NOT caught by this try's arms — it gets the
            # outer coverage, like the handlers and finally
            for child in node.orelse:
                stack.append((child, covered))
            for handler in node.handlers:
                for child in handler.body:
                    stack.append((child, covered))
            for child in node.finalbody:
                stack.append((child, covered))
            continue
        if isinstance(node, _FUNCTION_NODES):
            # a nested def's body runs when called, not under this try —
            # but the ladder/declared sets still apply (caller-side), so
            # reset only the lexical coverage
            for child in ast.iter_child_nodes(node):
                stack.append((child, frozenset()))
            continue
        for child in ast.iter_child_nodes(node):
            stack.append((child, covered))


def _handler_escapes(
    ff: _FileFacts,
    handler_name: str,
    corpus: dict[str, frozenset[str]],
    receiver_types: dict[int, dict[str, str]],
    resilient: bool,
) -> list[tuple[str, int, str, str]]:
    """(handler, line, exception, via) for every raisable exception the
    coverage walk cannot prove caught: local raises plus one level into
    callees resolved through import aliases and annotated receivers."""
    defs = ff.table.get(handler_name)
    if not defs:
        return []
    func = defs[0]
    baseline = MAPPED_EXCEPTIONS | BENIGN_EXCEPTIONS
    if resilient:
        baseline = baseline | LADDER_CAUGHT
    out: list[tuple[str, int, str, str]] = []
    seen: set[tuple[str, str]] = set()
    # receiver types visible in this handler: its own def plus nested defs
    types: dict[str, str] = {}
    for node in ast.walk(func):
        if isinstance(node, _FUNCTION_NODES):
            types.update(receiver_types.get(id(node), {}))
    types.update(receiver_types.get(id(func), {}))

    def flag(exc: str, via: str, line: int, covered: frozenset[str]) -> None:
        if exc in covered or exc in baseline:
            return
        if "Exception" in covered or "BaseException" in covered:
            return
        if exc.startswith("HTTP"):
            return  # aiohttp response classes carry their own status
        if (handler_name, exc) in seen:
            return
        seen.add((handler_name, exc))
        out.append((handler_name, line, exc, via))

    for node, covered in _walk_with_coverage(func):
        if isinstance(node, ast.Raise) and node.exc is not None:
            exc_expr = node.exc
            name = (
                resolve_call_name(exc_expr.func, ff.aliases)
                if isinstance(exc_expr, ast.Call)
                else resolve_call_name(exc_expr, ff.aliases)
            )
            leaf = _leaf(name)
            if leaf:
                flag(leaf, "local raise", node.lineno, covered)
        elif isinstance(node, ast.Call):
            # module-level function through an import alias
            if isinstance(node.func, ast.Name):
                dotted = ff.aliases.get(node.func.id)
                if dotted and dotted in corpus:
                    for exc in sorted(corpus[dotted]):
                        flag(exc, f"{node.func.id}()", node.lineno, covered)
            elif isinstance(node.func, ast.Attribute):
                spelled = _receiver_spelling(node.func.value)
                if spelled is not None and spelled in types:
                    key = f"{types[spelled]}.{node.func.attr}"
                    if key in corpus:
                        for exc in sorted(corpus[key]):
                            flag(
                                exc,
                                f"{spelled}.{node.func.attr}()",
                                node.lineno,
                                covered,
                            )
    return out


# --------------------------------------------------------------------------
# surface assembly
# --------------------------------------------------------------------------


def extract_surface(root: Path | str = PACKAGE_ROOT) -> Surface:
    """One pass over the edge files → the full surface model. Missing
    files are skipped (synthetic trees need only the scopes they test)."""
    root = Path(root)
    surface = Surface()
    corpus = _build_raise_corpus(root)
    for scope, rel in EDGE_FILES.items():
        py = root / rel
        if not py.exists():
            continue
        surface.files_scanned += 1
        rel_path = f"{root.name}/{rel}"
        if scope == "http":
            surface.http_path = rel_path
        elif scope == "grpc":
            surface.grpc_path = rel_path
        ff = _file_facts(ast.parse(py.read_text(), filename=rel_path), rel_path)
        receiver_types = _receiver_types(ff)
        if scope == "grpc":
            surface.grpc = _extract_grpc_methods(ff)
            for method in surface.grpc:
                for handler, line, exc, via in _handler_escapes(
                    ff, method.method, corpus, receiver_types, method.resilient
                ):
                    surface.escapes.append((ff.path, handler, line, exc, via))
        else:
            routes = _extract_http_routes(ff, scope)
            if scope == "http":
                surface.http = routes
            else:
                surface.router = routes
            for route in routes:
                for handler, line, exc, via in _handler_escapes(
                    ff, route.handler, corpus, receiver_types, route.resilient
                ):
                    surface.escapes.append((ff.path, handler, line, exc, via))
    router_core = root / ROUTER_CORE_FILE
    if router_core.exists():
        surface.files_scanned += 1
        surface.router_headers = _extract_router_headers(
            ast.parse(router_core.read_text())
        )
    models_py = root / MODELS_FILE
    if models_py.exists():
        surface.files_scanned += 1
        surface.models = _extract_models(ast.parse(models_py.read_text()))
    return surface


def surface_to_dict(surface: Surface) -> dict:
    """The checked-in golden's shape: deterministic ordering, NO line
    numbers (an edit that moves code without changing the surface must
    not churn the golden)."""

    def route_dict(r: HttpRoute) -> dict:
        return {
            "method": r.method,
            "path": r.path,
            "handler": r.handler,
            "sse": r.sse,
            "resilient": r.resilient,
            "allow_draining": r.allow_draining,
            "statuses": sorted(r.statuses),
            "query_params": {
                name: {"kind": p.kind, "bounded": p.bounded}
                for name, p in sorted(r.params.items())
            },
            "response_models": sorted(r.response_models),
            "exception_statuses": {
                exc: list(statuses)
                for exc, statuses in sorted(r.exception_statuses.items())
            },
        }

    def method_dict(m: GrpcMethod) -> dict:
        return {
            "service": m.service,
            "method": m.method,
            "streaming": m.streaming,
            "request": m.request,
            "resilient": m.resilient,
            "allow_draining": m.allow_draining,
            "codes": sorted(m.codes),
            "trailers": sorted(m.trailers),
            "params": {
                name: {"kind": p.kind, "bounded": p.bounded}
                for name, p in sorted(m.params.items())
            },
            "exception_codes": {
                exc: list(codes)
                for exc, codes in sorted(m.exception_codes.items())
            },
        }

    return {
        "version": 1,
        "http": [route_dict(r) for r in surface.http],
        "grpc": [method_dict(m) for m in surface.grpc],
        "router": [route_dict(r) for r in surface.router],
        "router_headers": {
            k: list(v) for k, v in sorted(surface.router_headers.items())
        },
        "models": {
            name: dict(sorted(fields.items()))
            for name, fields in sorted(surface.models.items())
        },
        "twins": [
            {"http": t.http, "grpc": list(t.grpc)}
            for t in sorted(TWINS, key=lambda t: t.http)
        ],
        "exemptions": [
            {"surface": e.surface, "reason": e.reason}
            for e in sorted(EXEMPTIONS, key=lambda e: e.surface)
        ],
    }


# --------------------------------------------------------------------------
# the contract rules
# --------------------------------------------------------------------------


def _v(path: str, line: int, rule: str, message: str) -> Violation:
    return Violation(path=path, line=line, rule=rule, message=message)


def _check_twins(
    surface: Surface, twins: tuple[Twin, ...], exemptions: tuple[Exemption, ...]
) -> list[Violation]:
    out: list[Violation] = []
    http = surface.http_by_key()
    grpc = surface.grpc_by_key()
    declared_http = {t.http for t in twins}
    declared_grpc = {key for t in twins for key in t.grpc}

    def exempt(key: str) -> bool:
        return any(e.matches(key) for e in exemptions)

    for key, route in http.items():
        if key not in declared_http and not exempt(key):
            out.append(
                _v(
                    route.file,
                    route.line,
                    "route-twin-missing",
                    f"HTTP route {key} has no declared gRPC twin and no "
                    "transport-specific exemption — declare one in "
                    "contractlint.TWINS/EXEMPTIONS so the mirror is a "
                    "reviewed decision, not an omission",
                )
            )
    for key, method in grpc.items():
        if key not in declared_grpc and not exempt(key):
            out.append(
                _v(
                    method.file,
                    method.line,
                    "route-twin-missing",
                    f"gRPC method {key} has no declared HTTP twin and no "
                    "transport-specific exemption (contractlint.TWINS/"
                    "EXEMPTIONS)",
                )
            )
    for twin in twins:
        if twin.http not in http:
            out.append(
                _v(
                    surface.http_path,
                    0,
                    "route-twin-missing",
                    f"twin map names HTTP route {twin.http}, which the "
                    "surface no longer contains — delete the stale entry",
                )
            )
        for key in twin.grpc:
            if key not in grpc:
                out.append(
                    _v(
                        surface.grpc_path,
                        0,
                        "route-twin-missing",
                        f"twin map names gRPC method {key}, which the "
                        "surface no longer contains — delete the stale entry",
                    )
                )
    surfaced = set(http) | set(grpc)
    for exemption in exemptions:
        if exemption.surface.endswith("*"):
            hit = any(exemption.matches(k) for k in surfaced)
        else:
            hit = exemption.surface in surfaced
        if not hit:
            out.append(
                _v(
                    surface.http_path,
                    0,
                    "route-twin-missing",
                    f"exemption for {exemption.surface} matches nothing on "
                    "the surface — delete the stale entry",
                )
            )
    return out


def _check_status_mapping(
    surface: Surface, twins: tuple[Twin, ...]
) -> list[Violation]:
    out: list[Violation] = []
    http = surface.http_by_key()
    grpc = surface.grpc_by_key()
    for twin in twins:
        route = http.get(twin.http)
        methods = [grpc[k] for k in twin.grpc if k in grpc]
        if route is None or not methods:
            continue  # stale entries are route-twin-missing's finding
        codes = set().union(*(m.codes for m in methods))
        trailers = set().union(*(m.trailers for m in methods))
        for status in sorted(route.statuses & CANONICAL_STATUS_TO_CODE.keys()):
            expected = CANONICAL_STATUS_TO_CODE[status]
            if expected not in codes:
                out.append(
                    _v(
                        route.file,
                        route.line,
                        "status-mapping-drift",
                        f"{twin.http} can answer {status} but its twin "
                        f"({', '.join(twin.grpc)}) never emits {expected} — "
                        "the same failure surfaces as UNKNOWN/OK there "
                        "(canonical table, docs/analysis.md 'Contract lint')",
                    )
                )
        for code in sorted(codes & CANONICAL_CODE_TO_STATUSES.keys()):
            # Reverse direction. INVALID_ARGUMENT is forward-only: the
            # JSON-bytes gRPC envelope can always fail to DECODE (an
            # encoding-level IA with no HTTP analogue — a GET query
            # string or an empty POST body cannot be malformed JSON), so
            # only the 422/400→IA direction is a contract claim.
            if code == "INVALID_ARGUMENT":
                continue
            expected_statuses = CANONICAL_CODE_TO_STATUSES[code]
            if not route.statuses & set(expected_statuses):
                out.append(
                    _v(
                        route.file,
                        route.line,
                        "status-mapping-drift",
                        f"twin of {twin.http} emits {code} but the HTTP "
                        "side never answers "
                        f"{'/'.join(map(str, expected_statuses))} — the "
                        "same failure has no HTTP spelling",
                    )
                )
        for code, trailer in TRAILER_REQUIRED.items():
            if code in codes and trailer not in trailers:
                out.append(
                    _v(
                        methods[0].file,
                        methods[0].line,
                        "status-mapping-drift",
                        f"twin of {twin.http} emits {code} without the "
                        f"`{trailer}` trailer — the HTTP side's Retry-After "
                        "hint has no gRPC spelling",
                    )
                )
    return out


def _check_sli_parity(
    surface: Surface, twins: tuple[Twin, ...]
) -> list[Violation]:
    out: list[Violation] = []
    http = surface.http_by_key()
    grpc = surface.grpc_by_key()
    for twin in twins:
        route = http.get(twin.http)
        if route is None:
            continue
        for key in twin.grpc:
            method = grpc.get(key)
            if method is None:
                continue
            if method.resilient != route.resilient:
                out.append(
                    _v(
                        method.file,
                        method.line,
                        "sli-parity",
                        f"{key} {'runs' if method.resilient else 'does not run'} "
                        f"under the resilience ladder but its twin {twin.http} "
                        f"{'does' if route.resilient else 'does not'} — the "
                        "transports would compute different SLIs for the "
                        "same workload",
                    )
                )
            elif method.allow_draining != route.allow_draining:
                out.append(
                    _v(
                        method.file,
                        method.line,
                        "sli-parity",
                        f"{key} and {twin.http} disagree on the drain "
                        "exemption (allow_draining) — lease handoff would "
                        "work on one transport and 503 on the other",
                    )
                )
    return out


def _check_param_coercion(
    surface: Surface, twins: tuple[Twin, ...]
) -> list[Violation]:
    out: list[Violation] = []
    http = surface.http_by_key()
    grpc = surface.grpc_by_key()
    for twin in twins:
        route = http.get(twin.http)
        if route is None:
            continue
        for key in twin.grpc:
            method = grpc.get(key)
            if method is None:
                continue
            for name in sorted(set(route.params) & set(method.params)):
                hp, gp = route.params[name], method.params[name]
                if hp.kind != gp.kind:
                    out.append(
                        _v(
                            method.file,
                            method.line,
                            "param-coercion-drift",
                            f"`{name}` is parsed as {hp.kind} on {twin.http} "
                            f"but as {gp.kind} on {key} — the same value "
                            "means different things per transport (the "
                            "bool(\"0\") bug class)",
                        )
                    )
                elif hp.bounded != gp.bounded:
                    strict = twin.http if hp.bounded else key
                    loose = key if hp.bounded else twin.http
                    out.append(
                        _v(
                            method.file,
                            method.line,
                            "param-coercion-drift",
                            f"`{name}` is rejected when negative on {strict} "
                            f"but accepted on {loose} — bound both or "
                            "neither",
                        )
                    )
    return out


def _check_exception_escapes(surface: Surface) -> list[Violation]:
    return [
        _v(
            path,
            line,
            "exception-escapes-as-500",
            f"{exc} (via {via}) can escape `{handler}` uncaught: no except "
            "arm, resilience-ladder arm, or declared mapping turns it into "
            "a clean status — it surfaces as a generic 500/UNKNOWN",
        )
        for path, handler, line, exc, via in surface.escapes
    ]


def _route_doc_pattern(path: str) -> re.Pattern:
    escaped = re.escape(path)
    return re.compile(re.sub(r"\\\{[^}]*\\\}", r"\\{[^}]+\\}", escaped))


def _check_documented(
    surface: Surface, docs_text: str | None
) -> list[Violation]:
    if docs_text is None:
        return []
    out: list[Violation] = []
    seen_paths: set[str] = set()
    for route in [*surface.http, *surface.router]:
        if route.path in seen_paths:
            continue
        seen_paths.add(route.path)
        if not _route_doc_pattern(route.path).search(docs_text):
            out.append(
                _v(
                    route.file,
                    route.line,
                    "undocumented-route",
                    f"route {route.path} appears nowhere in docs/ — an "
                    "operator cannot find a surface that is not written "
                    "down",
                )
            )
    for method in surface.grpc:
        pattern = rf"(?<![A-Za-z0-9_]){re.escape(method.method)}(?![A-Za-z0-9_])"
        if not re.search(pattern, docs_text):
            out.append(
                _v(
                    method.file,
                    method.line,
                    "undocumented-route",
                    f"gRPC method {method.key} appears nowhere in docs/",
                )
            )
    return out


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


def _docs_corpus(root: Path) -> str:
    """Everything under <repo>/docs plus the README — the documentation
    corpus the undocumented-route rule searches."""
    repo = root.parent
    chunks: list[str] = []
    docs = repo / "docs"
    if docs.is_dir():
        for md in sorted(docs.glob("*.md")):
            chunks.append(md.read_text())
    readme = repo / "README.md"
    if readme.exists():
        chunks.append(readme.read_text())
    return "\n".join(chunks)


def lint_contract_paths(
    root: Path | str = PACKAGE_ROOT,
    twins: tuple[Twin, ...] = TWINS,
    exemptions: tuple[Exemption, ...] = EXEMPTIONS,
    suppressions: tuple[Suppression, ...] = SUPPRESSIONS,
    docs_text: str | None = None,
) -> ContractReport:
    """Extract the surface, run every contract rule, apply the
    suppression ledger — the tier-1 entry point. ``docs_text=None`` (the
    default) reads the repo docs corpus; pass ``""`` to disable the
    undocumented-route rule on synthetic trees."""
    root = Path(root)
    surface = extract_surface(root)
    if docs_text is None:
        docs_text = _docs_corpus(root)
    all_violations = [
        *_check_twins(surface, twins, exemptions),
        *_check_status_mapping(surface, twins),
        *_check_sli_parity(surface, twins),
        *_check_param_coercion(surface, twins),
        *_check_exception_escapes(surface),
        *_check_documented(surface, docs_text or None),
    ]
    report = ContractReport(surface=surface)
    used: set[Suppression] = set()
    for violation in all_violations:
        match = next((s for s in suppressions if s.matches(violation)), None)
        if match is None:
            report.violations.append(violation)
        else:
            used.add(match)
            report.suppressed.append((violation, match))
    report.stale_suppressions = [s for s in suppressions if s not in used]
    report.violations.sort(key=lambda v: (v.path, v.line, v.rule, v.message))
    return report


def surface_json(root: Path | str = PACKAGE_ROOT) -> dict:
    """The golden document: ``python scripts/analyze.py --surface``
    regenerates it; tests/test_contractlint.py compares it against
    docs/api_surface.json."""
    return surface_to_dict(extract_surface(root))


# Memoized by hand rather than lru_cache: the FAILURE outcome must cache
# too (a stripped image without the source tree must pay the failing scan
# once, not once per bundle pull), and the lock keeps two first-pullers
# from scanning concurrently.
_SURFACE_MEMO: dict[str, str | None] = {}
_SURFACE_LOCK = threading.Lock()


def _compute_surface_section() -> str | None:
    try:
        report = lint_contract_paths()
        return json.dumps(
            {
                "model": surface_to_dict(report.surface),
                "lint": {
                    "clean": report.clean,
                    "violations": len(report.violations),
                    "suppressed": len(report.suppressed),
                    "stale_suppressions": len(report.stale_suppressions),
                },
            }
        )
    except Exception:
        return None


def surface_section() -> dict | None:
    """The ``surface`` section of ``/v1/debug/bundle``: the extraction
    model plus the live lint verdict and suppression count, computed once
    per process (a pure function of the installed source; None where the
    source tree isn't readable) and cached — success and failure alike."""
    with _SURFACE_LOCK:
        if "section" not in _SURFACE_MEMO:
            _SURFACE_MEMO["section"] = _compute_surface_section()
    value = _SURFACE_MEMO["section"]
    return json.loads(value) if value is not None else None


def surface_section_nowait() -> dict | None:
    """The request-path view: the cached section when the scan has
    completed, else ``{"status": "warming"}`` (kicking the warm thread if
    nothing is computing) — the event loop NEVER waits on the scan lock,
    so a bundle pulled right after process start answers immediately and
    the next pull carries the model."""
    if _SURFACE_LOCK.acquire(blocking=False):
        try:
            if "section" in _SURFACE_MEMO:
                value = _SURFACE_MEMO["section"]
                return json.loads(value) if value is not None else None
        finally:
            _SURFACE_LOCK.release()
        warm_surface_cache()
        return {"status": "warming"}
    return {"status": "warming"}  # the warm thread holds the lock: scanning


def warm_surface_cache() -> threading.Thread:
    """Fill the surface cache off the event loop. The scan is hundreds of
    milliseconds of synchronous AST work; both server constructors kick
    this daemon thread at build time so the first debug-bundle pull —
    usually mid-incident — doesn't stall the loop computing it."""
    thread = threading.Thread(
        target=surface_section, name="contractlint-surface-warm", daemon=True
    )
    thread.start()
    return thread
