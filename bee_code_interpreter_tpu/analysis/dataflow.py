"""Intraprocedural CFG + dataflow over ``ast`` (docs/analysis.md "Dataflow layer").

One reusable engine with two very different consumers:

- the **concurrency lint** (``analysis/concurrencylint.py``) walks per-function
  control-flow graphs asking path questions — "is there an await between this
  read and that write", "does every path from this ``acquire()`` pass a
  ``release()``" — with every statement annotated by the ``async with`` lock
  scopes that enclose it;
- the **workload policy** (``analysis/policy.py`` via ``inspect.py``) uses the
  same reaching-definitions + alias layer to resolve *values*: what dotted
  origin a name can hold at a call site (``x = __import__; x("socket")``) and
  whether a string argument constant-folds (``getattr(os, "sys" + "tem")``).

Design constraints, in order: never crash on valid Python (every construct has
a conservative fallback), stay intraprocedural (one function or the module
body at a time; nested functions get the enclosing module's *single-assignment*
bindings as extra aliases, nothing more), and stay cheap — the policy consumer
runs on the request path under a <1 ms p50 budget (bench.py asserts it), so
everything here is a single flattening pass plus a small fixpoint over
statement nodes.

Approximations are one-directional by rule: the CFG *over*-approximates paths
(every statement in a ``try`` may reach every handler; a ``finally`` body is
duplicated for abrupt exits), which is the safe direction for "a release must
exist on all paths"; value resolution *under*-approximates (a name with two
conflicting reaching definitions resolves to both origins, an unresolvable
expression to none), the safe direction for deny rules.
"""

from __future__ import annotations

import ast
import builtins
from dataclasses import dataclass, field

_BUILTIN_NAMES = frozenset(dir(builtins))

#: Virtual exit node id: edges to EXIT mean "the function returns/raises out".
EXIT = -1

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: Callables whose *value* is an import of their first (string) argument.
IMPORT_FUNCTIONS = frozenset(
    {
        "__import__",
        "builtins.__import__",
        "importlib.import_module",
        "importlib.__import__",
    }
)


def expr_text(expr: ast.expr) -> str | None:
    """Dotted source text of a plain ``Name``/``Attribute`` chain
    (``self._lock``, ``mod.sub.thing``); ``None`` for anything else —
    call results, subscripts and constants have no stable identity to
    compare lock scopes or receivers by."""
    parts: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def iter_own_exprs(stmt: ast.stmt):
    """The expressions a statement itself evaluates, excluding the bodies
    of nested functions/lambdas and — for compound statements — excluding
    sub-statement bodies (those become their own CFG nodes). ``ClassDef``
    is a leaf in the CFG, so its whole body (minus nested functions)
    counts as its own region."""
    if isinstance(stmt, (ast.If, ast.While)):
        roots: list[ast.AST] = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots = [stmt.target, stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [item.context_expr for item in stmt.items]
        roots += [
            item.optional_vars
            for item in stmt.items
            if item.optional_vars is not None
        ]
    elif isinstance(stmt, ast.Try):
        roots = []
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        roots = list(stmt.decorator_list)
    elif isinstance(stmt, ast.ClassDef):
        roots = list(stmt.decorator_list) + list(stmt.bases) + list(stmt.body)
    else:
        roots = [stmt]
    stack = list(roots)
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNCTION_NODES):
            continue
        if isinstance(node, ast.expr):
            yield node
        for child in ast.iter_child_nodes(node):
            stack.append(child)


def _stmt_has_await(stmt: ast.stmt) -> bool:
    """Does evaluating THIS statement's own region suspend? ``async for``
    headers and ``async with`` enters are await points by construction."""
    if isinstance(stmt, (ast.AsyncFor, ast.AsyncWith)):
        return True
    return any(isinstance(e, ast.Await) for e in iter_own_exprs(stmt))


def _assigned_names(stmt: ast.stmt) -> set[str]:
    """Plain names this statement (re)binds — the kill/gen set for
    reaching definitions. Attribute/subscript targets are not name
    bindings and are tracked separately by consumers."""
    names: set[str] = set()

    def targets(node: ast.expr) -> None:
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                targets(elt)
        elif isinstance(node, ast.Starred):
            targets(node.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            targets(t)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                targets(item.optional_vars)
    elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
        for alias in stmt.names:
            names.add((alias.asname or alias.name).split(".", 1)[0])
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        names.add(stmt.name)
    # walrus bindings inside the statement's own expressions
    for e in iter_own_exprs(stmt):
        if isinstance(e, ast.NamedExpr) and isinstance(e.target, ast.Name):
            names.add(e.target.id)
    return names


def _assign_value(stmt: ast.stmt, name: str) -> ast.expr | None:
    """The RHS expression that gives ``name`` its value at this def site,
    when one exists in a resolvable single-target shape. Tuple unpacking,
    loop targets, and with-as bindings return None ("unknown value")."""
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, ast.Name) and t.id == name:
                return stmt.value
    elif isinstance(stmt, ast.AnnAssign):
        if isinstance(stmt.target, ast.Name) and stmt.target.id == name:
            return stmt.value
    return None


#: Sentinel distinguishing "this expression is not a literal shape" from a
#: legitimate ``None`` fold result in :func:`_fold_literal`.
_NOT_LITERAL = object()


def _fold_literal(expr: ast.expr, recurse):
    """The literal constant-folding arms (string constants, ``+`` of
    foldables, all-literal f-strings) shared by BOTH folding modes —
    :meth:`FunctionFlow.fold_str` and :meth:`ScopeBindings.fold_str` differ
    only in how they resolve a *name*, never in what a literal is.
    Returns :data:`_NOT_LITERAL` when the expression needs name
    resolution (or cannot fold structurally)."""
    if isinstance(expr, ast.Constant):
        return expr.value if isinstance(expr.value, str) else None
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = recurse(expr.left)
        right = recurse(expr.right)
        return left + right if left is not None and right is not None else None
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for value in expr.values:
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                parts.append(value.value)
            else:
                return None
        return "".join(parts)
    return _NOT_LITERAL


@dataclass
class StmtNode:
    """One flattened statement in the CFG. ``held_scopes`` is the set of
    ``(context-expression text, id of the enclosing async-with statement)``
    pairs lexically enclosing this statement — the SCOPE identity matters:
    two separate ``async with self._lock`` blocks hold the same lock NAME
    but release it in between, which is exactly the window the RMW rule
    exists to catch. ``held_locks`` is the name-only projection for rules
    that compare against lock names (self-deadlock)."""

    idx: int
    stmt: ast.stmt
    succs: set[int] = field(default_factory=set)
    has_await: bool = False
    held_scopes: frozenset[tuple[str, int]] = frozenset()
    defines: set[str] = field(default_factory=set)

    @property
    def held_locks(self) -> frozenset[str]:
        return frozenset(name for name, _scope in self.held_scopes)

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)


class FunctionFlow:
    """CFG + reaching definitions for ONE scope: a function body or the
    module body (``scope`` is the FunctionDef/AsyncFunctionDef/Module).

    ``outer_origins``/``outer_consts`` carry the enclosing module's
    single-assignment bindings into nested scopes — enough to resolve
    ``IMP = __import__`` at module level used inside a function, without
    pretending to be interprocedural."""

    def __init__(
        self,
        scope: ast.AST,
        aliases: dict[str, str] | None = None,
        outer_origins: dict[str, set[str]] | None = None,
        outer_consts: dict[str, str] | None = None,
    ) -> None:
        self.scope = scope
        self.aliases = aliases or {}
        self.outer_origins = outer_origins or {}
        self.outer_consts = outer_consts or {}
        self.nodes: list[StmtNode] = []
        self._stmt_to_idx: dict[int, int] = {}  # id(ast stmt) -> node idx
        body = list(getattr(scope, "body", []))
        self._build_seq(body, EXIT, loops=[], finallies=[], exc=(), held=frozenset())
        # entry is the first statement of the body (nodes are created in
        # source order by _build_seq's reverse fold, so re-derive it):
        self.entry = self._stmt_to_idx[id(body[0])] if body else EXIT
        self._preds: dict[int, set[int]] | None = None
        self._reach_in: list[dict[str, frozenset[int]]] | None = None
        self.assigned_names: set[str] = set()
        for node in self.nodes:
            self.assigned_names |= node.defines

    # ------------------------------------------------------------ build
    def _new_node(
        self, stmt: ast.stmt, held: frozenset[tuple[str, int]]
    ) -> StmtNode:
        node = StmtNode(
            idx=len(self.nodes),
            stmt=stmt,
            has_await=_stmt_has_await(stmt),
            held_scopes=held,
            defines=_assigned_names(stmt),
        )
        self.nodes.append(node)
        self._stmt_to_idx[id(stmt)] = node.idx
        return node

    def _build_seq(
        self, stmts, succ, *, loops, finallies, exc, held
    ) -> int:
        """Flatten a statement sequence; returns the entry node idx (or
        ``succ`` for an empty sequence). Built by a reverse fold so each
        statement's successor is already known."""
        entry = succ
        for stmt in reversed(stmts):
            entry = self._build_stmt(
                stmt, entry, loops=loops, finallies=finallies, exc=exc, held=held
            )
        return entry

    def _abrupt_target(self, finallies) -> int:
        return finallies[-1] if finallies else EXIT

    def _build_stmt(self, stmt, succ, *, loops, finallies, exc, held) -> int:
        node = self._new_node(stmt, held)
        kw = dict(loops=loops, finallies=finallies, exc=exc, held=held)
        if isinstance(stmt, ast.If):
            body = self._build_seq(stmt.body, succ, **kw)
            orelse = self._build_seq(stmt.orelse, succ, **kw) if stmt.orelse else succ
            node.succs = {body, orelse}
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            orelse = self._build_seq(stmt.orelse, succ, **kw) if stmt.orelse else succ
            inner_loops = loops + [(succ, node.idx)]  # (break, continue)
            body = self._build_seq(
                stmt.body, node.idx,
                loops=inner_loops, finallies=finallies, exc=exc, held=held,
            )
            node.succs = {body, orelse}
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner_held = held
            if isinstance(stmt, ast.AsyncWith):
                keys = {
                    (t, id(stmt)) for item in stmt.items
                    if (t := expr_text(item.context_expr)) is not None
                }
                inner_held = held | keys
            body = self._build_seq(
                stmt.body, succ,
                loops=loops, finallies=finallies, exc=exc, held=inner_held,
            )
            node.succs = {body}
        elif isinstance(stmt, ast.Try):
            after = succ
            inner_finallies = finallies
            if stmt.finalbody:
                # Two copies of the finally body: one continuing normally,
                # one continuing the abrupt exit it is unwinding toward.
                after = self._build_seq(stmt.finalbody, succ, **kw)
                abrupt = self._build_seq(
                    stmt.finalbody, self._abrupt_target(finallies), **kw
                )
                inner_finallies = finallies + [abrupt]
            handler_entries = []
            for handler in stmt.handlers:
                handler_entries.append(
                    self._build_seq(
                        handler.body, after,
                        loops=loops, finallies=inner_finallies, exc=exc, held=held,
                    )
                )
            orelse = (
                self._build_seq(
                    stmt.orelse, after,
                    loops=loops, finallies=inner_finallies, exc=exc, held=held,
                )
                if stmt.orelse
                else after
            )
            inner_exc = tuple(handler_entries) or (
                (inner_finallies[-1],) if stmt.finalbody else exc
            )
            body = self._build_seq(
                stmt.body, orelse,
                loops=loops, finallies=inner_finallies, exc=inner_exc, held=held,
            )
            node.succs = {body}
        elif isinstance(stmt, (ast.Return, ast.Raise)):
            if isinstance(stmt, ast.Raise) and exc:
                node.succs = set(exc)
            else:
                node.succs = {self._abrupt_target(finallies)}
        elif isinstance(stmt, ast.Break):
            node.succs = {loops[-1][0]} if loops else {self._abrupt_target(finallies)}
        elif isinstance(stmt, ast.Continue):
            node.succs = {loops[-1][1]} if loops else {self._abrupt_target(finallies)}
        else:
            node.succs = {succ}
        # Any statement inside a try body may raise into the handlers —
        # the over-approximation that keeps "on all paths" rules honest.
        if exc and not isinstance(stmt, (ast.Return, ast.Break, ast.Continue)):
            node.succs |= set(exc)
        return node.idx

    # --------------------------------------------------- reaching defs
    def preds(self) -> dict[int, set[int]]:
        if self._preds is None:
            preds: dict[int, set[int]] = {n.idx: set() for n in self.nodes}
            for n in self.nodes:
                for s in n.succs:
                    if s != EXIT:
                        preds[s].add(n.idx)
            self._preds = preds
        return self._preds

    def reach_in(self, idx: int) -> dict[str, frozenset[int]]:
        """name → def-site node ids reaching the ENTRY of statement ``idx``."""
        if self._reach_in is None:
            self._compute_reaching()
        return self._reach_in[idx]

    def _compute_reaching(self) -> None:
        n = len(self.nodes)
        reach_in: list[dict[str, frozenset[int]]] = [{} for _ in range(n)]
        preds = self.preds()
        out: list[dict[str, frozenset[int]]] = [{} for _ in range(n)]

        def transfer(idx: int, in_map):
            node = self.nodes[idx]
            if not node.defines:
                return in_map
            new = dict(in_map)
            for name in node.defines:
                new[name] = frozenset((idx,))
            return new

        # Source order first (nodes are created roughly in source order):
        # forward dataflow over a mostly-reducible CFG then converges in
        # one or two sweeps instead of thrashing backwards.
        worklist = list(range(n - 1, -1, -1))
        while worklist:
            idx = worklist.pop()
            merged: dict[str, frozenset[int]] = {}
            for p in preds[idx]:
                for name, defs in out[p].items():
                    if name in merged:
                        merged[name] = merged[name] | defs
                    else:
                        merged[name] = defs
            if merged != reach_in[idx]:
                reach_in[idx] = merged
            new_out = transfer(idx, merged)
            if new_out != out[idx]:
                out[idx] = new_out
                for s in self.nodes[idx].succs:
                    if s != EXIT:
                        worklist.append(s)
        self._reach_in = reach_in

    # ------------------------------------------------- value resolution
    def idx_of(self, stmt: ast.stmt) -> int | None:
        return self._stmt_to_idx.get(id(stmt))

    def resolve_name(self, name: str, at_idx: int, _depth: int = 0) -> set[str]:
        """Possible dotted origins of ``name`` at statement ``at_idx``:
        import aliases, reaching single assignments (followed through
        plain-name and ``getattr``/``__import__`` chains), and enclosing-
        module single-assignment bindings. Empty set = unresolvable."""
        if _depth > 6:
            return set()
        defs = self.reach_in(at_idx).get(name) if 0 <= at_idx < len(self.nodes) else None
        if defs:
            origins: set[str] = set()
            for d in defs:
                value = _assign_value(self.nodes[d].stmt, name)
                if value is not None:
                    origins |= self.expr_origins(value, d, _depth + 1)
                elif name in self.aliases and isinstance(
                    self.nodes[d].stmt, (ast.Import, ast.ImportFrom)
                ):
                    origins.add(self.aliases[name])
            return origins
        if name in self.aliases:
            return {self.aliases[name]}
        if name in self.assigned_names:
            return set()  # assigned on some path we can't see through
        if name in self.outer_origins:
            return set(self.outer_origins[name])
        # An unbound, unaliased bare name resolves to the builtin itself
        # (`__import__`, `getattr`, `open`); anything else — parameters,
        # names bound by constructs we don't track — has no origin.
        return {name} if name in _BUILTIN_NAMES else set()

    def expr_origins(self, expr: ast.expr, at_idx: int, _depth: int = 0) -> set[str]:
        """Dotted origins an expression's VALUE may be: names/attributes
        resolve through :meth:`resolve_name`; ``getattr(x, "a")`` with a
        foldable name resolves like ``x.a``; ``__import__("m")``-shaped
        calls resolve to the module ``m`` itself."""
        if _depth > 6:
            return set()
        if isinstance(expr, ast.Name):
            return self.resolve_name(expr.id, at_idx, _depth)
        if isinstance(expr, ast.Attribute):
            return {
                f"{base}.{expr.attr}"
                for base in self.expr_origins(expr.value, at_idx, _depth + 1)
            }
        if isinstance(expr, ast.Call):
            func_origins = self.expr_origins(expr.func, at_idx, _depth + 1)
            out: set[str] = set()
            if func_origins & IMPORT_FUNCTIONS and expr.args:
                folded = self.fold_str(expr.args[0], at_idx)
                if folded:
                    out.add(folded)
            if "getattr" in func_origins and len(expr.args) >= 2:
                attr = self.fold_str(expr.args[1], at_idx)
                if attr and attr.isidentifier():
                    out |= {
                        f"{base}.{attr}"
                        for base in self.expr_origins(
                            expr.args[0], at_idx, _depth + 1
                        )
                    }
            return out
        return set()

    def fold_str(self, expr: ast.expr, at_idx: int, _depth: int = 0) -> str | None:
        """Constant-fold an expression to a string: literals, ``+`` of
        foldables, f-strings with only literal parts, and names whose
        every reaching definition folds to the SAME value. ``None`` means
        "not a compile-time constant" — the dynamic_import rule's case."""
        if _depth > 6:
            return None
        literal = _fold_literal(
            expr, lambda e: self.fold_str(e, at_idx, _depth + 1)
        )
        if literal is not _NOT_LITERAL:
            return literal
        if isinstance(expr, ast.Name):
            defs = (
                self.reach_in(at_idx).get(expr.id)
                if 0 <= at_idx < len(self.nodes)
                else None
            )
            if not defs:
                return self.outer_consts.get(expr.id)
            folded: set[str] = set()
            for d in defs:
                value = _assign_value(self.nodes[d].stmt, expr.id)
                if value is None:
                    return None
                one = self.fold_str(value, d, _depth + 1)
                if one is None:
                    return None
                folded.add(one)
            return folded.pop() if len(folded) == 1 else None
        return None

    # ------------------------------------------------------ path queries
    def reaches(self, a: int, b: int) -> bool:
        """Is there a CFG path from (after) statement ``a`` to ``b``?"""
        seen: set[int] = set()
        stack = [s for s in self.nodes[a].succs if s != EXIT]
        while stack:
            idx = stack.pop()
            if idx == b:
                return True
            if idx in seen:
                continue
            seen.add(idx)
            stack.extend(s for s in self.nodes[idx].succs if s != EXIT)
        return False

    def await_between(self, a: int, b: int) -> bool:
        """Does some path from ``a`` to ``b`` cross an await point? ``b``'s
        own await counts (``self.x = await f() + r`` suspends before the
        store); ``a``'s does not (its await happened before the read's
        value escaped)."""
        if a == b:
            # One statement reading and writing itself (AugAssign) is the
            # caller's case to judge — no path exists "between".
            return False
        seen: set[int] = set()
        stack = [s for s in self.nodes[a].succs if s != EXIT]
        while stack:
            idx = stack.pop()
            if idx in seen:
                continue
            seen.add(idx)
            if idx == b:
                if self.nodes[b].has_await:
                    return True
                continue  # path hit b without an await; keep exploring others
            if self.nodes[idx].has_await and (idx == b or self.reaches(idx, b)):
                return True
            stack.extend(s for s in self.nodes[idx].succs if s != EXIT)
        return False

    def reaches_without(self, a: int, b: int, predicate) -> bool:
        """Is there a CFG path from (after) ``a`` to ``b`` that never
        crosses a statement satisfying ``predicate``? The "lock still
        held here" query: an ``acquire()`` at ``a`` reaches ``b`` without
        passing a ``release()``."""
        seen: set[int] = set()
        stack = [s for s in self.nodes[a].succs if s != EXIT]
        while stack:
            idx = stack.pop()
            if idx == b:
                return True
            if idx in seen:
                continue
            seen.add(idx)
            if predicate(self.nodes[idx]):
                continue  # this path is blocked; others may still reach
            stack.extend(s for s in self.nodes[idx].succs if s != EXIT)
        return False

    def exit_reachable_without(self, start: int, predicate) -> bool:
        """Can EXIT be reached from (after) ``start`` without passing a
        statement for which ``predicate(node)`` is true? The shape of the
        lock-release rule: acquire → EXIT avoiding every release."""
        seen: set[int] = set()
        stack = list(self.nodes[start].succs)
        while stack:
            idx = stack.pop()
            if idx == EXIT:
                return True
            if idx in seen:
                continue
            seen.add(idx)
            if predicate(self.nodes[idx]):
                continue  # this path is satisfied; do not cross it
            stack.extend(self.nodes[idx].succs)
        return False


class ScopeBindings:
    """The FLOW-INSENSITIVE face of the dataflow layer: per-scope
    union-over-all-definitions value resolution, O(statements) to build
    and memoized to query — the mode the request-path policy consumer
    uses (the full CFG fixpoint in :class:`FunctionFlow` is for the
    offline concurrency lint; it is quadratic on adversarial input and
    the edge gate runs ON the event loop under a <1 ms budget).

    Union semantics are strictly *over*-approximating for origins (a name
    rebound ``x = print; x = __import__`` resolves to both — the safe
    direction for deny rules, and order-blind means padding the source
    with rebindings cannot hide one) and *under*-approximating for
    constant folding (a name folds only when every definition folds to
    the SAME string — a conflicting rebinding makes the argument
    non-constant, which lands in the ``dynamic_import`` rule, again the
    safe direction)."""

    def __init__(
        self,
        scope: ast.AST,
        aliases: dict[str, str],
        outer: "ScopeBindings | None" = None,
    ) -> None:
        self.scope = scope
        self.aliases = aliases
        self.outer = outer
        #: name -> list of RHS exprs; None entries are opaque definitions
        #: (loop targets, unpacking, parameters — no resolvable value).
        self._defs: dict[str, list[ast.expr | None]] = {}
        self._origin_memo: dict[str, set[str]] = {}
        self._fold_memo: dict[str, str | None] = {}
        #: names currently being resolved (cycle guard). Results computed
        #: while ANY name is in flight may be truncated by the cycle edge
        #: and must not be memoized — caching them would make `x = y; y =
        #: x; x = __import__` permanently unresolvable depending on query
        #: order, silently reopening the evasion this layer closes.
        self._active: set[str] = set()
        args = getattr(scope, "args", None)
        if args is not None:
            for a in (
                list(args.posonlyargs)
                + list(args.args)
                + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                self._defs.setdefault(a.arg, []).append(None)
        for stmt in self._own_stmts(scope):
            names = _assigned_names(stmt)
            for name in names:
                self._defs.setdefault(name, []).append(
                    _assign_value(stmt, name)
                )

    @staticmethod
    def _own_stmts(scope: ast.AST):
        """Statements belonging to this scope: the body, recursively, but
        never descending into nested function scopes. Class bodies are
        skipped for *bindings* (``class A: x = 1`` binds ``A.x``, not
        ``x``)."""
        stack = list(getattr(scope, "body", []))
        while stack:
            stmt = stack.pop()
            yield stmt
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            for field_name in ("body", "orelse", "finalbody"):
                stack.extend(getattr(stmt, field_name, []))
            for handler in getattr(stmt, "handlers", []):
                stack.extend(handler.body)
            for case in getattr(stmt, "cases", []):  # match statements
                stack.extend(case.body)

    def origins(self, name: str) -> set[str]:
        memo = self._origin_memo.get(name)
        if memo is not None:
            return memo
        if name in self._active:
            return set()  # resolution cycle edge (x = y; y = x)
        self._active.add(name)
        try:
            out: set[str] = set()
            if name in self._defs:
                for value in self._defs[name]:
                    if value is not None:
                        out |= self.expr_origins(value)
                if name in self.aliases:
                    out.add(self.aliases[name])
            elif name in self.aliases:
                out = {self.aliases[name]}
            elif self.outer is not None:
                out = self.outer.origins(name)
            elif name in _BUILTIN_NAMES:
                out = {name}
        finally:
            self._active.discard(name)
        if not self._active:
            # Top-level resolution only: a result computed under an
            # in-flight outer name may be cut short by the cycle guard.
            self._origin_memo[name] = out
        return out

    def expr_origins(self, expr: ast.expr) -> set[str]:
        if isinstance(expr, ast.Name):
            return self.origins(expr.id)
        if isinstance(expr, ast.Attribute):
            return {
                f"{base}.{expr.attr}"
                for base in self.expr_origins(expr.value)
            }
        if isinstance(expr, ast.Call):
            func_origins = self.expr_origins(expr.func)
            out: set[str] = set()
            if func_origins & IMPORT_FUNCTIONS and expr.args:
                folded = self.fold_str(expr.args[0])
                if folded:
                    out.add(folded)
            if "getattr" in func_origins and len(expr.args) >= 2:
                attr = self.fold_str(expr.args[1])
                if attr and attr.isidentifier():
                    out |= {
                        f"{base}.{attr}"
                        for base in self.expr_origins(expr.args[0])
                    }
            return out
        return set()

    def fold_str(self, expr: ast.expr) -> str | None:
        literal = _fold_literal(expr, self.fold_str)
        if literal is not _NOT_LITERAL:
            return literal
        if isinstance(expr, ast.Name):
            return self._fold_name(expr.id)
        return None

    def _fold_name(self, name: str) -> str | None:
        memo = self._fold_memo.get(name, False)
        if memo is not False:
            return memo
        fold_key = "fold:" + name  # distinct cycle domain from origins()
        if fold_key in self._active:
            return None  # folding cycle: not a constant
        self._active.add(fold_key)
        try:
            result: str | None = None
            if name in self._defs:
                folded: set[str] = set()
                ok = True
                for value in self._defs[name]:
                    one = self.fold_str(value) if value is not None else None
                    if one is None:
                        ok = False
                        break
                    folded.add(one)
                if ok and len(folded) == 1:
                    result = folded.pop()
            elif self.outer is not None:
                result = self.outer._fold_name(name)
        finally:
            self._active.discard(fold_key)
        if not any(k.startswith("fold:") for k in self._active):
            self._fold_memo[name] = result
        return result

    def own_calls(self):
        """Every ``ast.Call`` in this scope's own statements (class bodies
        included — they execute at module import; nested function bodies
        excluded — they are their own scope)."""
        for stmt in self._own_stmts(self.scope):
            for expr in iter_own_exprs(stmt):
                if isinstance(expr, ast.Call):
                    yield expr


def iter_scope_bindings(tree: ast.Module, aliases: dict[str, str]):
    """Yield :class:`ScopeBindings` for the module and every nested
    function, function scopes chained to the module scope (names not
    assigned locally resolve through the module's bindings)."""
    mod = ScopeBindings(tree, aliases)
    yield mod
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield ScopeBindings(node, aliases, outer=mod)


#: Identifier tokens whose absence PROVES a source cannot contain a
#: dynamic-import evasion this layer resolves — the cheap pre-scan that
#: keeps the dataflow pass off the hot path for ordinary submissions.
DYNAMIC_TRIGGER_NAMES = frozenset(
    {"__import__", "getattr", "import_module", "importlib", "builtins"}
)


def has_dynamic_triggers(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in DYNAMIC_TRIGGER_NAMES:
            return True
        if isinstance(node, ast.Import) and any(
            alias.name.split(".", 1)[0] in ("importlib", "builtins")
            for alias in node.names
        ):
            return True
        if isinstance(node, ast.ImportFrom) and (node.module or "").split(
            ".", 1
        )[0] in ("importlib", "builtins"):
            return True
    return False


def module_bindings(tree: ast.Module) -> dict[str, str]:
    """Names bound at module top level by ``import X [as y]`` → dotted
    module path. The receivers ``getattr(<module>, ...)`` policy rules
    recognize as modules."""
    out: dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                if alias.asname:
                    out[alias.asname] = alias.name
                else:
                    root = alias.name.split(".", 1)[0]
                    out[root] = root
    return out


def module_flow(tree: ast.Module, aliases: dict[str, str]) -> FunctionFlow:
    return FunctionFlow(tree, aliases=aliases)


def outer_bindings_for_nested(
    mod_flow: FunctionFlow,
) -> tuple[dict[str, set[str]], dict[str, str]]:
    """The module-level bindings a nested function may rely on: names
    assigned exactly ONCE at module level, resolved to origins / folded
    constants at their (single) def site. Single-assignment only — a
    rebound module global has no one value to carry inward."""
    def_sites: dict[str, list[int]] = {}
    for node in mod_flow.nodes:
        for name in node.defines:
            def_sites.setdefault(name, []).append(node.idx)
    origins: dict[str, set[str]] = {}
    consts: dict[str, str] = {}
    for name, sites in def_sites.items():
        if len(sites) != 1:
            continue
        stmt = mod_flow.nodes[sites[0]].stmt
        value = _assign_value(stmt, name)
        if value is None:
            if name in mod_flow.aliases and isinstance(
                stmt, (ast.Import, ast.ImportFrom)
            ):
                origins[name] = {mod_flow.aliases[name]}
            continue
        o = mod_flow.expr_origins(value, sites[0])
        if o:
            origins[name] = o
        c = mod_flow.fold_str(value, sites[0])
        if c is not None:
            consts[name] = c
    return origins, consts


def iter_scopes(tree: ast.Module, aliases: dict[str, str]):
    """Yield ``(scope_node, FunctionFlow)`` for the module body and every
    (arbitrarily nested) function, each nested flow seeded with the
    module's single-assignment bindings."""
    mod = module_flow(tree, aliases)
    yield tree, mod
    outer_origins, outer_consts = outer_bindings_for_nested(mod)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, FunctionFlow(
                node,
                aliases=aliases,
                outer_origins=outer_origins,
                outer_consts=outer_consts,
            )


def scope_calls(flow: FunctionFlow):
    """Every ``ast.Call`` in the scope's own statements, paired with the
    enclosing flattened statement idx (for reach-in lookups). Calls inside
    nested functions belong to the nested scope and are excluded."""
    for node in flow.nodes:
        for expr in iter_own_exprs(node.stmt):
            if isinstance(expr, ast.Call):
                yield expr, node.idx
