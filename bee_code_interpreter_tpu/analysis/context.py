"""Ambient carrier for edge dep predictions (docs/analysis.md).

The edge's single AST pass over a submission predicts the PyPI deps the
sandbox would otherwise discover with its own scan. That prediction must
reach the data plane without rewriting the ``CodeExecutor`` protocol and
every resilience front stacked on it — so, like the per-execution transfer
accounting, it rides the task context: the API edge stashes it right after
analysis, and whichever driver ends up talking to the sandbox (the HTTP
data-plane driver for pod/native backends, the in-process local executor)
reads it from the same context.

contextvars make this per-request by construction: each HTTP/gRPC handler
runs in its own task, and tasks the resilience layer spawns (hedges,
replays) copy the context at creation — a prediction can never bleed into
another request.
"""

from __future__ import annotations

from contextvars import ContextVar

_predicted_deps: ContextVar[tuple[str, ...] | None] = ContextVar(
    "bci_predicted_deps", default=None
)


def stash_predicted_deps(deps: list[str] | tuple[str, ...] | None) -> None:
    """Attach the edge's dep prediction to the current request context.
    ``None`` clears it — "no claim made", which the sandbox treats as
    "run your own scan". An EMPTY list is different: it is stashed as an
    empty tuple, the positive claim "the edge scanned and there is
    nothing to install", which makes the sandbox skip its scan."""
    _predicted_deps.set(tuple(deps) if deps is not None else None)


def predicted_deps() -> list[str] | None:
    """The ambient prediction, or None when the edge didn't analyze."""
    deps = _predicted_deps.get()
    return list(deps) if deps is not None else None
