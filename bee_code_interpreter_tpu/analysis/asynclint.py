"""AST self-lint for the asyncio control plane (docs/analysis.md "Self-lint").

The same static-analysis machinery that gates workloads at the edge
(``analysis/inspect.py``'s alias-resolved call names), turned on our own
packages. The service is ONE event loop; a single blocking call in an
``async def`` stalls every in-flight request, and a dropped task handle is
work nothing can cancel at drain. These are repo invariants, so they are
enforced by a tier-1 test (tests/test_asynclint.py), not a style guide.

Rules:

- ``blocking-call-in-async``  ``time.sleep`` / ``subprocess.run`` (and the
  rest of the blocking subprocess family) / ``requests.*`` /
  ``urllib.request.urlopen`` / ``os.system`` / builtin ``open`` where the
  NEAREST enclosing function is ``async def`` (a sync helper nested inside
  an async function runs in an executor or a subprocess — that is the
  sanctioned pattern and is not flagged).
- ``fire-and-forget-task``    ``asyncio.create_task`` / ``ensure_future`` /
  ``<loop>.create_task`` as a bare expression statement: the handle is
  dropped, so the task can never be awaited, cancelled at ``aclose``, or
  have its exception observed. Retaining it (assignment, return, await,
  passing it on — e.g. the backends' ``_spawn_background``) satisfies the
  rule.
- ``bare-except``             ``except:`` swallows ``CancelledError`` and
  breaks cooperative cancellation; catch ``Exception`` (or narrower).
- ``env-bypass``              an ``APP_*`` environment read outside
  ``config.py``: every service knob must flow through ``Config`` so
  ``from_env``/docs/configuration.md stay the single source of truth.
- ``undocumented-metric``     a ``bci_*`` name registered via
  ``counter``/``histogram``/``gauge`` that does not appear in
  docs/observability.md — an operator cannot alert on a metric they cannot
  find.

Suppressions are EXPLICIT: each carries the violating file, the rule, and
a one-line justification, and a suppression that no longer matches any
violation is itself an error (``stale_suppressions``) — the list can only
shrink honestly.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

from bee_code_interpreter_tpu.analysis.inspect import (
    collect_aliases,
    resolve_call_name,
)

PACKAGE_ROOT = Path(__file__).resolve().parent.parent
REPO_ROOT = PACKAGE_ROOT.parent
# The default scope is DERIVED from the package tree minus this explicit
# exclude list — a hand-maintained include list silently skipped every new
# top-level package (fleet/ shipped unlinted for a whole PR before being
# added by hand). Exclusions are the packages that are not asyncio
# control plane: model/kernel code (models/, parallel/, ops/) and the
# sandbox-side sitecustomize shim (runtime/shim/, which runs inside the
# pod's interpreter, not our event loop). Entries may be top-level package
# names or `pkg/subtree` path prefixes. These excluded trees are NOT
# unlinted: they are exactly jaxlint's ACCELERATOR_SCOPE (which imports
# this very tuple), so the two lint families partition the package and a
# module added anywhere lands in one of them by construction.
DEFAULT_EXCLUDES = (
    "models",
    "parallel",
    "ops",
    "runtime/shim",
)
DEFAULT_DOCS = REPO_ROOT / "docs" / "observability.md"


def default_packages(
    root: Path | str = PACKAGE_ROOT,
    excludes: tuple[str, ...] = DEFAULT_EXCLUDES,
) -> tuple[str, ...]:
    """Every top-level package under ``root`` (a directory with an
    ``__init__.py``) that is not excluded — the scope a freshly created
    subsystem lands in BY DEFAULT."""
    root = Path(root)
    return tuple(
        sorted(
            p.name
            for p in root.iterdir()
            if p.is_dir()
            and (p / "__init__.py").exists()
            and p.name not in excludes
        )
    )


def _excluded(rel_path: str, excludes: tuple[str, ...]) -> bool:
    """Is a package-root-relative file path under an excluded subtree?"""
    return any(
        rel_path == e or rel_path.startswith(e + "/") for e in excludes
    )

# Blocking entry points that must not run on the event loop. subprocess.Popen
# is absent deliberately: constructing it is quick; *communicating* with it
# blocks, and the blocking spellings are listed.
BLOCKING_CALLS = frozenset(
    {
        "time.sleep",
        "os.system",
        "subprocess.run",
        "subprocess.call",
        "subprocess.check_call",
        "subprocess.check_output",
        "urllib.request.urlopen",
        "open",
    }
)
BLOCKING_PREFIXES = ("requests.",)

_TASK_SPAWNERS = frozenset({"asyncio.create_task", "asyncio.ensure_future"})
_TASK_SPAWNER_ATTRS = frozenset({"create_task", "ensure_future"})
_METRIC_REGISTRARS = frozenset({"counter", "histogram", "gauge"})


@dataclass(frozen=True)
class Violation:
    path: str  # repo-relative
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass(frozen=True)
class Suppression:
    """One justified exception. ``path`` is a suffix match against the
    repo-relative file path; ``rule`` must match exactly. ``contains``
    (optional) narrows the entry to violations whose MESSAGE contains the
    substring — without it a file+rule entry sanctions every future
    violation of that rule in the file, which for surface-wide rules
    (contractlint anchors most status-mapping violations to the one HTTP
    edge file) would let one suppression neuter the rule."""

    path: str
    rule: str
    reason: str
    contains: str | None = None

    def matches(self, v: Violation) -> bool:
        return (
            v.rule == self.rule
            and v.path.endswith(self.path)
            and (self.contains is None or self.contains in v.message)
        )


# The shipped suppression budget: every entry names WHY the violation is
# acceptable. Additions need the same one-line justification.
SUPPRESSIONS: tuple[Suppression, ...] = (
    Suppression(
        path="services/local_code_executor.py",
        rule="blocking-call-in-async",
        reason=(
            "dev/test backend: workspace restore/snapshot do chunked I/O on "
            "local tmp files; per-chunk thread-pool hops would cost more than "
            "the sync writes they hide (the production pod path streams over "
            "HTTP instead)"
        ),
    ),
    Suppression(
        path="sessions/lease.py",
        rule="blocking-call-in-async",
        reason=(
            "LocalLease is the dev/test backend's lease: chunked I/O on "
            "local tmp files, same tradeoff (and the same sanction) as "
            "services/local_code_executor.py; the production pool leases "
            "stream over HTTP instead"
        ),
    ),
    Suppression(
        path="services/native_process_code_executor.py",
        rule="env-bypass",
        reason=(
            "APP_PYTHON selects the *sandbox* interpreter for spawned "
            "executor-server processes (docs/configuration.md); it configures "
            "the child environment contract, not this service's Config"
        ),
    ),
    Suppression(
        path="health_check.py",
        rule="env-bypass",
        reason=(
            "the health probe is a kubelet exec'd CLI run hundreds of times "
            "an hour; it reads the handful of APP_* listen-addr/TLS knobs "
            "directly instead of importing pydantic + Config (import cost "
            "dominates an exec probe), and each knob it reads is the same "
            "documented field Config owns"
        ),
    ),
    Suppression(
        path="runtime/executor_server.py",
        rule="env-bypass",
        reason=(
            "the in-sandbox executor server is configured SOLELY by the env "
            "the control plane injects into its pod/process (the child "
            "environment contract, docs/configuration.md); it has no Config "
            "object by design — it must match the C++/Rust servers' surface"
        ),
    ),
    Suppression(
        path="runtime/executor_server.py",
        rule="blocking-call-in-async",
        reason=(
            "the sandbox-side upload handler writes chunks to pod-local "
            "tmpfs; per-chunk thread-pool hops cost more than the sync "
            "writes they hide, and this loop serves ONE sandbox, not the "
            "control plane (same tradeoff as services/local_code_executor.py)"
        ),
    ),
    Suppression(
        path="runtime/executor_core.py",
        rule="env-bypass",
        reason=(
            "APP_JAX_CACHE_DIR is read in the sandbox-side core to export "
            "JAX_COMPILATION_CACHE_DIR into the child interpreter — part of "
            "the injected child environment contract, not this service's "
            "Config (the control-plane half IS a Config field: jax_cache_dir)"
        ),
    ),
)


@dataclass
class LintReport:
    violations: list[Violation] = field(default_factory=list)
    suppressed: list[tuple[Violation, Suppression]] = field(default_factory=list)
    stale_suppressions: list[Suppression] = field(default_factory=list)
    metric_names: set[str] = field(default_factory=set)

    @property
    def clean(self) -> bool:
        return not self.violations and not self.stale_suppressions

    def summary(self) -> str:
        lines = [str(v) for v in self.violations]
        lines += [
            f"stale suppression ({s.path} [{s.rule}]): no matching violation"
            for s in self.stale_suppressions
        ]
        return "\n".join(lines) or "clean"


class _Linter(ast.NodeVisitor):
    """One file's AST walk, tracking the nearest-enclosing-function kind."""

    def __init__(self, path: str, aliases: dict[str, str]) -> None:
        self.path = path
        self.aliases = aliases
        self.violations: list[Violation] = []
        self.metric_sites: list[tuple[str, int]] = []  # (bci name, line)
        self._async_stack: list[bool] = []  # nearest function is async?

    def _flag(self, node: ast.AST, rule: str, message: str) -> None:
        self.violations.append(
            Violation(
                path=self.path,
                line=getattr(node, "lineno", 0),
                rule=rule,
                message=message,
            )
        )

    # --- function scope tracking -----------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._async_stack.append(False)
        self.generic_visit(node)
        self._async_stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._async_stack.append(True)
        self.generic_visit(node)
        self._async_stack.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._async_stack.append(False)
        self.generic_visit(node)
        self._async_stack.pop()

    @property
    def _in_async(self) -> bool:
        return bool(self._async_stack) and self._async_stack[-1]

    # --- rules ------------------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._flag(
                node,
                "bare-except",
                "bare `except:` swallows CancelledError; catch Exception or narrower",
            )
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        # A task spawned as a bare statement: handle dropped on the floor.
        if isinstance(node.value, ast.Call):
            name = resolve_call_name(node.value.func, self.aliases)
            func = node.value.func
            # ANY `<receiver>.create_task(...)` / `.ensure_future(...)` as a
            # bare statement is flagged, whatever the receiver spelling —
            # `asyncio.`, `loop.`, `self._loop.`, a call chain. The name
            # check only adds the bare `create_task(...)` from-import form.
            if name in _TASK_SPAWNERS or (
                isinstance(func, ast.Attribute)
                and func.attr in _TASK_SPAWNER_ATTRS
            ):
                spelled = name or f"<…>.{func.attr}"
                self._flag(
                    node,
                    "fire-and-forget-task",
                    f"{spelled}(...) result discarded: retain the handle so "
                    "it can be awaited/cancelled (e.g. _spawn_background)",
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = resolve_call_name(node.func, self.aliases)
        if name is not None:
            if self._in_async and (
                name in BLOCKING_CALLS or name.startswith(BLOCKING_PREFIXES)
            ):
                self._flag(
                    node,
                    "blocking-call-in-async",
                    f"blocking call {name}() inside async def stalls the "
                    "event loop; use the asyncio equivalent or an executor",
                )
            if name in ("os.getenv", "os.environ.get") and node.args:
                self._check_env_key(node, node.args[0])
        # bci_* metric registration site (first positional arg is the name).
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_REGISTRARS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("bci_")
        ):
            self.metric_sites.append((node.args[0].value, node.lineno))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        target = resolve_call_name(node.value, self.aliases)
        if target == "os.environ":
            self._check_env_key(node, node.slice)
        self.generic_visit(node)

    def _check_env_key(self, node: ast.AST, key: ast.expr) -> None:
        if (
            isinstance(key, ast.Constant)
            and isinstance(key.value, str)
            and key.value.startswith("APP_")
        ):
            self._flag(
                node,
                "env-bypass",
                f"{key.value} read bypasses config.py; add a Config field "
                "so from_env and docs/configuration.md stay authoritative",
            )


def _lint_one(source: str, path: str) -> _Linter:
    tree = ast.parse(source, filename=path)
    linter = _Linter(path, collect_aliases(tree))
    linter.visit(tree)
    return linter


def _documented(name: str, docs_text: str) -> bool:
    """Word-bounded match: `bci_hedge` must not count as documented just
    because `bci_hedge_total` is — an operator searching the docs for the
    exact metric name has to find it."""
    return (
        re.search(
            rf"(?<![A-Za-z0-9_]){re.escape(name)}(?![A-Za-z0-9_])", docs_text
        )
        is not None
    )


def _metric_violations(
    linter: _Linter, docs_text: str | None
) -> list[Violation]:
    if docs_text is None:
        return []
    return [
        Violation(
            path=linter.path,
            line=line,
            rule="undocumented-metric",
            message=(
                f"{name} is registered here but not documented "
                "in docs/observability.md"
            ),
        )
        for name, line in linter.metric_sites
        if not _documented(name, docs_text)
    ]


def lint_source(
    source: str, path: str = "<memory>", docs_text: str | None = None
) -> list[Violation]:
    """Lint one source blob. ``docs_text`` enables the undocumented-metric
    rule (None skips it — unit-testing the other rules shouldn't require a
    docs corpus)."""
    linter = _lint_one(source, path)
    violations = linter.violations + _metric_violations(linter, docs_text)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations


def lint_paths(
    root: Path | str = PACKAGE_ROOT,
    packages: tuple[str, ...] | None = None,
    docs_path: Path | str | None = DEFAULT_DOCS,
    suppressions: tuple[Suppression, ...] = SUPPRESSIONS,
    excludes: tuple[str, ...] = DEFAULT_EXCLUDES,
) -> LintReport:
    """Lint the control-plane packages, apply the suppression list, and
    report what remains — the tier-1 entry point. ``packages=None`` (the
    default) derives the scope from the package tree so a new subsystem
    cannot ship unlinted by omission."""
    root = Path(root)
    if packages is None:
        packages = default_packages(root, excludes)
    docs_text: str | None = None
    if docs_path is not None:
        docs = Path(docs_path)
        docs_text = docs.read_text() if docs.exists() else ""
    report = LintReport()
    all_violations: list[Violation] = []
    # Top-level modules (application_context.py, health_check.py, __main__)
    # are control plane too — the composition root is where wiring bugs
    # live, and it is in no package directory.
    top_modules = tuple(sorted(root.glob("*.py")))
    package_files = [
        py for package in packages for py in sorted((root / package).rglob("*.py"))
    ]
    for py in [*top_modules, *package_files]:
        rel = str(py.relative_to(root.parent))
        if _excluded(str(py.relative_to(root)), excludes):
            continue
        linter = _lint_one(py.read_text(), rel)
        all_violations.extend(linter.violations)
        all_violations.extend(_metric_violations(linter, docs_text))
        report.metric_names.update(name for name, _ in linter.metric_sites)
    used: set[Suppression] = set()
    for v in all_violations:
        match = next((s for s in suppressions if s.matches(v)), None)
        if match is None:
            report.violations.append(v)
        else:
            used.add(match)
            report.suppressed.append((v, match))
    report.stale_suppressions = [s for s in suppressions if s not in used]
    report.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return report
