"""Single-pass AST inspection of submitted source (docs/analysis.md).

The edge pays for every admitted submission with a warm single-use sandbox,
even when the code can never run. ``inspect_source`` is the one AST pass
that prevents that: parse once, and from the same tree collect everything
the edge decides on —

- **syntax validity**, with the error rendered in the exact shape the
  in-sandbox interpreter would have printed to stderr (``File``/caret/
  ``SyntaxError`` lines), so a fail-fast response is indistinguishable in
  format from a sandbox run that died at parse;
- **imports**, truncated by the same namespace-package rules the dep
  guesser uses (``runtime/dep_guess.py`` — this module feeds the parsed
  tree straight into it, so the edge never re-parses to predict deps);
- **call sites**, resolved through import aliases to dotted names
  (``import subprocess as sp; sp.run(...)`` resolves to
  ``subprocess.run``) with "inside a loop" marked, so the policy engine
  can match call *shapes* (``os.fork`` loops), not just names;
- **absolute path literals**, for path-prefix policy rules.

The alias-resolution machinery is shared with ``analysis/asynclint.py`` —
the same inspection that gates workloads lints our own control plane.
"""

from __future__ import annotations

import ast
import traceback
from dataclasses import dataclass, field

from bee_code_interpreter_tpu.analysis import dataflow
from bee_code_interpreter_tpu.runtime import dep_guess

# The sandbox writes the submission to <tempdir>/script.py and execs it
# (runtime/executor_core.py); rendering the edge's syntax error against the
# same basename keeps the two stderr shapes aligned.
SCRIPT_FILENAME = "script.py"


@dataclass(frozen=True)
class CallSite:
    """One resolved call: ``name`` is the alias-resolved dotted target."""

    name: str
    line: int
    in_loop: bool


@dataclass
class SourceInspection:
    """Everything one parse of a submission yields. When ``syntax_error``
    is set, the collections are empty — there is no tree to walk. When
    ``analysis_error`` is set the parse itself blew a resource limit
    (RecursionError/MemoryError on a degenerate-but-maybe-valid program):
    the edge could not analyze, which is NOT the same as "the sandbox
    would refuse it" — the policy layer decides what that means."""

    syntax_error: str | None = None  # rendered stderr, in-sandbox shape
    analysis_error: str | None = None  # parse blew a limit; no claims made
    imports: set[str] = field(default_factory=set)
    calls: list[CallSite] = field(default_factory=list)
    path_literals: set[str] = field(default_factory=set)
    predicted_deps: list[str] = field(default_factory=list)
    # Dataflow layer (docs/analysis.md "Dataflow layer"): dynamic imports
    # whose target constant-folds (`x = __import__; x("socket")` →
    # {"socket": [line]}) are matched by the import policy lists exactly
    # like static imports; sites whose target does NOT fold are the
    # `dynamic_import` rule's input. ``max_loop_depth`` feeds cost
    # classification.
    dynamic_imports: dict[str, list[int]] = field(default_factory=dict)
    dynamic_import_sites: list[tuple[int, str]] = field(default_factory=list)
    max_loop_depth: int = 0

    def call_names(self) -> set[str]:
        return {c.name for c in self.calls}


def render_syntax_error(exc: SyntaxError) -> str:
    """The stderr a ``python script.py`` run of this source would have
    produced: CPython prints exactly the ``File``/source-line/caret/
    ``SyntaxError:`` block for a parse failure (no ``Traceback`` header),
    which is what ``format_exception_only`` renders for SyntaxError."""
    return "".join(traceback.format_exception_only(type(exc), exc))


def collect_aliases(tree: ast.AST) -> dict[str, str]:
    """{local name: dotted target} for every import binding in the tree —
    ``import a.b`` binds ``a``→``a``, ``import a.b as c`` binds ``c``→``a.b``,
    ``from a import b as c`` binds ``c``→``a.b``. Relative imports resolve
    to nothing useful for policy and are skipped."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    aliases[alias.asname] = alias.name
                else:
                    aliases[alias.name.split(".", 1)[0]] = alias.name.split(
                        ".", 1
                    )[0]
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            for alias in node.names:
                aliases[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}"
                )
    return aliases


def resolve_call_name(
    func: ast.expr, aliases: dict[str, str] | None = None
) -> str | None:
    """Dotted name of a call target, resolved through import aliases.
    ``None`` when the root isn't a plain name (``self.x()``, ``f()()``,
    subscripts) — those can't be matched against a module-path policy and
    must not be guessed at."""
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = node.id
    if aliases and root in aliases:
        root = aliases[root]
    parts.append(root)
    return ".".join(reversed(parts))


_COMPREHENSION_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_calls(
    tree: ast.AST, aliases: dict[str, str]
) -> tuple[list[CallSite], int, dict[int, bool]]:
    """Call sites with loop context (plus the tree's maximum loop-nesting
    depth — a cost-classification input — and a per-Call-node loop-context
    map for the dataflow resolver): a call lexically inside a For/
    While/comprehension body is ``in_loop``. Entering a nested function
    resets the loop context (the def executes in the loop; its body only
    runs when called) — a deliberate under-approximation that keeps
    ``deny`` rules free of false positives.

    Iterative on an explicit stack: ``ast.parse`` accepts expressions far
    deeper than the interpreter's recursion limit (a 2 KB ``----…x`` chain
    is a valid program), and the edge gate must never blow the stack on
    source the sandbox would happily run."""
    calls: list[CallSite] = []
    max_depth = 0
    # Every Call node's loop context, keyed by node identity — the
    # dataflow resolver reuses it so a RESOLVED call site (`m = x("os");
    # m.fork()` in a loop) keeps its in_loop flag and still matches the
    # loop-sensitive shapes (fork_in_loop).
    loop_context: dict[int, bool] = {}
    stack: list[tuple[ast.AST, int]] = [(tree, 0)]
    while stack:
        node, loop_depth = stack.pop()
        max_depth = max(max_depth, loop_depth)
        if isinstance(node, ast.Call):
            loop_context[id(node)] = loop_depth > 0
            name = resolve_call_name(node.func, aliases)
            if name is not None:
                calls.append(
                    CallSite(
                        name=name,
                        line=getattr(node, "lineno", 0),
                        in_loop=loop_depth > 0,
                    )
                )
        if isinstance(node, (ast.For, ast.AsyncFor)):
            # The iterable (and target) evaluate ONCE before iteration and
            # the else-suite ONCE after it — only the body repeats.
            stack.append((node.target, loop_depth))
            stack.append((node.iter, loop_depth))
            stack.extend((child, loop_depth) for child in node.orelse)
            stack.extend((child, loop_depth + 1) for child in node.body)
            continue
        if isinstance(node, ast.While):
            # The test re-evaluates every iteration; the else-suite runs
            # at most once.
            stack.append((node.test, loop_depth + 1))
            stack.extend((child, loop_depth) for child in node.orelse)
            stack.extend((child, loop_depth + 1) for child in node.body)
            continue
        if isinstance(node, _COMPREHENSION_NODES):
            # The OUTERMOST iterable evaluates once, eagerly, in the
            # enclosing scope; the element expression, conditions, and
            # inner generators run per element.
            for i, gen in enumerate(node.generators):
                stack.append((gen.iter, loop_depth if i == 0 else loop_depth + 1))
                stack.append((gen.target, loop_depth + 1))
                stack.extend((cond, loop_depth + 1) for cond in gen.ifs)
            if isinstance(node, ast.DictComp):
                stack.append((node.key, loop_depth + 1))
                stack.append((node.value, loop_depth + 1))
            else:
                stack.append((node.elt, loop_depth + 1))
            continue
        next_depth = 0 if isinstance(node, _FUNCTION_NODES) else loop_depth
        stack.extend(
            (child, next_depth) for child in ast.iter_child_nodes(node)
        )
    return calls, max_depth, loop_context


@dataclass
class _DynamicResolution:
    """What the dataflow pass adds on top of the syntactic walk."""

    imports: dict[str, list[int]] = field(default_factory=dict)
    sites: list[tuple[int, str]] = field(default_factory=list)
    extra_calls: list[CallSite] = field(default_factory=list)


def _resolve_dynamic(
    tree: ast.Module,
    aliases: dict[str, str],
    loop_context: dict[int, bool] | None = None,
) -> _DynamicResolution:
    """Close the easy policy evasions with the dataflow layer's
    flow-insensitive bindings (docs/analysis.md "Dataflow layer"):
    ``__import__``/``importlib.import_module`` reached through assignments,
    ``getattr(<module>, <const str>)`` chains, and calls through variables
    bound to either. Constant-foldable targets become ordinary policy
    inputs; non-constant ones become ``dynamic_import`` sites
    (warn/deny-able, docs/analysis.md).

    Cost discipline: this runs ON the event loop inside the <1 ms gate
    budget, so (a) sources without any trigger identifier skip the pass
    entirely — no binding can reach ``__import__``/``getattr`` without
    spelling one of the trigger tokens somewhere — and (b) resolution is
    the O(statements) union-over-defs mode, not the CFG fixpoint (see
    ``dataflow.ScopeBindings``)."""
    out = _DynamicResolution()
    if not dataflow.has_dynamic_triggers(tree):
        return out
    modules = dataflow.module_bindings(tree)
    module_names = set(modules.values()) | {"builtins"}
    seen_sites: set[int] = set()
    for scope in dataflow.iter_scope_bindings(tree, aliases):
        for call in scope.own_calls():
            line = getattr(call, "lineno", 0)
            syntactic = resolve_call_name(call.func, aliases)
            func_origins = scope.expr_origins(call.func)
            if not func_origins:
                continue
            if func_origins & dataflow.IMPORT_FUNCTIONS:
                folded = scope.fold_str(call.args[0]) if call.args else None
                if folded is not None:
                    out.imports.setdefault(folded, []).append(line)
                elif line not in seen_sites:
                    seen_sites.add(line)
                    spelled = sorted(func_origins & dataflow.IMPORT_FUNCTIONS)[0]
                    out.sites.append(
                        (line, f"{spelled} with a non-constant module name")
                    )
            if "getattr" in func_origins and len(call.args) >= 2:
                receiver_origins = scope.expr_origins(call.args[0])
                on_module = receiver_origins & module_names
                if on_module and scope.fold_str(call.args[1]) is None:
                    if line not in seen_sites:
                        seen_sites.add(line)
                        out.sites.append(
                            (
                                line,
                                f"getattr on module {sorted(on_module)[0]} "
                                "with a non-constant attribute name",
                            )
                        )
            # A call whose target RESOLVES to a dotted name the syntactic
            # walk could not see (`g = getattr(os, "system"); g(...)`,
            # `m = __import__("subprocess"); m.run(...)`) joins the
            # ordinary call-policy inputs.
            for origin in func_origins:
                if (
                    origin != syntactic
                    and "." in origin
                    and origin not in dataflow.IMPORT_FUNCTIONS
                ):
                    out.extra_calls.append(
                        CallSite(
                            name=origin,
                            line=line,
                            in_loop=(loop_context or {}).get(
                                id(call), False
                            ),
                        )
                    )
    return out


def _path_literals(tree: ast.AST) -> set[str]:
    """Absolute-path-looking string constants (policy path rules key on
    prefixes, so only rooted literals matter). Multi-line strings and
    anything space-separated are prose, not paths."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("/")
            and len(node.value) > 1
            and len(node.value) <= 256
            and not any(ch.isspace() for ch in node.value)
        ):
            out.add(node.value)
    return out


def inspect_source(source_code: str) -> SourceInspection:
    """ONE parse of a submission; everything the edge decides on comes off
    the same tree. Syntax errors short-circuit with the rendered stderr."""
    # A NUL byte makes the source unanalyzable. ``ast.parse`` on a string
    # raises ValueError, and the sandbox's FILE tokenizer treats NUL
    # line-dependently (verified on this image's 3.10: a NUL drops only
    # the remainder of its own line — LATER lines still execute, while a
    # NUL mid-statement is a SyntaxError), so any edge truncation would
    # misdescribe what actually runs: 'print(1)\n\x00\nimport socket'
    # would pass a deny-imports gate yet run the denied import. The edge
    # makes NO claim — fail-closed under a declared policy, and
    # predicted_deps=None keeps the in-pod scan (which reads the real
    # file) authoritative.
    if "\x00" in source_code:
        return SourceInspection(
            analysis_error=(
                "source contains a NUL byte; the sandbox tokenizer's "
                "handling is line-dependent and cannot be mirrored at "
                "the edge"
            )
        )
    try:
        tree = ast.parse(source_code, filename=SCRIPT_FILENAME)
    except SyntaxError as e:
        return SourceInspection(syntax_error=render_syntax_error(e))
    except (RecursionError, MemoryError, ValueError) as e:
        # Degenerate-but-parseable-in-C programs (100k-deep unary chains)
        # can blow ast.parse's Python-object construction where the
        # sandbox's compile() might survive. The edge makes NO claim here
        # — never a 500; the policy layer decides refuse-vs-proceed.
        return SourceInspection(analysis_error=repr(e))
    imports = dep_guess.guessed_imports_from_tree(tree)
    aliases = collect_aliases(tree)
    calls, max_loop_depth, loop_context = _walk_calls(tree, aliases)
    try:
        dynamic = _resolve_dynamic(tree, aliases, loop_context)
    except (RecursionError, MemoryError) as e:
        # The dataflow pass recurses on statement nesting; a degenerate
        # program can exhaust it where the flat walks above survived. Same
        # contract as a parse-limit blowup: the edge makes NO claim
        # (fail-closed under a declared policy), never a 500.
        return SourceInspection(analysis_error=repr(e))
    return SourceInspection(
        imports=imports,
        calls=calls + dynamic.extra_calls,
        path_literals=_path_literals(tree),
        predicted_deps=dep_guess.dependencies_for_imports(imports),
        dynamic_imports=dynamic.imports,
        dynamic_import_sites=sorted(dynamic.sites),
        max_loop_depth=max_loop_depth,
    )
