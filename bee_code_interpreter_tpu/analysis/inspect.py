"""Single-pass AST inspection of submitted source (docs/analysis.md).

The edge pays for every admitted submission with a warm single-use sandbox,
even when the code can never run. ``inspect_source`` is the one AST pass
that prevents that: parse once, and from the same tree collect everything
the edge decides on —

- **syntax validity**, with the error rendered in the exact shape the
  in-sandbox interpreter would have printed to stderr (``File``/caret/
  ``SyntaxError`` lines), so a fail-fast response is indistinguishable in
  format from a sandbox run that died at parse;
- **imports**, truncated by the same namespace-package rules the dep
  guesser uses (``runtime/dep_guess.py`` — this module feeds the parsed
  tree straight into it, so the edge never re-parses to predict deps);
- **call sites**, resolved through import aliases to dotted names
  (``import subprocess as sp; sp.run(...)`` resolves to
  ``subprocess.run``) with "inside a loop" marked, so the policy engine
  can match call *shapes* (``os.fork`` loops), not just names;
- **absolute path literals**, for path-prefix policy rules.

The alias-resolution machinery is shared with ``analysis/asynclint.py`` —
the same inspection that gates workloads lints our own control plane.
"""

from __future__ import annotations

import ast
import traceback
from dataclasses import dataclass, field

from bee_code_interpreter_tpu.runtime import dep_guess

# The sandbox writes the submission to <tempdir>/script.py and execs it
# (runtime/executor_core.py); rendering the edge's syntax error against the
# same basename keeps the two stderr shapes aligned.
SCRIPT_FILENAME = "script.py"


@dataclass(frozen=True)
class CallSite:
    """One resolved call: ``name`` is the alias-resolved dotted target."""

    name: str
    line: int
    in_loop: bool


@dataclass
class SourceInspection:
    """Everything one parse of a submission yields. When ``syntax_error``
    is set, the collections are empty — there is no tree to walk. When
    ``analysis_error`` is set the parse itself blew a resource limit
    (RecursionError/MemoryError on a degenerate-but-maybe-valid program):
    the edge could not analyze, which is NOT the same as "the sandbox
    would refuse it" — the policy layer decides what that means."""

    syntax_error: str | None = None  # rendered stderr, in-sandbox shape
    analysis_error: str | None = None  # parse blew a limit; no claims made
    imports: set[str] = field(default_factory=set)
    calls: list[CallSite] = field(default_factory=list)
    path_literals: set[str] = field(default_factory=set)
    predicted_deps: list[str] = field(default_factory=list)

    def call_names(self) -> set[str]:
        return {c.name for c in self.calls}


def render_syntax_error(exc: SyntaxError) -> str:
    """The stderr a ``python script.py`` run of this source would have
    produced: CPython prints exactly the ``File``/source-line/caret/
    ``SyntaxError:`` block for a parse failure (no ``Traceback`` header),
    which is what ``format_exception_only`` renders for SyntaxError."""
    return "".join(traceback.format_exception_only(type(exc), exc))


def collect_aliases(tree: ast.AST) -> dict[str, str]:
    """{local name: dotted target} for every import binding in the tree —
    ``import a.b`` binds ``a``→``a``, ``import a.b as c`` binds ``c``→``a.b``,
    ``from a import b as c`` binds ``c``→``a.b``. Relative imports resolve
    to nothing useful for policy and are skipped."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    aliases[alias.asname] = alias.name
                else:
                    aliases[alias.name.split(".", 1)[0]] = alias.name.split(
                        ".", 1
                    )[0]
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            for alias in node.names:
                aliases[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}"
                )
    return aliases


def resolve_call_name(
    func: ast.expr, aliases: dict[str, str] | None = None
) -> str | None:
    """Dotted name of a call target, resolved through import aliases.
    ``None`` when the root isn't a plain name (``self.x()``, ``f()()``,
    subscripts) — those can't be matched against a module-path policy and
    must not be guessed at."""
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = node.id
    if aliases and root in aliases:
        root = aliases[root]
    parts.append(root)
    return ".".join(reversed(parts))


_COMPREHENSION_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_calls(tree: ast.AST, aliases: dict[str, str]) -> list[CallSite]:
    """Call sites with loop context: a call lexically inside a For/While/
    comprehension body is ``in_loop``. Entering a nested function resets the
    loop context (the def executes in the loop; its body only runs when
    called) — a deliberate under-approximation that keeps ``deny`` rules
    free of false positives.

    Iterative on an explicit stack: ``ast.parse`` accepts expressions far
    deeper than the interpreter's recursion limit (a 2 KB ``----…x`` chain
    is a valid program), and the edge gate must never blow the stack on
    source the sandbox would happily run."""
    calls: list[CallSite] = []
    stack: list[tuple[ast.AST, int]] = [(tree, 0)]
    while stack:
        node, loop_depth = stack.pop()
        if isinstance(node, ast.Call):
            name = resolve_call_name(node.func, aliases)
            if name is not None:
                calls.append(
                    CallSite(
                        name=name,
                        line=getattr(node, "lineno", 0),
                        in_loop=loop_depth > 0,
                    )
                )
        if isinstance(node, (ast.For, ast.AsyncFor)):
            # The iterable (and target) evaluate ONCE before iteration and
            # the else-suite ONCE after it — only the body repeats.
            stack.append((node.target, loop_depth))
            stack.append((node.iter, loop_depth))
            stack.extend((child, loop_depth) for child in node.orelse)
            stack.extend((child, loop_depth + 1) for child in node.body)
            continue
        if isinstance(node, ast.While):
            # The test re-evaluates every iteration; the else-suite runs
            # at most once.
            stack.append((node.test, loop_depth + 1))
            stack.extend((child, loop_depth) for child in node.orelse)
            stack.extend((child, loop_depth + 1) for child in node.body)
            continue
        if isinstance(node, _COMPREHENSION_NODES):
            # The OUTERMOST iterable evaluates once, eagerly, in the
            # enclosing scope; the element expression, conditions, and
            # inner generators run per element.
            for i, gen in enumerate(node.generators):
                stack.append((gen.iter, loop_depth if i == 0 else loop_depth + 1))
                stack.append((gen.target, loop_depth + 1))
                stack.extend((cond, loop_depth + 1) for cond in gen.ifs)
            if isinstance(node, ast.DictComp):
                stack.append((node.key, loop_depth + 1))
                stack.append((node.value, loop_depth + 1))
            else:
                stack.append((node.elt, loop_depth + 1))
            continue
        next_depth = 0 if isinstance(node, _FUNCTION_NODES) else loop_depth
        stack.extend(
            (child, next_depth) for child in ast.iter_child_nodes(node)
        )
    return calls


def _path_literals(tree: ast.AST) -> set[str]:
    """Absolute-path-looking string constants (policy path rules key on
    prefixes, so only rooted literals matter). Multi-line strings and
    anything space-separated are prose, not paths."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("/")
            and len(node.value) > 1
            and len(node.value) <= 256
            and not any(ch.isspace() for ch in node.value)
        ):
            out.add(node.value)
    return out


def inspect_source(source_code: str) -> SourceInspection:
    """ONE parse of a submission; everything the edge decides on comes off
    the same tree. Syntax errors short-circuit with the rendered stderr."""
    # A NUL byte makes the source unanalyzable. ``ast.parse`` on a string
    # raises ValueError, and the sandbox's FILE tokenizer treats NUL
    # line-dependently (verified on this image's 3.10: a NUL drops only
    # the remainder of its own line — LATER lines still execute, while a
    # NUL mid-statement is a SyntaxError), so any edge truncation would
    # misdescribe what actually runs: 'print(1)\n\x00\nimport socket'
    # would pass a deny-imports gate yet run the denied import. The edge
    # makes NO claim — fail-closed under a declared policy, and
    # predicted_deps=None keeps the in-pod scan (which reads the real
    # file) authoritative.
    if "\x00" in source_code:
        return SourceInspection(
            analysis_error=(
                "source contains a NUL byte; the sandbox tokenizer's "
                "handling is line-dependent and cannot be mirrored at "
                "the edge"
            )
        )
    try:
        tree = ast.parse(source_code, filename=SCRIPT_FILENAME)
    except SyntaxError as e:
        return SourceInspection(syntax_error=render_syntax_error(e))
    except (RecursionError, MemoryError, ValueError) as e:
        # Degenerate-but-parseable-in-C programs (100k-deep unary chains)
        # can blow ast.parse's Python-object construction where the
        # sandbox's compile() might survive. The edge makes NO claim here
        # — never a 500; the policy layer decides refuse-vs-proceed.
        return SourceInspection(analysis_error=repr(e))
    imports = dep_guess.guessed_imports_from_tree(tree)
    return SourceInspection(
        imports=imports,
        calls=_walk_calls(tree, collect_aliases(tree)),
        path_literals=_path_literals(tree),
        predicted_deps=dep_guess.dependencies_for_imports(imports),
    )
