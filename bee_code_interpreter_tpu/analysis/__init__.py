"""Edge-side static analysis: pre-flight code gate, policy engine, and the
asyncio-control-plane self-lint.

Two halves (docs/analysis.md):

- **Workload analysis** — one AST pass per submission at both API edges
  (``inspect.py``), evaluated by a config-declared :class:`PolicyEngine`
  (``policy.py``): syntax errors fail fast as ordinary exit_code=1
  responses without consuming a warm sandbox, ``deny`` policy hits reject
  as client faults, and the same pass pre-resolves PyPI deps so the pod
  can skip its own scan (``context.py`` carries the prediction to the
  data plane).
- **Self-analysis** — ``asynclint.py`` turns the same machinery on our own
  ``api``/``services``/``resilience``/``observability`` packages,
  enforcing repo asyncio invariants in tier-1.

Layered like ``resilience/`` and ``observability/``: primitives here,
wiring at the edges (api/, services/, runtime/).
"""

from bee_code_interpreter_tpu.analysis.asynclint import (
    LintReport,
    Suppression,
    Violation,
    lint_paths,
    lint_source,
)
from bee_code_interpreter_tpu.analysis.context import (
    predicted_deps,
    stash_predicted_deps,
)
from bee_code_interpreter_tpu.analysis.inspect import (
    CallSite,
    SourceInspection,
    inspect_source,
    render_syntax_error,
)
from bee_code_interpreter_tpu.analysis.policy import (
    SHAPES,
    AnalysisVerdict,
    Finding,
    PolicyEngine,
    WorkloadAnalyzer,
    split_patterns,
)

__all__ = [
    "AnalysisVerdict",
    "CallSite",
    "Finding",
    "LintReport",
    "PolicyEngine",
    "SHAPES",
    "SourceInspection",
    "Suppression",
    "Violation",
    "WorkloadAnalyzer",
    "inspect_source",
    "lint_paths",
    "lint_source",
    "predicted_deps",
    "render_syntax_error",
    "split_patterns",
    "stash_predicted_deps",
]
