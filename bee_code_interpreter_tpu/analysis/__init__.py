"""Edge-side static analysis: pre-flight code gate, policy engine, and the
asyncio-control-plane self-lint.

Two halves (docs/analysis.md):

- **Workload analysis** — one AST pass per submission at both API edges
  (``inspect.py``), evaluated by a config-declared :class:`PolicyEngine`
  (``policy.py``): syntax errors fail fast as ordinary exit_code=1
  responses without consuming a warm sandbox, ``deny`` policy hits reject
  as client faults, and the same pass pre-resolves PyPI deps so the pod
  can skip its own scan (``context.py`` carries the prediction to the
  data plane).
- **Self-analysis** — ``asynclint.py`` turns the same machinery on our own
  control-plane packages (the scope is DERIVED from the package tree so a
  new subsystem is linted by default), ``concurrencylint.py`` adds the
  await-aware rules (RMW across await, lock leaks, self-deadlocks,
  unawaited teardown, cross-thread loop touches) on top of the
  ``dataflow.py`` CFG engine, and ``jaxlint.py`` owns the OTHER half of
  the tree — the accelerator stack (``models/``, ``parallel/``, ``ops/``,
  ``runtime/shim/``) the asyncio lints exclude — with TPU-throughput
  rules (host-sync-in-hot-loop, retrace hazards, missing donation,
  traced Python branches, unbound collective axes). All three enforced
  in tier-1.

Layered like ``resilience/`` and ``observability/``: primitives here,
wiring at the edges (api/, services/, runtime/).
"""

from bee_code_interpreter_tpu.analysis.asynclint import (
    LintReport,
    Suppression,
    Violation,
    default_packages,
    lint_paths,
    lint_source,
)
from bee_code_interpreter_tpu.analysis.concurrencylint import (
    ConcurrencyReport,
    lint_concurrency_paths,
    lint_concurrency_source,
)
from bee_code_interpreter_tpu.analysis.dataflow import (
    EXIT,
    FunctionFlow,
    iter_scopes,
)
from bee_code_interpreter_tpu.analysis.contractlint import (
    ContractReport,
    extract_surface,
    lint_contract_paths,
    surface_json,
    surface_section,
)
from bee_code_interpreter_tpu.analysis.jaxlint import (
    ACCELERATOR_SCOPE,
    JaxLintReport,
    lint_jax_paths,
    lint_jax_source,
)
from bee_code_interpreter_tpu.analysis.sarif import sarif_log, tool_run
from bee_code_interpreter_tpu.analysis.context import (
    predicted_deps,
    stash_predicted_deps,
)
from bee_code_interpreter_tpu.analysis.inspect import (
    CallSite,
    SourceInspection,
    inspect_source,
    render_syntax_error,
)
from bee_code_interpreter_tpu.analysis.policy import (
    COST_CLASSES,
    HEAVY_COST_CLASSES,
    SHAPES,
    AnalysisVerdict,
    Finding,
    PolicyEngine,
    WorkloadAnalyzer,
    classify_cost,
    split_patterns,
)

__all__ = [
    "ACCELERATOR_SCOPE",
    "AnalysisVerdict",
    "COST_CLASSES",
    "CallSite",
    "ConcurrencyReport",
    "ContractReport",
    "EXIT",
    "Finding",
    "FunctionFlow",
    "HEAVY_COST_CLASSES",
    "JaxLintReport",
    "LintReport",
    "PolicyEngine",
    "SHAPES",
    "SourceInspection",
    "Suppression",
    "Violation",
    "WorkloadAnalyzer",
    "classify_cost",
    "default_packages",
    "extract_surface",
    "inspect_source",
    "iter_scopes",
    "lint_concurrency_paths",
    "lint_concurrency_source",
    "lint_jax_paths",
    "lint_jax_source",
    "lint_paths",
    "lint_source",
    "predicted_deps",
    "lint_contract_paths",
    "render_syntax_error",
    "sarif_log",
    "split_patterns",
    "stash_predicted_deps",
    "surface_json",
    "surface_section",
    "tool_run",
]
