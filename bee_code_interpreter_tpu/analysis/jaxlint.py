"""JAX-aware static lint for the accelerator stack (docs/analysis.md
"Accelerator lint").

asynclint/concurrencylint hold the asyncio control plane; their exclude
lists (``models/``, ``parallel/``, ``ops/``, ``runtime/shim/``) are
exactly the trees THIS linter owns — the two scopes partition the package
so no module ships unlinted by omission. The invariants here are the ones
that silently destroy TPU throughput instead of correctness: a decode
loop that round-trips the device per token, a ``jax.jit`` rebuilt per
call, a step function that copies its whole state pytree because nothing
was donated, a Python branch that forks the trace, a collective whose
axis no mesh ever binds. vLLM-class engines hold these by review; here
they are a tier-1 lint (tests/test_jaxlint.py) with the same explicit
suppression contract as the other self-lints — every sanctioned site
carries a justification, and a stale suppression FAILS.

Rules:

- ``host-sync-in-hot-loop``   a device→host transfer — ``jax.device_get``,
  ``.block_until_ready()``, ``.item()`` / ``np.asarray`` / ``np.array`` /
  ``float()`` / ``int()`` applied to a value the dataflow layer tracks to
  a jitted callable or a ``jnp``/``lax`` producer — inside a loop, or
  anywhere in a method reachable from a class's ``step()`` (the batcher
  hot path: ``step`` itself runs in the serving loop, so everything it
  calls is per-token even without a lexical loop).
- ``jit-in-loop``             ``jax.jit`` / ``jax.pmap`` constructed
  inside a loop body — a fresh wrapper per iteration retraces every time.
- ``retrace-hazard``          ``jax.jit(f)(...)`` called immediately (a
  fresh cache per call), a jit built AND called inside the same function
  body (rebuilt per invocation), or ``static_argnums``/``static_argnames``
  that are not compile-time constants.
- ``missing-donation``        a jitted state-in/state-out function — its
  return includes one of its own parameters (the ``cache``/``params``
  shape) — jitted without ``donate_argnums``/``donate_argnames``: every
  call pays a full copy of the state it threads. ``models/mnist.py``'s
  ``make_train_step`` is the sanctioned spelling.
- ``traced-python-branch``    Python ``if``/``while`` on a TRACED
  parameter's value inside a function that is jitted in the corpus —
  branch-by-value forks the trace (ConcretizationTypeError on abstract
  values, or a silent retrace per branch taken). Shape/dtype/ndim/size
  attributes, ``len()``, and ``is None`` tests are static and sanctioned.
- ``collective-axis-mismatch`` ``lax.psum``/``ppermute``/``all_to_all``/
  ``axis_index``/… with a literal ``axis_name`` that no ``shard_map``/
  ``Mesh``/``pmap``/``PartitionSpec`` in the file binds and no enclosing
  parameter supplies — the call can only ever raise "unbound axis name"
  at trace time, on hardware, far from the edit that broke it.

Approximation stance matches the engine underneath (dataflow.py): paths
over-approximate, values under-approximate — a finding is a real shape in
the code, and the suppression list is where a real-but-sanctioned shape
gets its justification recorded.
"""

from __future__ import annotations

import ast
import dataclasses
from dataclasses import dataclass, field
from pathlib import Path

from bee_code_interpreter_tpu.analysis.asynclint import (
    DEFAULT_EXCLUDES,
    PACKAGE_ROOT,
    Suppression,
    Violation,
)
from bee_code_interpreter_tpu.analysis.inspect import (
    collect_aliases,
    resolve_call_name,
)

#: The derived accelerator scope: exactly the subtrees the asyncio lints
#: exclude (asynclint.DEFAULT_EXCLUDES), so the two lint families
#: partition the package tree — a new module under models/ or parallel/
#: is jaxlint-scoped the moment it exists, and a new top-level package
#: lands in asynclint's derived scope instead.
ACCELERATOR_SCOPE: tuple[str, ...] = DEFAULT_EXCLUDES

_JIT_WRAPPERS = frozenset({"jax.jit", "jax.pmap"})

#: Call roots whose results live on device. jnp/lax/random cover the
#: producers; jax.device_put is an explicit placement; jax.jit results
#: are tracked separately (per-scope jitted-callable sets).
_DEVICE_PRODUCER_PREFIXES = (
    "jax.numpy.",
    "jax.lax.",
    "jax.random.",
    "jax.nn.",
)
_DEVICE_PRODUCERS = frozenset({"jax.device_put", "jax.jit", "jax.pmap"})

#: Host-materialization sinks by dotted call name. float/int are listed
#: builtins; np.asarray/np.array resolve through aliases to numpy.*.
_SYNC_CALLS = frozenset(
    {"numpy.asarray", "numpy.array", "float", "int", "jax.device_get"}
)

_COLLECTIVES: dict[str, int] = {
    # dotted name -> positional index of axis_name when not a kwarg
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.axis_index": 0,
    "jax.lax.axis_size": 0,
}

#: Identifier tokens whose absence proves a file cannot contain anything
#: this linter flags — the same cheap pre-scan discipline as
#: ``dataflow.has_dynamic_triggers`` (a jax-free file costs one token
#: scan, no CFG, no class graph).
JAX_TRIGGER_NAMES = frozenset(
    {"jax", "jnp", "lax", "shard_map", "pmap", "jit", "block_until_ready"}
)


# The shipped suppression budget — same contract as the other self-lints:
# every entry names WHY the flagged shape is sound, and an entry that no
# longer matches any violation fails tests/test_jaxlint.py.
SUPPRESSIONS: tuple[Suppression, ...] = (
    Suppression(
        path="models/serving.py",
        rule="host-sync-in-hot-loop",
        reason=(
            "the batcher's step-path transfers are the DESIGNED device/"
            "host split (module docstring): ONE bounded pull per compiled "
            "step — greedy tokens reduce on device to [B] int32 before "
            "crossing, the full logits rows cross only when some active "
            "row samples/records logprobs/is steered, and the speculative "
            "round pulls [B,gamma+1] predictions once per gamma+1 tokens "
            "— plus per-WINDOW (page-aligned, never per-token) pulls on "
            "the admission prefill paths; host-side numpy sampling is the "
            "per-request heterogeneity the fixed-shape device program "
            "deliberately excludes (tests/test_serving.py pins the split)"
        ),
    ),
)


@dataclass
class JaxLintReport:
    violations: list[Violation] = field(default_factory=list)
    suppressed: list[tuple[Violation, Suppression]] = field(default_factory=list)
    stale_suppressions: list[Suppression] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def clean(self) -> bool:
        return not self.violations and not self.stale_suppressions

    def summary(self) -> str:
        lines = [str(v) for v in self.violations]
        lines += [
            f"stale suppression ({s.path} [{s.rule}]): no matching violation"
            for s in self.stale_suppressions
        ]
        return "\n".join(lines) or "clean"


def has_jax_triggers(tree: ast.AST) -> bool:
    """Cheap pre-scan: can this file possibly contain a jax shape? Any
    import of jax/its aliases, or a bare trigger identifier."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in JAX_TRIGGER_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in (
            "block_until_ready",
            "device_get",
        ):
            return True
        if isinstance(node, ast.Import) and any(
            alias.name.split(".", 1)[0] == "jax" for alias in node.names
        ):
            return True
        if isinstance(node, ast.ImportFrom) and (node.module or "").split(
            ".", 1
        )[0] == "jax":
            return True
    return False


# --------------------------------------------------------------------------
# shared facts about one file
# --------------------------------------------------------------------------


@dataclass
class _FunctionFacts:
    """What the donation/traced-branch rules need to know about one
    function definition."""

    node: ast.AST
    params: tuple[str, ...]
    returned_params: frozenset[str]  # params appearing bare in a return


def _function_params(func: ast.AST) -> tuple[str, ...]:
    args = func.args
    names = [a.arg for a in (*args.posonlyargs, *args.args)]
    return tuple(n for n in names if n != "self")


def _returned_params(func: ast.AST, params: tuple[str, ...]) -> frozenset[str]:
    """Params whose NAME appears as a bare element of some return value —
    the state-in/state-out shape (``return logits, cache``). Rebinding the
    name first (``cache = update(cache)``) still counts: the function
    threads that state through, which is exactly when donation pays."""
    pset = set(params)
    out: set[str] = set()

    def elements(expr: ast.expr):
        if isinstance(expr, ast.Tuple):
            for e in expr.elts:
                yield from elements(e)
        else:
            yield expr

    for node in ast.walk(func):
        if isinstance(node, ast.Return) and node.value is not None:
            for e in elements(node.value):
                if isinstance(e, ast.Name) and e.id in pset:
                    out.add(e.id)
    return frozenset(out)


def _collect_functions(tree: ast.AST) -> dict[str, _FunctionFacts]:
    """Every FunctionDef in the file keyed by bare name (innermost wins on
    collision — good enough for the factory pattern where the nested def
    is the jit target)."""
    out: dict[str, _FunctionFacts] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = _function_params(node)
            out[node.name] = _FunctionFacts(
                node=node,
                params=params,
                returned_params=_returned_params(node, params),
            )
    return out


def _const_str_tuple(expr: ast.expr) -> bool:
    """Is this expression a compile-time constant suitable for
    static_argnums/static_argnames? (int/str constant, or a tuple/list of
    them)."""
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, (int, str))
    if isinstance(expr, (ast.Tuple, ast.List)):
        return all(
            isinstance(e, ast.Constant) and isinstance(e.value, (int, str))
            for e in expr.elts
        )
    return False


@dataclass
class _JitSite:
    """One ``jax.jit(...)`` call, decomposed."""

    call: ast.Call
    target_name: str | None  # bare name of the jitted function, if a Name
    partial_kwargs: frozenset[str]  # kwargs bound via functools.partial
    static_names: frozenset[str]
    static_nums: frozenset[int]  # positional static_argnums indices
    has_donation: bool
    static_args_constant: bool


def _decompose_jit(call: ast.Call, aliases: dict[str, str]) -> _JitSite | None:
    name = resolve_call_name(call.func, aliases)
    if name not in _JIT_WRAPPERS:
        return None
    target: ast.expr | None = call.args[0] if call.args else None
    partial_kwargs: set[str] = set()
    # unwrap functools.partial(f, **bound): bound kwargs become static
    # Python values at trace time
    if isinstance(target, ast.Call) and resolve_call_name(
        target.func, aliases
    ) in ("functools.partial", "partial"):
        partial_kwargs = {kw.arg for kw in target.keywords if kw.arg}
        target = target.args[0] if target.args else None
    target_name = target.id if isinstance(target, ast.Name) else None
    static_names: set[str] = set()
    static_nums: set[int] = set()
    has_donation = False
    static_constant = True
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            has_donation = True
        elif kw.arg in ("static_argnums", "static_argnames"):
            if not _const_str_tuple(kw.value):
                static_constant = False
                continue
            consts = (
                [kw.value]
                if isinstance(kw.value, ast.Constant)
                else list(kw.value.elts)
            )
            for e in consts:
                if isinstance(e.value, str):
                    static_names.add(e.value)
                elif isinstance(e.value, int):
                    static_nums.add(e.value)
    return _JitSite(
        call=call,
        target_name=target_name,
        partial_kwargs=frozenset(partial_kwargs),
        static_names=frozenset(static_names),
        static_nums=frozenset(static_nums),
        has_donation=has_donation,
        static_args_constant=static_constant,
    )


# --------------------------------------------------------------------------
# loop / hot-path context
# --------------------------------------------------------------------------

_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)
_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNCTIONS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_scope(node: ast.AST):
    """This scope's own nodes, NOT descending into nested defs/lambdas —
    ``ast.walk`` with a ``continue`` on FunctionDef still yields the
    skipped function's descendants, which is exactly the bug class this
    helper exists to avoid (same shape as concurrencylint's
    ``_walk_excluding_nested``)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _FUNCTIONS):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _call_contexts(
    tree: ast.AST,
) -> dict[int, tuple[bool, tuple[ast.AST, ...]]]:
    """id(Call) -> (lexically inside a loop?, enclosing-function chain,
    outermost first). Loop context resets at function boundaries (a def
    in a loop executes its body only when called), mirroring
    inspect._walk_calls; the comprehension's outermost iterable evaluates
    once and stays out. The full chain (not just the nearest function)
    matters because closures over an outer function's ``axis_name``
    parameter are THE idiom shard_map bodies use."""
    out: dict[int, tuple[bool, tuple[ast.AST, ...]]] = {}
    Chain = tuple[ast.AST, ...]
    stack: list[tuple[ast.AST, bool, Chain]] = [(tree, False, ())]
    while stack:
        node, in_loop, funcs = stack.pop()
        if isinstance(node, ast.Call):
            out[id(node)] = (in_loop, funcs)
        if isinstance(node, _FUNCTIONS):
            inner = (*funcs, node)
            # a def in a loop executes its body only when called; a
            # lambda is almost always invoked where it is written (sort
            # keys, callbacks), so it INHERITS the loop context
            body_loop = in_loop if isinstance(node, ast.Lambda) else False
            for child in ast.iter_child_nodes(node):
                stack.append((child, body_loop, inner))
            continue
        if isinstance(node, _LOOP_NODES):
            body_loop = True
            if isinstance(node, (ast.For, ast.AsyncFor)):
                stack.append((node.iter, in_loop, funcs))
                stack.append((node.target, in_loop, funcs))
                for child in node.orelse:
                    stack.append((child, in_loop, funcs))
                for child in node.body:
                    stack.append((child, body_loop, funcs))
            else:  # While: the test re-evaluates per iteration
                stack.append((node.test, body_loop, funcs))
                for child in node.orelse:
                    stack.append((child, in_loop, funcs))
                for child in node.body:
                    stack.append((child, body_loop, funcs))
            continue
        if isinstance(node, _COMPREHENSIONS):
            for i, gen in enumerate(node.generators):
                stack.append((gen.iter, in_loop if i == 0 else True, funcs))
                for cond in gen.ifs:
                    stack.append((cond, True, funcs))
            if isinstance(node, ast.DictComp):
                stack.append((node.key, True, funcs))
                stack.append((node.value, True, funcs))
            else:
                stack.append((node.elt, True, funcs))
            continue
        for child in ast.iter_child_nodes(node):
            stack.append((child, in_loop, funcs))
    return out


#: Method names that seed a class's hot path: ``step`` is called per
#: decode step by every serving loop, so everything it reaches is
#: per-token work even without a lexical loop around the call site.
HOT_SEEDS = frozenset({"step"})


def _hot_methods(cls: ast.ClassDef) -> dict[str, ast.AST]:
    """Methods reachable from the class's HOT_SEEDS via ``self.m(...)``
    calls — the intra-class call graph BFS."""
    methods: dict[str, ast.AST] = {
        m.name: m
        for m in cls.body
        if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    edges: dict[str, set[str]] = {}
    for name, func in methods.items():
        callees: set[str] = set()
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr in methods
            ):
                callees.add(node.func.attr)
        edges[name] = callees
    hot: set[str] = set()
    frontier = [m for m in methods if m in HOT_SEEDS]
    while frontier:
        name = frontier.pop()
        if name in hot:
            continue
        hot.add(name)
        frontier.extend(edges.get(name, ()))
    return {name: methods[name] for name in hot}


# --------------------------------------------------------------------------
# device-value tracking (per function scope, flow-insensitive)
# --------------------------------------------------------------------------


def _class_jit_attrs(cls: ast.ClassDef, aliases: dict[str, str]) -> set[str]:
    """Attribute names any method binds to a jit/pmap result
    (``self._decode = jax.jit(...)``) — callable device programs.
    ``self.X = self.Y`` aliases propagate to a fixpoint (the
    ``self._verify = self._window`` idiom: one compiled program, two
    roles)."""
    out: set[str] = set()
    attr_aliases: list[tuple[str, str]] = []  # (target attr, source attr)
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            self_targets = [
                t.attr
                for t in node.targets
                if isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ]
            if not self_targets:
                continue
            if isinstance(node.value, ast.Call) and (
                resolve_call_name(node.value.func, aliases) in _JIT_WRAPPERS
            ):
                out.update(self_targets)
            elif (
                isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"
            ):
                attr_aliases.extend(
                    (t, node.value.attr) for t in self_targets
                )
    changed = True
    while changed:
        changed = False
        for target, source in attr_aliases:
            if source in out and target not in out:
                out.add(target)
                changed = True
    return out


def _is_device_call(
    call: ast.Call,
    aliases: dict[str, str],
    jit_attrs: set[str],
    jitted_names: set[str],
) -> bool:
    """Does this call produce a device value? jnp/lax/random producers,
    jax.device_put, calls THROUGH a jitted attribute/name, and immediate
    ``jax.jit(f)(...)`` invocations."""
    name = resolve_call_name(call.func, aliases)
    if name is not None:
        if name in _DEVICE_PRODUCERS or name.startswith(
            _DEVICE_PRODUCER_PREFIXES
        ):
            return True
        root = name.split(".", 1)[0]
        if root in jitted_names and "." not in name:
            return True
    func = call.func
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "self"
        and func.attr in jit_attrs
    ):
        return True
    if isinstance(func, ast.Call):
        inner = resolve_call_name(func.func, aliases)
        if inner in _JIT_WRAPPERS:
            return True
    return False


def _device_names_in_scope(
    func: ast.AST,
    aliases: dict[str, str],
    jit_attrs: set[str],
    jitted_names: set[str],
) -> set[str]:
    """Names bound (incl. tuple unpacking) from a device-producing call in
    this function's own statements — the alias set the sink checks test.
    Flow-insensitive union over definitions: over-approximating, the safe
    direction for a hint-grade rule with a suppression ledger."""
    out: set[str] = set()

    def bind_targets(target: ast.expr) -> None:
        if isinstance(target, ast.Name):
            out.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                bind_targets(e)
        elif isinstance(target, ast.Starred):
            bind_targets(target.value)

    # own statements only: a nested def's bindings are ITS scope's names,
    # and letting them leak out would mark same-named host locals here
    for node in _walk_scope(func):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_device_call(node.value, aliases, jit_attrs, jitted_names):
                for t in node.targets:
                    bind_targets(t)
    return out


def _expr_is_deviceish(
    expr: ast.expr,
    device_names: set[str],
    aliases: dict[str, str],
    jit_attrs: set[str],
    jitted_names: set[str],
) -> bool:
    """Is this expression rooted in a tracked device value? A bare name in
    the device set, a subscript/attribute/method chain over one
    (``logits[0, i]``, ``logits[i].sum()``), or directly a
    device-producing call."""
    node = expr
    while True:
        if isinstance(node, (ast.Subscript, ast.Attribute)):
            node = node.value
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if _is_device_call(node, aliases, jit_attrs, jitted_names):
                return True
            # a method call on a device value yields a device value
            # (.sum(), .astype(), .reshape(), ...)
            node = node.func.value
        else:
            break
    if isinstance(node, ast.Name):
        return node.id in device_names
    if isinstance(node, ast.Call):
        return _is_device_call(node, aliases, jit_attrs, jitted_names)
    return False


# --------------------------------------------------------------------------
# the per-file walk
# --------------------------------------------------------------------------


def _bound_axes(tree: ast.AST, aliases: dict[str, str]) -> set[str]:
    """Axis names SOME context in this file binds: string literals inside
    ``PartitionSpec``/``P`` calls, ``Mesh``/``make_mesh`` axis tuples,
    ``shard_map``/``pmap`` ``axis_name=`` kwargs, and the string defaults
    of parameters named ``axis_name`` (the default-parameter chain the
    ``*_sharded`` wrappers complete)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = resolve_call_name(node.func, aliases) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf in ("PartitionSpec", "P"):
                for a in node.args:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        out.add(a.value)
            if leaf in ("Mesh", "make_mesh", "create_device_mesh"):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str
                    ):
                        out.add(sub.value)
            # a collective's own axis_name kwarg is a USE, not a binding —
            # counting it would make every literal self-sanctioning
            if name in _COLLECTIVES:
                continue
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str
                        ):
                            out.add(sub.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            named = [*args.posonlyargs, *args.args, *args.kwonlyargs]
            defaults = [*args.defaults, *args.kw_defaults]
            for a, d in zip(reversed(named), reversed(defaults)):
                if (
                    a.arg == "axis_name"
                    and isinstance(d, ast.Constant)
                    and isinstance(d.value, str)
                ):
                    out.add(d.value)
    return out


def _enclosing_param_names(funcs: tuple[ast.AST, ...]) -> set[str]:
    """Parameter names visible anywhere in an enclosing-function chain —
    what a closure can legitimately read its axis name from."""
    out: set[str] = set()
    for func in funcs:
        args = getattr(func, "args", None)
        if args is not None:
            out.update(
                a.arg
                for a in (*args.posonlyargs, *args.args, *args.kwonlyargs)
            )
    return out


def _params_without_defaults(func: ast.AST) -> frozenset[str]:
    """Positional params that have NO default value — the ones a jit call
    must supply, hence the ones that arrive as tracers. A default-valued
    flag param the caller leaves alone stays a concrete Python value, so
    branching on it is fine (the ``return_kv``/``lora_bank`` idiom)."""
    args = func.args
    named = [*args.posonlyargs, *args.args]
    n_without = len(named) - len(args.defaults)
    return frozenset(a.arg for a in named[:n_without] if a.arg != "self")


_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})


def _test_uses_traced_value(test: ast.expr, traced: frozenset[str]) -> bool:
    """Does a branch test read a traced param's VALUE (vs its static
    shape/dtype metadata or identity-vs-None)?"""

    def walk(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False  # x.shape[...] and friends are static under trace
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            return False  # `x is None` tests identity of the Python object
        if isinstance(node, ast.Call):
            fname = node.func
            if isinstance(fname, ast.Name) and fname.id in ("len", "isinstance"):
                return False  # len() reads shape; isinstance reads the type
        if isinstance(node, ast.Name) and node.id in traced:
            return True
        return any(walk(child) for child in ast.iter_child_nodes(node))

    return walk(test)


class _FileLint:
    """One file's full pass: shared fact collection + every rule."""

    def __init__(
        self,
        tree: ast.Module,
        path: str,
        corpus: "_CorpusFacts | None" = None,
    ) -> None:
        self.tree = tree
        self.path = path
        self.corpus = corpus
        self.aliases = collect_aliases(tree)
        self.violations: list[Violation] = []
        self.functions = _collect_functions(tree)
        self.contexts = _call_contexts(tree)
        self.bound_axes = _bound_axes(tree, self.aliases)
        # module/local names bound to a jit result (`m = jax.jit(f)`)
        self.jitted_names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if (
                    resolve_call_name(node.value.func, self.aliases)
                    in _JIT_WRAPPERS
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.jitted_names.add(t.id)
        # class facts
        self.jit_attrs: dict[int, set[str]] = {}
        self.hot_funcs: set[int] = set()
        self.func_to_class: dict[int, ast.ClassDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self.jit_attrs[id(node)] = _class_jit_attrs(node, self.aliases)
                for m in _hot_methods(node).values():
                    self.hot_funcs.add(id(m))
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.func_to_class[id(m)] = node
        # which local function names are jitted anywhere in this file, and
        # with what static/partial-bound names — traced-branch's input
        self.jit_sites: list[_JitSite] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                site = _decompose_jit(node, self.aliases)
                if site is not None:
                    self.jit_sites.append(site)

    def _flag(self, node: ast.AST, rule: str, message: str) -> None:
        self.violations.append(
            Violation(
                path=self.path,
                line=getattr(node, "lineno", 0),
                rule=rule,
                message=message,
            )
        )

    # ------------------------------------------------------------- rules
    def run(self) -> list[Violation]:
        self._check_jit_sites()
        self._check_traced_branches()
        self._check_collectives()
        self._check_host_sync()
        self.violations.sort(key=lambda v: (v.path, v.line, v.rule))
        return self.violations

    def _check_jit_sites(self) -> None:
        # jit-in-loop + retrace-hazard (immediate call / non-constant
        # statics / built-and-called-in-same-function)
        for site in self.jit_sites:
            call = site.call
            in_loop, func = self.contexts.get(id(call), (False, None))
            if in_loop:
                self._flag(
                    call,
                    "jit-in-loop",
                    "jax.jit constructed inside a loop: every iteration "
                    "builds a fresh wrapper with an empty trace cache — "
                    "hoist the jit out of the loop",
                )
            if not site.static_args_constant:
                self._flag(
                    call,
                    "retrace-hazard",
                    "static_argnums/static_argnames is not a compile-time "
                    "constant: the static set can drift per call site and "
                    "every new static VALUE retraces",
                )
            self._check_missing_donation(site)
        # immediate invocation: jax.jit(f)(args) — the wrapper and its
        # cache die with the statement
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Call)
                and resolve_call_name(node.func.func, self.aliases)
                in _JIT_WRAPPERS
            ):
                self._flag(
                    node,
                    "retrace-hazard",
                    "jax.jit(f)(...) invoked immediately: the compiled "
                    "program is thrown away after one call — bind the "
                    "jitted callable once and reuse it",
                )
        # built AND called inside the same function body (rebuilt per
        # invocation of the enclosing function)
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            built: dict[str, int] = {}
            returned: set[str] = set()
            called: dict[str, int] = {}
            # own body only: `g = jax.jit(f); def step(x): return g(x);
            # return step` is the canonical closure factory — the nested
            # call must not read as "called per invocation of THIS fn"
            for inner in _walk_scope(node):
                if isinstance(inner, ast.Assign) and isinstance(
                    inner.value, ast.Call
                ):
                    if (
                        resolve_call_name(inner.value.func, self.aliases)
                        in _JIT_WRAPPERS
                    ):
                        for t in inner.targets:
                            if isinstance(t, ast.Name):
                                built[t.id] = inner.lineno
                elif isinstance(inner, ast.Return) and inner.value is not None:
                    # the jit OBJECT escaping (factory pattern) sanctions
                    # the build: `return g` / `return g, opt`; `return
                    # g(x)` is a CALL of it and must not count
                    elts = (
                        inner.value.elts
                        if isinstance(inner.value, ast.Tuple)
                        else [inner.value]
                    )
                    returned.update(
                        e.id for e in elts if isinstance(e, ast.Name)
                    )
                elif isinstance(inner, ast.Call) and isinstance(
                    inner.func, ast.Name
                ):
                    called.setdefault(inner.func.id, inner.lineno)
            for name, line in built.items():
                if name in called and name not in returned:
                    self._flag(
                        ast.copy_location(ast.Pass(), node),
                        "retrace-hazard",
                        f"jax.jit bound to `{name}` (line {line}) is built "
                        f"and called inside {node.name}(): each call of "
                        f"{node.name} re-jits from scratch — build once "
                        "(module level, __init__, or a returned factory)",
                    )

    def _resolve_jit_target(self, site: _JitSite) -> _FunctionFacts | None:
        if site.target_name is None:
            return None
        facts = self.functions.get(site.target_name)
        if facts is not None:
            return facts
        if self.corpus is not None:
            dotted = self.aliases.get(site.target_name)
            if dotted is not None:
                return self.corpus.functions.get(dotted)
        return None

    def _check_missing_donation(self, site: _JitSite) -> None:
        if site.has_donation:
            return
        facts = self._resolve_jit_target(site)
        if facts is None:
            return
        threaded = facts.returned_params - site.partial_kwargs - site.static_names
        if threaded:
            names = ", ".join(sorted(threaded))
            self._flag(
                site.call,
                "missing-donation",
                f"jitted function returns its own parameter(s) {names} "
                "(state-in/state-out) but the jit has no donate_argnums: "
                "every call copies the full state buffers — donate the "
                "threaded state (see models/mnist.py make_train_step)",
            )

    def _check_traced_branches(self) -> None:
        # Every function defined IN THIS FILE that some corpus jit site
        # targets (own sites resolve locally; sites in other files whose
        # target lives here arrive via corpus.foreign_sites), with its
        # traced params. Resolution is local-only so the violation is
        # reported against the file holding the branch, never the caller.
        seen: set[int] = set()
        sites = list(self.jit_sites)
        if self.corpus is not None:
            sites += self.corpus.foreign_sites.get(self.path, [])
        for site in sites:
            facts = (
                self.functions.get(site.target_name)
                if site.target_name is not None
                else None
            )
            if facts is None or id(facts.node) in seen:
                continue
            seen.add(id(facts.node))
            static_by_pos = frozenset(
                facts.params[i]
                for i in site.static_nums
                if i < len(facts.params)
            )
            traced = _params_without_defaults(facts.node) - (
                site.partial_kwargs | site.static_names | static_by_pos
            )
            if not traced:
                continue
            for node in ast.walk(facts.node):
                if isinstance(node, (ast.If, ast.While)):
                    if _test_uses_traced_value(node.test, traced):
                        kind = "while" if isinstance(node, ast.While) else "if"
                        self._flag(
                            node,
                            "traced-python-branch",
                            f"Python `{kind}` on a traced argument's value "
                            f"inside jitted `{facts.node.name}`: the branch "
                            "runs at trace time, not per element — use "
                            "jnp.where/lax.cond, or mark the argument "
                            "static",
                        )

    def _check_collectives(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = resolve_call_name(node.func, self.aliases)
            if name not in _COLLECTIVES:
                continue
            axis_expr: ast.expr | None = None
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis_expr = kw.value
            if axis_expr is None:
                idx = _COLLECTIVES[name]
                if len(node.args) > idx:
                    axis_expr = node.args[idx]
            if axis_expr is None:
                continue
            leaf = name.rsplit(".", 1)[-1]
            if isinstance(axis_expr, ast.Constant) and isinstance(
                axis_expr.value, str
            ):
                if axis_expr.value not in self.bound_axes:
                    self._flag(
                        node,
                        "collective-axis-mismatch",
                        f"lax.{leaf} over axis {axis_expr.value!r}, which "
                        "no shard_map/Mesh/PartitionSpec/pmap in this file "
                        "binds and no parameter default declares — this "
                        "can only raise 'unbound axis name' at trace time",
                    )
            elif isinstance(axis_expr, ast.Name):
                _, funcs = self.contexts.get(id(node), (False, ()))
                if axis_expr.id not in _enclosing_param_names(funcs):
                    self._flag(
                        node,
                        "collective-axis-mismatch",
                        f"lax.{leaf} axis_name `{axis_expr.id}` is neither "
                        "a parameter of the enclosing function nor a "
                        "literal a mesh context binds — the axis chain "
                        "cannot be audited",
                    )

    def _check_host_sync(self) -> None:
        # per enclosing function: the device-name set, then sink calls
        scopes: list[ast.AST] = [self.tree] + [
            n
            for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            cls = self.func_to_class.get(id(scope))
            jit_attrs = self.jit_attrs.get(id(cls), set()) if cls else set()
            device_names = _device_names_in_scope(
                scope, self.aliases, jit_attrs, self.jitted_names
            )
            hot_method = id(scope) in self.hot_funcs
            for node in ast.walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                in_loop, funcs = self.contexts.get(id(node), (False, ()))
                # attribute each call to its nearest NON-LAMBDA function:
                # a lambda body (a sort key, a callback) reads the
                # enclosing scope's names and runs in its loop context —
                # `sorted(rows, key=lambda i: float(logits[i]))` is still
                # a per-iteration sync of the enclosing function
                nearest = next(
                    (
                        f
                        for f in reversed(funcs)
                        if not isinstance(f, ast.Lambda)
                    ),
                    None,
                )
                if nearest is not scope and scope is not self.tree:
                    continue
                if scope is self.tree and nearest is not None:
                    continue
                hot = in_loop or hot_method
                if not hot:
                    continue
                sink = self._sync_sink(
                    node, device_names, jit_attrs
                )
                if sink is not None:
                    where = (
                        "inside a loop"
                        if in_loop
                        else f"on the step path (via {getattr(scope, 'name', '?')})"
                    )
                    self._flag(
                        node,
                        "host-sync-in-hot-loop",
                        f"{sink} {where}: a device→host transfer per "
                        "iteration serializes the pipeline — batch the "
                        "transfer per step, reduce on device first, or "
                        "sanction it with a justified suppression",
                    )

    def _sync_sink(
        self,
        call: ast.Call,
        device_names: set[str],
        jit_attrs: set[str],
    ) -> str | None:
        """The spelled sink name when this call host-materializes a
        tracked device value, else None."""
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr == "block_until_ready":
                return "block_until_ready()"  # only exists on jax arrays
            if (
                func.attr == "item"
                and not call.args
                and _expr_is_deviceish(
                    func.value, device_names, self.aliases, jit_attrs,
                    self.jitted_names,
                )
            ):
                return ".item()"
        name = resolve_call_name(func, self.aliases)
        if name in _SYNC_CALLS:
            if name == "jax.device_get":
                return "jax.device_get()"
            if call.args and _expr_is_deviceish(
                call.args[0], device_names, self.aliases, jit_attrs,
                self.jitted_names,
            ):
                return f"{name}()"
        return None


# --------------------------------------------------------------------------
# corpus aggregation + entry points
# --------------------------------------------------------------------------


@dataclass
class _CorpusFacts:
    """Cross-file facts: top-level function defs keyed by dotted module
    path (``bee_code_interpreter_tpu.models.transformer.forward``), and
    jit sites whose target resolves INTO another file (so that file's
    traced-branch pass sees them)."""

    functions: dict[str, _FunctionFacts] = field(default_factory=dict)
    foreign_sites: dict[str, list[_JitSite]] = field(default_factory=dict)


def _module_dotted(rel_path: str) -> str:
    return rel_path[: -len(".py")].replace("/", ".")


def accelerator_files(
    root: Path | str = PACKAGE_ROOT,
    scope: tuple[str, ...] = ACCELERATOR_SCOPE,
) -> list[Path]:
    """Every .py file under the accelerator subtrees. The scope is the
    SAME tuple asynclint excludes, so the partition cannot drift: editing
    one side's list edits the other's."""
    root = Path(root)
    out: list[Path] = []
    for entry in scope:
        base = root / entry
        if base.is_dir():
            out.extend(sorted(base.rglob("*.py")))
    return out


def lint_jax_source(source: str, path: str = "<memory>") -> list[Violation]:
    """Lint one source blob file-locally (unit-test entry point)."""
    import textwrap

    tree = ast.parse(textwrap.dedent(source), filename=path)
    if not has_jax_triggers(tree):
        return []
    return _FileLint(tree, path).run()


def lint_jax_paths(
    root: Path | str = PACKAGE_ROOT,
    scope: tuple[str, ...] = ACCELERATOR_SCOPE,
    suppressions: tuple[Suppression, ...] = SUPPRESSIONS,
) -> JaxLintReport:
    """Lint the accelerator subtrees, apply the suppression ledger, and
    report what remains — the tier-1 entry point."""
    root = Path(root)
    report = JaxLintReport()
    files = accelerator_files(root, scope)
    trees: list[tuple[ast.Module, str]] = []
    corpus = _CorpusFacts()
    for py in files:
        rel = str(py.relative_to(root.parent))
        tree = ast.parse(py.read_text(), filename=rel)
        report.files_scanned += 1
        if not has_jax_triggers(tree):
            continue
        trees.append((tree, rel))
        dotted_mod = _module_dotted(rel)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = _function_params(stmt)
                corpus.functions[f"{dotted_mod}.{stmt.name}"] = _FunctionFacts(
                    node=stmt,
                    params=params,
                    returned_params=_returned_params(stmt, params),
                )
    # pass 2: route each file's cross-file jit sites to the defining file
    # so ITS traced-branch pass runs with the real static/partial sets
    dotted_to_rel = {
        _module_dotted(str(py.relative_to(root.parent))): str(
            py.relative_to(root.parent)
        )
        for py in files
    }
    for tree, rel in trees:
        aliases = collect_aliases(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                site = _decompose_jit(node, aliases)
                if site is None or site.target_name is None:
                    continue
                dotted = aliases.get(site.target_name)
                if dotted and dotted in corpus.functions:
                    target_rel = dotted_to_rel.get(
                        dotted.rsplit(".", 1)[0]
                    )
                    if target_rel and target_rel != rel:
                        # route under the DEFINING file's bare function
                        # name: `from m import forward as fwd` must hit
                        # m's `forward`, not a nonexistent `fwd`
                        corpus.foreign_sites.setdefault(
                            target_rel, []
                        ).append(
                            dataclasses.replace(
                                site,
                                target_name=dotted.rsplit(".", 1)[1],
                            )
                        )
    all_violations: list[Violation] = []
    for tree, rel in trees:
        all_violations.extend(_FileLint(tree, rel, corpus).run())
    used: set[Suppression] = set()
    for v in all_violations:
        match = next((s for s in suppressions if s.matches(v)), None)
        if match is None:
            report.violations.append(v)
        else:
            used.add(match)
            report.suppressed.append((v, match))
    report.stale_suppressions = [s for s in suppressions if s not in used]
    report.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return report
