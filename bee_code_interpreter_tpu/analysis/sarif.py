"""Minimal SARIF 2.1.0 rendering for the repo self-lints
(docs/analysis.md "Self-lint").

One run per tool (asynclint, concurrencylint), the smallest shape CI code
scanners accept: driver name + declared rules, one ``result`` per
violation with a physical location. Suppressed findings are emitted with
``suppressions`` entries (kind="inSource" is wrong for our list-based
model, so they carry kind="external" with the justification), which is how
the SARIF viewers show "known, explained" without hiding it.
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def _result(violation, suppression=None) -> dict:
    out: dict = {
        "ruleId": violation.rule,
        "level": "error",
        "message": {"text": violation.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": violation.path},
                    "region": {"startLine": max(1, violation.line)},
                }
            }
        ],
    }
    if suppression is not None:
        out["suppressions"] = [
            {"kind": "external", "justification": suppression.reason}
        ]
    return out


def tool_run(
    tool_name: str,
    violations,
    suppressed=(),
    information_uri: str = "docs/analysis.md",
) -> dict:
    """One SARIF ``run`` for one lint tool. ``violations`` are unexplained
    findings; ``suppressed`` is the (violation, suppression) pairs that
    carried a justification."""
    rules = sorted(
        {v.rule for v in violations} | {v.rule for v, _ in suppressed}
    )
    return {
        "tool": {
            "driver": {
                "name": tool_name,
                "informationUri": information_uri,
                "rules": [{"id": r} for r in rules],
            }
        },
        "results": [_result(v) for v in violations]
        + [_result(v, s) for v, s in suppressed],
    }


def sarif_log(runs: list[dict]) -> dict:
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": runs,
    }
