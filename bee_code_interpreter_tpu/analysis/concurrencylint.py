"""Await-aware concurrency lint for the asyncio control plane
(docs/analysis.md "Concurrency lint rules").

``asynclint.py`` catches single-statement hazards (a blocking call inside
``async def``, a dropped task handle). This linter catches the hazards that
only exist *across* statements — the bug class the repo has now hit several
times by hand-auditing: shared state mutated across an ``await``, a lock
that leaks on an early return, a teardown nobody awaits. It is built on the
``analysis/dataflow.py`` CFG engine and, like asynclint, runs as a tier-1
test (tests/test_concurrencylint.py) with an explicit, justified suppression
list where stale suppressions FAIL.

Rules:

- ``unlocked-rmw-across-await``   a ``self.``-attribute (or declared-global)
  value is read, an ``await`` can run, and the stale value is then written
  back — the lost-update shape single-loop asyncio only protects you from
  *between* awaits, never across them — with no ``asyncio.Lock`` scope
  (``async with lock:``) shared by the read and the write.
- ``lock-not-released``           ``<x>.acquire()`` with a CFG path to the
  function exit that never passes ``<x>.release()`` (early return, raise
  into a handler that forgets, missing ``finally``). ``async with`` cannot
  leak and is the sanctioned spelling.
- ``await-under-lock-self-deadlock``  while a lock scope is held, ``await
  self.m(...)`` where method ``m`` of the same class takes the SAME lock —
  asyncio.Lock is not reentrant, so the caller deadlocks on itself.
- ``unawaited-teardown``          a class defines ``async def aclose``/
  ``stop``, an instance is constructed somewhere in the linted corpus, and
  NO teardown path ever awaits either method on such an instance — work
  nothing can cancel at drain.
- ``thread-loop-touch``           a function handed to ``threading.Thread``
  / ``asyncio.to_thread`` / ``run_in_executor`` pokes event-loop state
  directly (``call_soon``/``create_task``/``ensure_future``/``set_result``/
  ``set_exception``) instead of going through ``call_soon_threadsafe`` —
  the contprof/serving-hook bug class (PR 8/9) promoted to a rule.

The first three rules are intraprocedural per ``async def``; the last two
aggregate per file / per corpus. All of them over-approximate *paths* and
under-approximate *values* (see dataflow.py), so a finding is a real shape
in the code even when the runtime schedule happens to be benign — which is
exactly what the suppression list is for.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from bee_code_interpreter_tpu.analysis.asynclint import (
    PACKAGE_ROOT,
    Suppression,
    Violation,
    default_packages,
)
from bee_code_interpreter_tpu.analysis.dataflow import (
    EXIT,
    FunctionFlow,
    expr_text,
    iter_own_exprs,
)
from bee_code_interpreter_tpu.analysis.inspect import (
    collect_aliases,
    resolve_call_name,
)

#: Packages the concurrency lint additionally skips beyond asynclint's
#: excludes: generated proto stubs, the in-sandbox runtime (its own process,
#: not this event loop), and leaf util/model/kernel code with no async state.
#: (asynclint's excluded accelerator trees — models/, parallel/, ops/,
#: runtime/shim/ — are owned by jaxlint, not skipped.)
EXTRA_EXCLUDES = ("proto", "runtime", "utils")

_TEARDOWN_METHODS = ("aclose", "stop")
_LOOP_TOUCH_ATTRS = frozenset(
    {"call_soon", "create_task", "ensure_future", "set_result", "set_exception"}
)
_THREAD_SPAWNERS = frozenset({"threading.Thread", "asyncio.to_thread"})


# The shipped suppression budget — same contract as asynclint.SUPPRESSIONS:
# every entry names WHY the shape is sound, and an entry that no longer
# matches any violation fails the suite.
SUPPRESSIONS: tuple[Suppression, ...] = (
    Suppression(
        path="services/kubernetes_code_executor.py",
        rule="unawaited-teardown",
        reason=(
            "closed at drain by ApplicationContext.aclose via the getattr-"
            "dispatched `aclose = getattr(backend, 'aclose', None); await "
            "aclose()` behind unwrap_executor — dynamic dispatch the "
            "intraprocedural engine cannot follow; the e2e drain suite "
            "exercises the real path"
        ),
    ),
    Suppression(
        path="services/native_process_code_executor.py",
        rule="unawaited-teardown",
        reason=(
            "same getattr-dispatched backend aclose as the kubernetes "
            "executor (ApplicationContext.aclose / unwrap_executor); the "
            "bench and chaos harnesses also close it via shutdown() on "
            "their sync exit paths"
        ),
    ),
)


@dataclass
class ConcurrencyReport:
    violations: list[Violation] = field(default_factory=list)
    suppressed: list[tuple[Violation, Suppression]] = field(default_factory=list)
    stale_suppressions: list[Suppression] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def clean(self) -> bool:
        return not self.violations and not self.stale_suppressions

    def summary(self) -> str:
        lines = [str(v) for v in self.violations]
        lines += [
            f"stale suppression ({s.path} [{s.rule}]): no matching violation"
            for s in self.stale_suppressions
        ]
        return "\n".join(lines) or "clean"


# --------------------------------------------------------------------------
# per-function rules (RMW across await, lock leak, self-deadlock)
# --------------------------------------------------------------------------


def _attr_loads(stmt: ast.stmt) -> set[str]:
    out = set()
    for e in iter_own_exprs(stmt):
        if isinstance(e, ast.Attribute) and isinstance(e.ctx, ast.Load):
            t = expr_text(e)
            if t is not None and t.startswith("self."):
                out.add(t)
    return out


def _attr_stores(stmt: ast.stmt) -> set[str]:
    targets: list[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out = set()
    for t in targets:
        if isinstance(t, ast.Attribute):
            text = expr_text(t)
            if text is not None and text.startswith("self."):
                out.add(text)
    return out


def _rhs_name_loads(stmt: ast.stmt) -> set[str]:
    value = getattr(stmt, "value", None)
    if not isinstance(value, ast.expr):
        return set()
    return {
        n.id
        for n in ast.walk(value)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _global_names(func: ast.AST) -> set[str]:
    """Names a ``global`` statement makes writable module state inside this
    function — the module-global half of the RMW rule."""
    out: set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _lock_calls(stmt: ast.stmt, method: str) -> set[str]:
    """Receiver texts of ``<recv>.acquire()`` / ``.release()`` calls in this
    statement's own region."""
    out = set()
    for e in iter_own_exprs(stmt):
        if (
            isinstance(e, ast.Call)
            and isinstance(e.func, ast.Attribute)
            and e.func.attr == method
            and not e.args
            and not e.keywords
        ):
            recv = expr_text(e.func.value)
            if recv is not None:
                out.add(recv)
    return out


def _check_rmw(flow: FunctionFlow, path: str, out: list[Violation]) -> None:
    globals_here = _global_names(flow.scope)

    def stores(node) -> set[str]:
        s = _attr_stores(node.stmt)
        if globals_here:
            s |= {n for n in node.defines if n in globals_here}
        return s

    def loads(node) -> set[str]:
        s = _attr_loads(node.stmt)
        if globals_here:
            for e in iter_own_exprs(node.stmt):
                if (
                    isinstance(e, ast.Name)
                    and isinstance(e.ctx, ast.Load)
                    and e.id in globals_here
                ):
                    s.add(e.id)
        return s

    for node in flow.nodes:
        written = stores(node)
        if not written:
            continue
        # Case A: one statement reads, awaits, and writes the same target
        # (`self.x = self.x + await f()`, `self.x += await q.get()`): the
        # read value is stale by the time the store runs.
        if node.has_await and not node.held_locks:
            one_stmt_rmw = written & loads(node)
            if isinstance(node.stmt, ast.AugAssign):
                # the AugAssign target is a read too (AST marks it Store only)
                one_stmt_rmw = written
            for target in one_stmt_rmw:
                out.append(
                    Violation(
                        path=path,
                        line=node.line,
                        rule="unlocked-rmw-across-await",
                        message=(
                            f"{target} is read and written back in one "
                            "statement that awaits in between; the stored "
                            "value is stale — guard with an asyncio.Lock "
                            "or restructure to write before the await"
                        ),
                    )
                )
        # Case B: the write's RHS flows from a local whose defining
        # statement read the same target, with an await on some path in
        # between and no lock scope shared by both ends.
        rhs_locals = _rhs_name_loads(node.stmt)
        if not rhs_locals:
            continue
        reach = flow.reach_in(node.idx)
        for name in rhs_locals:
            for def_idx in reach.get(name, ()):
                def_node = flow.nodes[def_idx]
                for target in written & loads(def_node):
                    # Scope IDENTITY, not lock name: two separate
                    # `async with self._lock` blocks release the lock
                    # across the await between them — the exact window
                    # this rule exists for.
                    if def_node.held_scopes & node.held_scopes:
                        continue
                    if flow.await_between(def_idx, node.idx):
                        out.append(
                            Violation(
                                path=path,
                                line=node.line,
                                rule="unlocked-rmw-across-await",
                                message=(
                                    f"{target} read at line {def_node.line} "
                                    f"is written back here after an await "
                                    "without a shared asyncio.Lock scope; "
                                    "another task can interleave and the "
                                    "update is lost"
                                ),
                            )
                        )


def _check_lock_release(flow: FunctionFlow, path: str, out: list[Violation]) -> None:
    for node in flow.nodes:
        for recv in _lock_calls(node.stmt, "acquire"):
            leaks = flow.exit_reachable_without(
                node.idx, lambda n, r=recv: r in _lock_calls(n.stmt, "release")
            )
            if leaks:
                out.append(
                    Violation(
                        path=path,
                        line=node.line,
                        rule="lock-not-released",
                        message=(
                            f"{recv}.acquire() has a path to the function "
                            f"exit that never calls {recv}.release(); use "
                            "`async with` (it cannot leak) or release in "
                            "a finally"
                        ),
                    )
                )


def _locks_taken(func: ast.AST) -> set[str]:
    """Every ``self.*`` lock scope a method enters anywhere in its body:
    ``async with self._lock`` items plus ``await self._lock.acquire()``."""
    out: set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.AsyncWith):
            for item in node.items:
                t = expr_text(item.context_expr)
                if t is not None and t.startswith("self."):
                    out.add(t)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            recv = expr_text(node.func.value)
            if recv is not None and recv.startswith("self."):
                out.add(recv)
    return out


def _acquired_locks_at(flow: FunctionFlow, node) -> set[str]:
    """``self.*`` locks held at ``node`` via the explicit
    ``await <lock>.acquire()`` spelling: an acquire site reaches this
    statement on some path with no intervening ``release()``."""
    out: set[str] = set()
    for acq in flow.nodes:
        if acq.idx == node.idx:
            continue
        for recv in _lock_calls(acq.stmt, "acquire"):
            if not recv.startswith("self."):
                continue
            if flow.reaches_without(
                acq.idx,
                node.idx,
                lambda n, r=recv: r in _lock_calls(n.stmt, "release"),
            ):
                out.add(recv)
    return out


def _check_self_deadlock(
    methods: dict[str, ast.AST],
    flows: dict[str, FunctionFlow],
    path: str,
    out: list[Violation],
) -> None:
    taken = {name: _locks_taken(func) for name, func in methods.items()}
    for name, flow in flows.items():
        for node in flow.nodes:
            awaited_callees = [
                e.value.func.attr
                for e in iter_own_exprs(node.stmt)
                if isinstance(e, ast.Await)
                and isinstance(e.value, ast.Call)
                and isinstance(e.value.func, ast.Attribute)
                and isinstance(e.value.func.value, ast.Name)
                and e.value.func.value.id == "self"
            ]
            if not awaited_callees:
                continue
            held = {k for k in node.held_locks if k.startswith("self.")}
            # ...plus locks held via the explicit acquire() spelling (an
            # acquire reaching here with no release on the path)
            held |= _acquired_locks_at(flow, node)
            if not held:
                continue
            for callee in awaited_callees:
                overlap = held & taken.get(callee, set())
                if overlap:
                    lock = sorted(overlap)[0]
                    out.append(
                        Violation(
                            path=path,
                            line=node.line,
                            rule="await-under-lock-self-deadlock",
                            message=(
                                f"await self.{callee}(...) while holding "
                                f"{lock}, which {callee}() takes again — "
                                "asyncio.Lock is not reentrant; this "
                                "deadlocks on itself"
                            ),
                        )
                    )


# --------------------------------------------------------------------------
# per-file rules (thread-loop-touch) and corpus aggregation (teardown)
# --------------------------------------------------------------------------


def _walk_excluding_nested(func: ast.AST):
    """Walk a function body without descending into nested defs/lambdas —
    a nested function handed to ``call_soon_threadsafe`` runs ON the loop,
    where touching loop state is the whole point."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _thread_entry_names(tree: ast.AST, aliases: dict[str, str]) -> set[str]:
    """Function/method names handed to a thread in this file: the
    ``target=`` of ``threading.Thread``, the callable of
    ``asyncio.to_thread`` / ``<loop>.run_in_executor``."""
    out: set[str] = set()

    def callable_name(expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr  # self._run -> "_run"
        return None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = resolve_call_name(node.func, aliases)
        if resolved in _THREAD_SPAWNERS:
            target: ast.expr | None = None
            if resolved == "threading.Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif node.args:
                target = node.args[0]
            name = callable_name(target) if target is not None else None
            if name:
                out.add(name)
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "run_in_executor"
            and len(node.args) >= 2
        ):
            name = callable_name(node.args[1])
            if name:
                out.add(name)
    return out


def _check_thread_loop_touch(
    tree: ast.AST, aliases: dict[str, str], path: str, out: list[Violation]
) -> None:
    entries = _thread_entry_names(tree, aliases)
    if not entries:
        return
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.FunctionDef)  # a thread target is sync
            and node.name in entries
        ):
            for inner in _walk_excluding_nested(node):
                if not isinstance(inner, ast.Call):
                    continue
                attr = (
                    inner.func.attr
                    if isinstance(inner.func, ast.Attribute)
                    else None
                )
                resolved = resolve_call_name(inner.func, aliases)
                if attr in _LOOP_TOUCH_ATTRS or resolved in (
                    "asyncio.create_task",
                    "asyncio.ensure_future",
                ):
                    touched = attr or resolved
                    out.append(
                        Violation(
                            path=path,
                            line=inner.lineno,
                            rule="thread-loop-touch",
                            message=(
                                f"{node.name}() runs on a worker thread but "
                                f"calls {touched}() directly; asyncio state "
                                "is not thread-safe — marshal through "
                                "loop.call_soon_threadsafe"
                            ),
                        )
                    )


@dataclass
class _TeardownFacts:
    """Cross-file facts the unawaited-teardown rule aggregates."""

    # class name -> (path, line, tuple of async teardown method names)
    classes: dict[str, tuple[str, int, tuple[str, ...]]] = field(
        default_factory=dict
    )
    # class name -> set of binding components its instances land in
    # ("self.storage = Storage(...)" -> "storage")
    constructions: dict[str, set[str]] = field(default_factory=dict)
    # (binding component, method) pairs awaited anywhere
    awaited: set[tuple[str, str]] = field(default_factory=set)
    # classes entered via `async with Class(...)` — teardown via __aexit__
    async_with: set[str] = field(default_factory=set)


def _class_of_call(func: ast.expr) -> str | None:
    """The class a construction-shaped call names: ``C(...)`` → C,
    ``mod.C(...)`` → C, ``C.from_config(...)`` → C (classmethod)."""
    text = expr_text(func)
    if text is None:
        return None
    parts = text.split(".")
    for part in reversed(parts):
        if part[:1].isupper():
            return part
    return None


def _binding_component(target: ast.expr) -> str | None:
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def _collect_teardown_facts(
    tree: ast.AST, path: str, facts: _TeardownFacts
) -> None:
    def visit(node: ast.AST, func_name: str | None) -> None:
        if isinstance(node, ast.ClassDef):
            methods = tuple(
                m.name
                for m in node.body
                if isinstance(m, ast.AsyncFunctionDef)
                and m.name in _TEARDOWN_METHODS
            )
            if methods and node.name not in facts.classes:
                facts.classes[node.name] = (path, node.lineno, methods)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            cls = _class_of_call(node.value.func)
            if cls is not None:
                for t in node.targets:
                    comp = _binding_component(t)
                    if comp is not None:
                        facts.constructions.setdefault(cls, set()).add(comp)
                if func_name is not None:
                    # The factory pattern: a construction inside `def N`
                    # usually escapes AS `N` (cached_property / builder
                    # methods) — `await ctx.sessions.stop()` tears down the
                    # SessionManager that `def sessions()` built.
                    facts.constructions.setdefault(cls, set()).add(func_name)
        elif isinstance(node, ast.Await):
            call = node.value
            if (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in _TEARDOWN_METHODS
            ):
                recv = expr_text(call.func.value)
                if recv is not None:
                    facts.awaited.add((recv.split(".")[-1], call.func.attr))
        elif isinstance(node, ast.AsyncWith):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    cls = _class_of_call(item.context_expr.func)
                    if cls is not None:
                        facts.async_with.add(cls)
        inner = func_name
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = node.name
        for child in ast.iter_child_nodes(node):
            visit(child, inner)

    visit(tree, None)


def _teardown_violations(facts: _TeardownFacts) -> list[Violation]:
    out: list[Violation] = []
    for cls, (path, line, methods) in sorted(facts.classes.items()):
        if cls in facts.async_with:
            continue
        bindings = facts.constructions.get(cls)
        if not bindings:
            continue  # never constructed in the linted corpus
        awaited = any(
            (comp, m) in facts.awaited for comp in bindings for m in methods
        )
        if not awaited:
            spelled = "/".join(methods)
            out.append(
                Violation(
                    path=path,
                    line=line,
                    rule="unawaited-teardown",
                    message=(
                        f"{cls} defines async {spelled} but no teardown "
                        f"path awaits it on any constructed instance "
                        f"({', '.join(sorted(bindings))}) — its background "
                        "work cannot be cancelled at drain"
                    ),
                )
            )
    return out


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


def _lint_tree(
    tree: ast.AST, path: str, facts: _TeardownFacts | None
) -> list[Violation]:
    aliases = collect_aliases(tree)
    out: list[Violation] = []
    # class methods first (so self-deadlock sees whole classes), then
    # remaining async defs (module-level helpers, nested closures)
    seen: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            methods: dict[str, ast.AST] = {}
            flows: dict[str, FunctionFlow] = {}
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods[m.name] = m
                    if isinstance(m, ast.AsyncFunctionDef):
                        flows[m.name] = FunctionFlow(m, aliases=aliases)
                        seen.add(id(m))
            for flow in flows.values():
                _check_rmw(flow, path, out)
                _check_lock_release(flow, path, out)
            _check_self_deadlock(methods, flows, path, out)
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef) and id(node) not in seen:
            flow = FunctionFlow(node, aliases=aliases)
            _check_rmw(flow, path, out)
            _check_lock_release(flow, path, out)
    _check_thread_loop_touch(tree, aliases, path, out)
    if facts is not None:
        _collect_teardown_facts(tree, path, facts)
    return out


def lint_concurrency_source(source: str, path: str = "<memory>") -> list[Violation]:
    """Lint one source blob with the intraprocedural + per-file rules and
    the teardown rule scoped to this blob alone (unit-test entry point)."""
    import textwrap

    tree = ast.parse(textwrap.dedent(source), filename=path)
    facts = _TeardownFacts()
    violations = _lint_tree(tree, path, facts)
    violations += _teardown_violations(facts)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations


def lint_concurrency_paths(
    root: Path | str = PACKAGE_ROOT,
    packages: tuple[str, ...] | None = None,
    suppressions: tuple[Suppression, ...] = SUPPRESSIONS,
) -> ConcurrencyReport:
    """Lint the control-plane packages (asynclint's derived scope minus
    :data:`EXTRA_EXCLUDES`), apply the suppression list, and report what
    remains — the tier-1 entry point."""
    root = Path(root)
    if packages is None:
        packages = tuple(
            p for p in default_packages(root) if p not in EXTRA_EXCLUDES
        )
    report = ConcurrencyReport()
    facts = _TeardownFacts()
    all_violations: list[Violation] = []
    # Top-level modules too: the composition root (application_context.py)
    # is where most teardown paths live.
    files = list(sorted(root.glob("*.py"))) + [
        py for package in packages for py in sorted((root / package).rglob("*.py"))
    ]
    for py in files:
        rel = str(py.relative_to(root.parent))
        tree = ast.parse(py.read_text(), filename=rel)
        all_violations.extend(_lint_tree(tree, rel, facts))
        report.files_scanned += 1
    all_violations.extend(_teardown_violations(facts))
    used: set[Suppression] = set()
    for v in all_violations:
        match = next((s for s in suppressions if s.matches(v)), None)
        if match is None:
            report.violations.append(v)
        else:
            used.add(match)
            report.suppressed.append((v, match))
    report.stale_suppressions = [s for s in suppressions if s not in used]
    report.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return report
