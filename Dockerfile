# Service (control plane) image. Parity with the reference's service image
# (Dockerfile:1-20): python runtime + kubectl + storage dir; our dependencies
# are pure-pip (aiohttp/grpcio/pydantic/httpx).
FROM python:3.12-slim AS runtime

RUN apt-get update \
    && apt-get install -y --no-install-recommends curl ca-certificates \
    && curl -fsSLo /usr/local/bin/kubectl \
       "https://dl.k8s.io/release/v1.30.0/bin/linux/$(dpkg --print-architecture)/kubectl" \
    && chmod +x /usr/local/bin/kubectl \
    && apt-get purge -y curl && apt-get autoremove -y \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml ./
COPY bee_code_interpreter_tpu ./bee_code_interpreter_tpu
RUN pip install --no-cache-dir .

RUN mkdir -p /storage && chmod 777 /storage
ENV APP_FILE_STORAGE_PATH=/storage

EXPOSE 50051 50081
CMD ["python", "-m", "bee_code_interpreter_tpu"]
