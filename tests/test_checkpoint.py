"""Sharding-aware checkpoint save/restore on the virtual 8-device mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    param_specs,
    shard_params,
)
from bee_code_interpreter_tpu.parallel.mesh import make_mesh
from bee_code_interpreter_tpu.utils.checkpoint import (
    TrainCheckpointer,
    abstract_like,
)


def cfg():
    return dataclasses.replace(TransformerConfig.tiny(), dtype=jnp.float32)


def test_roundtrip_plain_pytree(tmp_path):
    state = {
        "w": jnp.arange(12.0).reshape(3, 4),
        "nested": {"b": jnp.ones((5,)), "count": jnp.int32(7)},
    }
    with TrainCheckpointer(tmp_path) as ckpt:
        ckpt.save(0, state)
        got = ckpt.restore()
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_save_and_cross_topology_restore(tmp_path):
    # Save params sharded over {fsdp: 2, tp: 4}; restore onto {fsdp: 4,
    # tp: 2}. Values must survive exactly and the restored leaves must carry
    # the NEW mesh's shardings — the preempted-slice / changed-topology
    # resume story.
    config = cfg()
    mesh_a = make_mesh({"fsdp": 2, "tp": 4})
    mesh_b = make_mesh({"fsdp": 4, "tp": 2})
    params = shard_params(init_params(config, jax.random.PRNGKey(0)), config, mesh_a)

    with TrainCheckpointer(tmp_path) as ckpt:
        ckpt.save(1, params)
        template = abstract_like(params, mesh_b, param_specs(config, mesh_b))
        restored = ckpt.restore(template=template)

    for path, leaf in jax.tree_util.tree_leaves_with_path(restored):
        assert leaf.sharding.mesh.shape == dict(mesh_b.shape), path
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_optimizer_state_roundtrip(tmp_path):
    # Full train-state checkpoint: params + AdamW moments (nested pytree
    # with non-array-shaped leaves like the step count).
    import optax

    config = cfg()
    mesh = make_mesh({"fsdp": 2, "tp": 4})
    params = shard_params(init_params(config, jax.random.PRNGKey(0)), config, mesh)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    state = {"params": params, "opt_state": opt_state, "step": jnp.int32(17)}

    with TrainCheckpointer(tmp_path) as ckpt:
        ckpt.save(17, state)
        got = ckpt.restore(template=abstract_like(state))

    assert int(got["step"]) == 17
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_retention_and_latest(tmp_path):
    state = {"w": jnp.zeros((2,))}
    with TrainCheckpointer(tmp_path, keep_last=2) as ckpt:
        for s in (1, 2, 3):
            ckpt.save(s, {"w": jnp.full((2,), float(s))})
        assert ckpt.latest_step() == 3
        assert ckpt.all_steps() == [2, 3]  # keep_last pruned step 1
        got = ckpt.restore(step=2, template=abstract_like(state))
    assert float(got["w"][0]) == 2.0


def test_restore_missing_raises(tmp_path):
    with TrainCheckpointer(tmp_path) as ckpt:
        with pytest.raises(FileNotFoundError, match="no checkpoint"):
            ckpt.restore()
