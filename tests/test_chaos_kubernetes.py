"""Chaos suite: the resilience acceptance criteria under injected faults
(ISSUE 1): (a) an edge deadline bounds total wall time across
spawn+upload+execute+download; (b) the spawn breaker opens at the configured
failure rate, routes to the local fallback while open, and half-opens after
cooldown — with matching counters in the /metrics exposition. Faults are
scripted through tests/chaos.py; nothing here talks to a real cluster."""

import asyncio
import time

import pytest

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.resilience import (
    BreakerOpenError,
    BreakerState,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    ResilientCodeExecutor,
    SandboxTransientError,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.services.local_code_executor import LocalCodeExecutor
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import ChaosKubectl, Fail, FaultPlan, Hang, HttpStatus, ManualClock
from tests.fakes import FakeExecutorPods

pytestmark = pytest.mark.chaos


@pytest.fixture
def faults():
    return FaultPlan()


@pytest.fixture
def pods(tmp_path, faults):
    return FakeExecutorPods(tmp_path / "pods", faults=faults)


def make_executor(pods, storage, faults, *, metrics=None, spawn_breaker=None,
                  **config_overrides):
    overrides = dict(
        executor_backend="kubernetes",
        executor_port=pods.port,
        # No warm pool: every execute goes through the faultable spawn path,
        # so the scripted timeline is exactly the request timeline.
        executor_pod_queue_target_length=0,
        pod_ready_timeout_s=5,
        executor_retry_wait_min_s=0.01,
        executor_retry_wait_max_s=0.05,
    )
    overrides.update(config_overrides)
    config = Config(**overrides)
    return KubernetesCodeExecutor(
        kubectl=ChaosKubectl(pods, faults),
        storage=storage,
        config=config,
        metrics=metrics,
        spawn_breaker=spawn_breaker,
        ip_poll_interval_s=0.02,
    )


# --------------------------------------------------- (a) deadline bounding


async def test_deadline_bounds_wall_time_over_hung_spawn(pods, storage, faults):
    # Pod spawn hangs 10s (slow apiserver); the 0.5s edge deadline must bound
    # the request within 10%, not wait out the hang.
    faults.script("pod_wait", Hang(10.0))
    executor = ResilientCodeExecutor(make_executor(pods, storage, faults))
    try:
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            await executor.execute("print(1)", deadline=Deadline.after(0.5))
        elapsed = time.monotonic() - t0
        assert elapsed < 0.55, f"deadline 0.5s not honored: took {elapsed:.3f}s"
    finally:
        await pods.close()


async def test_deadline_bounds_wall_time_over_hung_execute(pods, storage, faults):
    # Healthy spawn, then the sandbox hangs mid-/execute: the deadline spans
    # the whole spawn+upload+execute pipeline, not per-call budgets.
    faults.script("execute", Hang(10.0))
    executor = ResilientCodeExecutor(make_executor(pods, storage, faults))
    try:
        t0 = time.monotonic()
        with pytest.raises((DeadlineExceeded, SandboxTransientError)):
            await executor.execute("print(1)", deadline=Deadline.after(1.0))
        elapsed = time.monotonic() - t0
        assert elapsed < 1.1, f"deadline 1.0s not honored: took {elapsed:.3f}s"
    finally:
        await pods.close()


async def test_deadline_leaves_no_leaked_pods(pods, storage, faults):
    # The pods created before the deadline fired must still be torn down
    # (cancellation runs the gang delete-on-failure path).
    faults.script("pod_wait", Hang(10.0))
    k8s = make_executor(pods, storage, faults)
    try:
        with pytest.raises(DeadlineExceeded):
            await ResilientCodeExecutor(k8s).execute(
                "print(1)", deadline=Deadline.after(0.3)
            )
        for _ in range(5):
            await asyncio.sleep(0.02)  # let fire-and-forget deletes land
        kubectl = k8s._kubectl
        created = {m["metadata"]["name"] for m in kubectl.created_manifests}
        assert created <= set(kubectl.deleted)
    finally:
        await pods.close()


# ------------------------------------- (b) breaker + fallback + half-open


async def test_spawn_breaker_opens_falls_back_then_recovers(
    pods, storage, faults, tmp_path
):
    clock = ManualClock()
    metrics = Registry()
    spawn_breaker = CircuitBreaker(
        "k8s-spawn", window=4, failure_rate_threshold=0.5, min_calls=2,
        cooldown_s=30.0, half_open_max_calls=1, clock=clock,
    )
    k8s = make_executor(
        pods, storage, faults,
        metrics=metrics,
        spawn_breaker=spawn_breaker,
        executor_retry_attempts=1,  # 1 spawn attempt per request: scripted 1:1
    )
    fallback = LocalCodeExecutor(
        storage=storage,
        workspace_root=tmp_path / "fallback-ws",
        disable_dep_install=True,
    )
    resilient = ResilientCodeExecutor(k8s, fallback=fallback, metrics=metrics)
    kubectl = k8s._kubectl
    try:
        # Two spawn failures at 100% failure rate (min_calls=2): breaker opens.
        faults.script("pod_create", Fail("apiserver down"), Fail("apiserver down"))
        for _ in range(2):
            with pytest.raises(RuntimeError):
                await resilient.execute("print('down')")
        assert spawn_breaker.state is BreakerState.OPEN

        # While OPEN: no spawn attempted, request served by the local fallback.
        creates_before = len(kubectl.created_manifests)
        result = await resilient.execute("print(21 * 2)")
        assert result.stdout == "42\n"
        assert len(kubectl.created_manifests) == creates_before  # no k8s call
        text = metrics.expose()
        assert "bci_executor_fallback_total 1" in text
        assert (
            'bci_breaker_transitions_total{breaker="k8s-spawn",to="open"} 1'
            in text
        )

        # Cooldown elapses -> HALF_OPEN; the healthy probe closes the breaker
        # and the request is served by a real pod again.
        clock.advance(31.0)
        assert spawn_breaker.state is BreakerState.HALF_OPEN
        result = await resilient.execute("print('back')")
        assert result.stdout == "back\n"
        assert spawn_breaker.state is BreakerState.CLOSED
        assert len(kubectl.created_manifests) == creates_before + 1
        text = metrics.expose()
        assert (
            'bci_breaker_transitions_total{breaker="k8s-spawn",to="half_open"} 1'
            in text
        )
        assert (
            'bci_breaker_transitions_total{breaker="k8s-spawn",to="closed"} 1'
            in text
        )
    finally:
        await pods.close()


async def test_open_breaker_without_fallback_fails_fast(pods, storage, faults):
    clock = ManualClock()
    spawn_breaker = CircuitBreaker(
        "k8s-spawn", window=4, failure_rate_threshold=0.5, min_calls=2,
        cooldown_s=30.0, clock=clock,
    )
    executor = make_executor(
        pods, storage, faults,
        spawn_breaker=spawn_breaker, executor_retry_attempts=1,
    )
    resilient = ResilientCodeExecutor(executor)  # no fallback configured
    try:
        faults.script("pod_create", Fail(), Fail())
        for _ in range(2):
            with pytest.raises(RuntimeError):
                await resilient.execute("print(1)")
        t0 = time.monotonic()
        with pytest.raises(BreakerOpenError) as exc:
            await resilient.execute("print(1)")
        assert time.monotonic() - t0 < 0.1  # fail-fast, no spawn wait
        assert exc.value.retry_after_s == pytest.approx(30.0, abs=1.0)
    finally:
        await pods.close()


async def test_http_breaker_opens_on_sustained_5xx(pods, storage, faults):
    # The data-plane breaker: sustained 5xx from sandboxes trips k8s-http.
    executor = make_executor(
        pods, storage, faults,
        executor_retry_attempts=1,
        breaker_min_calls=2, breaker_window=4,
    )
    try:
        faults.script(
            "execute",
            HttpStatus(503), HttpStatus(503), HttpStatus(503), HttpStatus(503),
        )
        for _ in range(2):
            with pytest.raises(SandboxTransientError):
                await executor.execute("print(1)")
        assert executor.http_breaker.state is BreakerState.OPEN
        # Next request spawns a pod but the data plane refuses fast.
        with pytest.raises(BreakerOpenError):
            await executor.execute("print(1)")
    finally:
        await pods.close()


async def test_transient_5xx_retried_to_success_with_metrics(
    pods, storage, faults
):
    metrics = Registry()
    executor = make_executor(pods, storage, faults, metrics=metrics)
    try:
        faults.script("execute", HttpStatus(502))  # one bad answer, then healthy
        result = await executor.execute("print('recovered')")
        assert result.stdout == "recovered\n"
        assert [op for op, _ in executor.retry_backoffs] == ["execute"]
        assert (
            'bci_executor_retry_attempts_total{op="execute"} 1'
            in metrics.expose()
        )
    finally:
        await pods.close()
