"""Test doubles for the cluster seam (SURVEY.md §4: the fake kubectl / fake
executor the reference never had)."""

from __future__ import annotations

import asyncio
import json
import socket
from pathlib import Path

from aiohttp import web

from bee_code_interpreter_tpu.runtime.executor_core import ExecutorCore
from bee_code_interpreter_tpu.runtime.executor_server import create_app


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FakeExecutorPods:
    """Real executor HTTP servers, one per simulated pod, each on its own
    loopback IP (127.1.0.x) sharing a single port — so the executor driver can
    address them exactly like pods on a pod network.

    Set ``self.faults`` to a ``tests.chaos.FaultPlan`` to inject scripted
    data-plane failures (5xx, hangs, connection resets) on the upload /
    execute / download routes — the deterministic chaos seam the resilience
    tests drive (tests/chaos.py)."""

    def __init__(
        self, workspace_root: Path, port: int | None = None, faults=None
    ) -> None:
        self.workspace_root = workspace_root
        self.port = port or free_port()
        self.faults = faults
        # Anchors fire-and-forget pod-kill tasks (the loop holds only weak
        # refs; an unanchored task can be GC-cancelled before it runs).
        self._background_tasks: set[asyncio.Task] = set()
        self._runners: dict[str, web.AppRunner] = {}
        self.cores: dict[str, ExecutorCore] = {}
        self.execute_counts: dict[str, int] = {}
        self._next_ip = 1

    async def start_pod(self, manifest: dict | None = None) -> str:
        ip = f"127.1.0.{self._next_ip}"
        self._next_ip += 1
        core = ExecutorCore(
            workspace=self.workspace_root / ip, disable_dep_install=True,
            default_timeout_s=30.0,
        )
        app = create_app(core)

        @web.middleware
        async def count_executes(request, handler):
            # /execute and its streaming twin /execute/stream both count.
            if request.path.startswith("/execute"):
                self.execute_counts[ip] = self.execute_counts.get(ip, 0) + 1
            return await handler(request)

        @web.middleware
        async def inject_faults(request, handler):
            if self.faults is not None:
                op = None
                if request.path.startswith("/execute"):
                    op = "execute"
                elif request.path.startswith("/workspace"):
                    op = "upload" if request.method == "PUT" else "download"
                if op is not None:
                    response = await self.faults.apply_http(
                        # kill lets DieMidExecute take this whole pod down,
                        # not just the one connection.
                        op, request, kill=lambda: self._kill_pod(ip)
                    )
                    if response is not None:
                        return response
            return await handler(request)

        app.middlewares.append(count_executes)
        app.middlewares.append(inject_faults)
        # Short shutdown grace: stop_pod()/close() must not wait out a
        # scripted Hang(...) still sleeping in a handler.
        runner = web.AppRunner(app, shutdown_timeout=0.1)
        await runner.setup()
        site = web.TCPSite(runner, ip, self.port)
        await site.start()
        self._runners[ip] = runner
        self.cores[ip] = core
        return ip

    def _kill_pod(self, ip: str) -> None:
        """Schedule a pod's death (DieMidExecute), anchored so GC cannot
        cancel the teardown before it runs."""
        task = asyncio.ensure_future(self.stop_pod(ip))
        self._background_tasks.add(task)
        task.add_done_callback(self._background_tasks.discard)

    async def stop_pod(self, ip: str) -> None:
        """Simulate preemption: the pod's server vanishes mid-pool."""
        runner = self._runners.pop(ip, None)
        if runner is not None:
            await runner.cleanup()

    async def close(self) -> None:
        for runner in self._runners.values():
            await runner.cleanup()


class FakeKubectl:
    """In-memory kubectl: create/get/wait/delete on pod manifests, backed by
    FakeExecutorPods for pod IPs."""

    def __init__(self, pods: FakeExecutorPods) -> None:
        self._backend = pods
        self.pods: dict[str, dict] = {}
        self.deleted: list[str] = []
        self.created_manifests: list[dict] = []
        self.fail_create_names: set[str] = set()  # pods whose creation errors
        self.fail_ready_names: set[str] = set()  # pods that never become Ready

    async def create(self, *args, _input=None, **kwargs):
        manifest = json.loads(_input)
        name = manifest["metadata"]["name"]
        self.created_manifests.append(manifest)
        if name in self.fail_create_names:
            raise RuntimeError(f"fake: create {name} failed")
        # Backends get the manifest so they can honor the container env the
        # control plane baked in (the full-stack distributed test applies it
        # to real server processes; most backends ignore it).
        ip = await self._backend.start_pod(manifest)
        self.pods[name] = {
            "metadata": manifest["metadata"],
            "spec": manifest["spec"],
            "status": {"podIP": ip, "phase": "Running"},
        }
        return self.pods[name]

    async def get(self, kind, name, **kwargs):
        assert kind == "pod"
        if name not in self.pods:
            raise RuntimeError(f"fake: pod {name} not found")
        return self.pods[name]

    async def wait(self, target, **kwargs):
        name = target.removeprefix("pod/")
        if name in self.fail_ready_names or name not in self.pods:
            raise RuntimeError(f"fake: pod {name} never Ready")
        return self.pods[name]

    async def delete(self, kind, name, **kwargs):
        self.deleted.append(name)
        self.pods.pop(name, None)
        return {}


class ReplicaStack:
    """One COMPLETE in-process replica for fleet-tier tests and chaos
    scenario 14 (docs/fleet.md): the real HTTP edge over the real
    KubernetesCodeExecutor against its own fake-pod cluster, with its own
    SessionManager / SLO engine / admission / drain — sharing a
    SharedDirectoryBackend snapshot root with its siblings, served on a
    real localhost socket. Production fleet shape minus kubectl.

    Imports are deferred to ``start()`` so importing tests.fakes stays
    cheap for the many suites that only want the fake cluster."""

    def __init__(
        self,
        name: str,
        tmp_path,
        shared_root,
        faults=None,
        tenants: str | None = None,
        lease_router_urls: list[str] | None = None,
        autoscale_window_s: float | None = None,
    ) -> None:
        self.name = name
        self.tmp_path = Path(tmp_path)
        self.shared_root = shared_root
        self.faults = faults
        self.tenants = tenants  # APP_TENANTS spec for this replica's edge
        # Fleet-wide quota leasing (docs/tenancy.md "Fleet-wide tenancy"):
        # router base URLs this replica leases rate-quota slices from.
        self.lease_router_urls = lease_router_urls
        # Capacity observability (docs/capacity.md): a short demand window
        # wires the DemandTracker/Forecaster pair into this replica's edge
        # so GET /v1/autoscale answers — short so chaos tests see the
        # recommendation converge in test-scale seconds, not 60s windows.
        self.autoscale_window_s = autoscale_window_s
        self.demand = None
        self.forecaster = None
        self.lease_client = None
        self.quota_leases = None
        self.stopped = False

    async def start(self) -> "ReplicaStack":
        from bee_code_interpreter_tpu.api.http_server import create_http_server
        from bee_code_interpreter_tpu.config import Config
        from bee_code_interpreter_tpu.observability import (
            FlightRecorder,
            SloEngine,
            Tracer,
            parse_objectives,
        )
        from bee_code_interpreter_tpu.resilience import (
            AdmissionController,
            DrainController,
        )
        from bee_code_interpreter_tpu.services.custom_tool_executor import (
            CustomToolExecutor,
        )
        from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
            KubernetesCodeExecutor,
        )
        from bee_code_interpreter_tpu.services.storage import (
            SharedDirectoryBackend,
            Storage,
        )
        from bee_code_interpreter_tpu.sessions import SessionManager
        from bee_code_interpreter_tpu.utils.metrics import Registry

        self.pods = FakeExecutorPods(
            self.tmp_path / f"pods-{self.name}", faults=self.faults
        )
        self.storage = Storage(
            backend=SharedDirectoryBackend(self.shared_root)
        )
        config = Config(
            executor_backend="kubernetes",
            executor_port=self.pods.port,
            executor_pod_queue_target_length=1,
            pod_ready_timeout_s=5,
            executor_retry_attempts=1,
            session_drain_grace_s=30.0,
        )
        self.metrics = Registry()
        self.k8s = KubernetesCodeExecutor(
            kubectl=FakeKubectl(self.pods),
            storage=self.storage,
            config=config,
            metrics=self.metrics,
            ip_poll_interval_s=0.02,
        )
        await self.k8s.fill_executor_pod_queue()
        self.drain = DrainController()
        self.slo = SloEngine(parse_objectives(99.5, None), metrics=self.metrics)
        self.sessions = SessionManager(
            self.k8s,
            self.storage,
            max_sessions=4,
            ttl_s=120.0,
            idle_s=120.0,
            sweep_interval_s=0.2,
            drain_grace_s=30.0,
            drain=self.drain,
            metrics=self.metrics,
        )
        self.tenancy = None
        if self.tenants is not None:
            from bee_code_interpreter_tpu.tenancy import (
                TenantRegistry,
                parse_tenants,
            )

            self.tenancy = TenantRegistry(
                parse_tenants(self.tenants), metrics=self.metrics
            )
        if self.lease_router_urls:
            from bee_code_interpreter_tpu.tenancy import (
                QuotaLeaseCache,
                QuotaLeaseClient,
            )

            self.quota_leases = QuotaLeaseCache()
        autoscale = None
        if self.autoscale_window_s is not None:
            from bee_code_interpreter_tpu.observability import (
                DemandTracker,
                Forecaster,
            )
            from bee_code_interpreter_tpu.resilience.autoscaler import (
                autoscale_snapshot,
            )

            window = self.autoscale_window_s
            self.demand = DemandTracker(
                window_s=window, metrics=self.metrics
            )
            self.forecaster = Forecaster(
                self.demand,
                peak_window_s=min(window, 5.0),
                max_horizon_s=2.0,
                metrics=self.metrics,
            )
            self.k8s.journal.add_sink(self.demand.on_fleet_event)
            autoscale = lambda: autoscale_snapshot(  # noqa: E731
                demand=self.demand,
                forecaster=self.forecaster,
                slo=self.slo,
            )
        self.admission = AdmissionController(
            max_in_flight=8,
            max_queue=16,
            retry_after_s=0.2,
            metrics=self.metrics,
            tenancy=self.tenancy,
            quota_leases=self.quota_leases,
            demand=self.demand,
        )
        if self.lease_router_urls:
            self.lease_client = QuotaLeaseClient(
                self.quota_leases,
                self.admission,
                replica=self.name,
                router_urls=list(self.lease_router_urls),
                interval_s=0.2,
                metrics=self.metrics,
            )
            self.lease_client.start()
        self.recorder = FlightRecorder(max_events=4096, metrics=self.metrics)
        tracer = Tracer(metrics=self.metrics)
        tracer.add_sink(self.recorder.record_trace)
        app = create_http_server(
            code_executor=self.k8s,
            custom_tool_executor=CustomToolExecutor(code_executor=self.k8s),
            metrics=self.metrics,
            admission=self.admission,
            request_deadline_s=30.0,
            tracer=tracer,
            fleet=self.k8s.journal,
            drain=self.drain,
            slo=self.slo,
            sessions=self.sessions,
            tenancy=self.tenancy,
            recorder=self.recorder,
            autoscale=autoscale,
        )
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        self.port = free_port()
        await web.TCPSite(self.runner, "127.0.0.1", self.port).start()
        self.base_url = f"http://127.0.0.1:{self.port}"
        return self

    async def stop(self, hard: bool = False) -> None:
        """``hard=True`` is the replica-kill: listener and backend torn
        down with leases left wherever they are (a fleet router must have
        moved them first)."""
        if self.stopped:
            return
        self.stopped = True
        if self.lease_client is not None:
            await self.lease_client.stop()
        await self.sessions.stop()
        if not hard:
            await self.sessions.close_all()
        await self.runner.cleanup()
        await self.k8s.aclose()
        await self.pods.close()


class FakeS3:
    """In-process S3-shaped object store for the ``S3HttpBackend``
    conformance suite (docs/fleet.md "Storage backends"): path-style
    ``PUT/GET/HEAD /{bucket}/{key}`` over an in-memory dict. Multiple
    backend instances pointed at the same FakeS3 share one "bucket" —
    exactly the replica-agnosticism the fleet tier relies on."""

    def __init__(self, port: int | None = None) -> None:
        self.port = port or free_port()
        self.objects: dict[tuple[str, str], bytes] = {}
        self.put_count = 0
        self.fail_next = 0  # next N PUT/GETs answer 503 (retry/error paths)
        self._runner: web.AppRunner | None = None

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def _maybe_fail(self) -> web.Response | None:
        if self.fail_next > 0:
            self.fail_next -= 1
            return web.json_response({"detail": "slow down"}, status=503)
        return None

    async def _put(self, request: web.Request) -> web.Response:
        if (fail := self._maybe_fail()) is not None:
            return fail
        key = (request.match_info["bucket"], request.match_info["key"])
        self.objects[key] = await request.read()
        self.put_count += 1
        return web.Response(status=200)

    async def _get(self, request: web.Request) -> web.Response:
        if (fail := self._maybe_fail()) is not None:
            return fail
        key = (request.match_info["bucket"], request.match_info["key"])
        body = self.objects.get(key)
        if body is None:
            return web.Response(status=404)
        return web.Response(body=body)

    async def _head(self, request: web.Request) -> web.Response:
        key = (request.match_info["bucket"], request.match_info["key"])
        return web.Response(status=200 if key in self.objects else 404)

    async def _delete(self, request: web.Request) -> web.Response:
        key = (request.match_info["bucket"], request.match_info["key"])
        self.objects.pop(key, None)
        return web.Response(status=204)

    async def start(self) -> "FakeS3":
        app = web.Application(client_max_size=1 << 28)
        app.router.add_put("/{bucket}/{key}", self._put)
        app.router.add_route("HEAD", "/{bucket}/{key}", self._head)
        app.router.add_get("/{bucket}/{key}", self._get, allow_head=False)
        app.router.add_delete("/{bucket}/{key}", self._delete)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        await web.TCPSite(self._runner, "127.0.0.1", self.port).start()
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


class FakeCollector:
    """In-process OTLP/HTTP collector double for the telemetry exporter:
    records every JSON payload POSTed to ``/v1/traces`` / ``/v1/metrics`` /
    ``/v1/logs``. ``fail_next`` makes the next N posts answer 503 (retry
    coverage); ``stop()`` kills the listener mid-run (the chaos scenario)."""

    def __init__(self, port: int | None = None) -> None:
        self.port = port or free_port()
        self.trace_batches: list[dict] = []
        self.metric_batches: list[dict] = []
        self.log_batches: list[dict] = []
        self.requests = 0
        self.fail_next = 0
        self._runner: web.AppRunner | None = None

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def span_trace_ids(self) -> set[str]:
        """Every traceId seen across all received span batches."""
        return {
            span["traceId"]
            for batch in self.trace_batches
            for rs in batch.get("resourceSpans", [])
            for ss in rs.get("scopeSpans", [])
            for span in ss.get("spans", [])
        }

    def log_records(self) -> list[dict]:
        """Every logRecord seen across all received logs batches (the wide
        events the flight recorder exported)."""
        return [
            record
            for batch in self.log_batches
            for rl in batch.get("resourceLogs", [])
            for sl in rl.get("scopeLogs", [])
            for record in sl.get("logRecords", [])
        ]

    async def _handle(self, request: web.Request, sink: list) -> web.Response:
        self.requests += 1
        if self.fail_next > 0:
            self.fail_next -= 1
            return web.json_response({"detail": "collector overloaded"}, status=503)
        sink.append(json.loads(await request.read()))
        return web.json_response({})

    async def start(self) -> "FakeCollector":
        app = web.Application(client_max_size=1 << 26)

        async def traces(request):
            return await self._handle(request, self.trace_batches)

        async def metrics(request):
            return await self._handle(request, self.metric_batches)

        async def logs(request):
            return await self._handle(request, self.log_batches)

        app.router.add_post("/v1/traces", traces)
        app.router.add_post("/v1/metrics", metrics)
        app.router.add_post("/v1/logs", logs)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        await web.TCPSite(self._runner, "127.0.0.1", self.port).start()
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
