"""The round-3 example payloads (vision training, checkpoint/resume) driven
through the real service path, mirroring tests/test_baseline_configs.py:
examples must be runnable artifacts, not documentation."""

from pathlib import Path

import pytest

from tests.http_helpers import post_execute  # http_app fixture: conftest

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"


@pytest.fixture
def local_executor(local_executor_factory):
    # Overrides conftest's 30s-capped executor: these payloads jit-compile
    # real models, and on a loaded box (e.g. a parallel pytest run) the
    # compile alone can blow a 30s budget — a flake, not a regression.
    return local_executor_factory(execution_timeout_s=600.0)


async def test_resnet_train_example(http_app):
    # Off-TPU the example self-downsizes to the tiny config, so the payload
    # runs as-is through the service (the CPU path CI can afford).
    source = (EXAMPLES / "resnet-train-jax.py").read_text()
    body = await post_execute(
        http_app, {"source_code": source, "timeout": 600}
    )
    assert body["exit_code"] == 0, body["stderr"]
    assert "resnet train:" in body["stdout"]
    assert "img/s" in body["stdout"]


async def test_speculative_decode_example(http_app):
    source = (EXAMPLES / "speculative-decode.py").read_text()
    body = await post_execute(http_app, {"source_code": source, "timeout": 600})
    assert body["exit_code"] == 0, body["stderr"]
    assert "exact-vs-greedy True" in body["stdout"]


async def test_continuous_batching_example(http_app):
    source = (EXAMPLES / "continuous-batching.py").read_text()
    body = await post_execute(http_app, {"source_code": source, "timeout": 600})
    assert body["exit_code"] == 0, body["stderr"]
    assert "continuous batching OK" in body["stdout"]
    assert "speculative serving OK" in body["stdout"]
    assert "prefix caching OK" in body["stdout"]
    assert "outputs == solo decode" in body["stdout"]


async def test_checkpoint_resume_example(http_app):
    # The checkpoint lands under /workspace, so the response's file map must
    # carry the checkpoint artifacts — that is the resume contract (pass the
    # map back into the next execution to continue training).
    source = (EXAMPLES / "checkpoint-resume.py").read_text()
    body = await post_execute(
        http_app, {"source_code": source, "timeout": 600}
    )
    assert body["exit_code"] == 0, body["stderr"]
    assert "state-exact True" in body["stdout"]
    assert any("ckpt/3/" in path for path in body["files"]), body["files"]


async def test_serving_features_example(http_app):
    source = (EXAMPLES / "serving-features.py").read_text()
    body = await post_execute(http_app, {"source_code": source, "timeout": 600})
    assert body["exit_code"] == 0, body["stderr"]
    for marker in ("stops+logprobs OK", "constrained decoding OK",
                   "cancel OK", "multi-LoRA OK"):
        assert marker in body["stdout"]


async def test_hf_weights_text_serving_example(http_app):
    source = (EXAMPLES / "hf-weights-text-serving.py").read_text()
    body = await post_execute(http_app, {"source_code": source, "timeout": 600})
    assert body["exit_code"] == 0, body["stderr"]
    for marker in ("hf parity OK", "text serving OK", "stop strings OK"):
        assert marker in body["stdout"]
