"""Chunked prefill and EOS stop tokens."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.models import transformer as T


def cfg(**kw):
    return dataclasses.replace(T.TransformerConfig.tiny(), dtype=jnp.float32, **kw)


@pytest.mark.parametrize("L,chunk", [(24, 8), (20, 8), (7, 16), (16, 16)])
def test_chunked_prefill_matches_full_forward(L, chunk):
    # cache + final logits must equal the one-shot forward, across exact
    # multiples, a remainder chunk, and a single partial chunk.
    config = cfg(n_kv_heads=2)
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, L), 0, config.vocab_size)
    total = L + 4

    logits_full, (k_pre, v_pre) = T.forward(params, tokens, config, return_kv=True)
    want_cache = T.init_decode_cache(config, 2, total, k_pre, v_pre)

    last, cache = T.prefill_chunked(params, tokens, config, total, chunk=chunk)
    np.testing.assert_allclose(
        np.asarray(last), np.asarray(logits_full[:, -1, :]), atol=1e-4, rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(cache), jax.tree.leaves(want_cache)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )


def test_chunked_prefill_then_decode_matches_generate():
    # End-to-end: seed the cache chunked, then greedy-decode with
    # decode_step — tokens must match generate_cached (whole-prompt prefill).
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 10), 0, config.vocab_size)
    n_new = 5
    want = T.Transformer(config).generate_cached(params, prompt, n_new)

    last, cache = T.prefill_chunked(
        params, prompt, config, 10 + n_new, chunk=4
    )
    tok = jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None]
    out = [tok]
    for i in range(n_new - 1):
        lg, cache = T.decode_step(params, tok, jnp.int32(10 + i), cache, config)
        tok = jnp.argmax(lg[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
        out.append(tok)
    got = jnp.concatenate(out, axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want[:, 10:]))


def test_eos_freezes_row():
    # Pick eos_id = the token greedy emits at step 3; everything after must
    # repeat it, while the pre-EOS prefix is unchanged.
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, config.vocab_size)
    free = T.Transformer(config).generate_cached(params, prompt, 8)
    eos = int(free[0, 5 + 2])  # the 3rd generated token

    out = T.Transformer(config).generate_cached(params, prompt, 8, eos_id=eos)
    got = np.asarray(out[0, 5:])
    want_prefix = np.asarray(free[0, 5 : 5 + 3])  # up to and incl. the eos
    np.testing.assert_array_equal(got[:3], want_prefix)
    assert (got[2:] == eos).all(), got


def test_eos_in_first_token():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 5), 0, config.vocab_size)
    free = T.Transformer(config).generate_cached(params, prompt, 6)
    eos = int(free[0, 5])  # the very first generated token
    out = T.Transformer(config).generate_cached(params, prompt, 6, eos_id=eos)
    assert (np.asarray(out[0, 5:]) == eos).all()


def test_undersized_total_len_rejected():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jnp.zeros((1, 12), jnp.int32)
    with pytest.raises(ValueError, match="must cover the prompt"):
        T.prefill_chunked(params, prompt, config, total_len=8, chunk=4)


def test_int8_prefill_chunk_invariant():
    # int8 chunked prefill is chunk-size-invariant: every row's K/V is
    # quantized per row on append and every read is dequantized, so the
    # cache evolution doesn't depend on how the prompt was windowed. (It is
    # NOT pinned against the full forward — full prefill attends in exact
    # precision before quantizing, chunked attends over the progressively
    # quantized cache, the same semantics incremental decode has.)
    config = cfg(n_kv_heads=2, kv_cache_dtype="int8")
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 13), 0, config.vocab_size)

    lg_a, cache_a = T.prefill_chunked(params, prompt, config, 16, chunk=13)
    lg_b, cache_b = T.prefill_chunked(params, prompt, config, 16, chunk=4)
    np.testing.assert_allclose(
        np.asarray(lg_a), np.asarray(lg_b), atol=1e-4, rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(cache_a), jax.tree.leaves(cache_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_generate_cached_int8_with_prefill_chunk():
    # End-to-end: the int8 + prefill_chunk combination decodes and the
    # result is chunk-invariant (chunk >= L degenerates to one window).
    config = cfg(n_kv_heads=2, kv_cache_dtype="int8")
    model = T.Transformer(config)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 11), 0, config.vocab_size)
    one_window = model.generate_cached(
        params, prompt, max_new_tokens=6, prefill_chunk=11
    )
    chunked = model.generate_cached(
        params, prompt, max_new_tokens=6, prefill_chunk=4
    )
    np.testing.assert_array_equal(np.asarray(chunked), np.asarray(one_window))


def test_empty_prompt_rejected():
    # L == 0 has no last_logits to start decode from — fail fast at entry
    # instead of an opaque None crash later in sample_logits (ADVICE r3).
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jnp.zeros((1, 0), jnp.int32)
    with pytest.raises(ValueError, match="non-empty"):
        T.prefill_chunked(params, prompt, config, total_len=8, chunk=4)


def test_generate_cached_with_prefill_chunk():
    # the integrated path: generate_cached(prefill_chunk=N) must produce the
    # same tokens as the full-prefill path, sampling and eos included.
    config = cfg(n_kv_heads=2)
    model = T.Transformer(config)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 11), 0, config.vocab_size)
    full = model.generate_cached(params, prompt, max_new_tokens=6)
    chunked = model.generate_cached(
        params, prompt, max_new_tokens=6, prefill_chunk=4
    )
    np.testing.assert_array_equal(np.asarray(chunked), np.asarray(full))

    k = jax.random.PRNGKey(7)
    a = model.generate_cached(
        params, prompt, max_new_tokens=6, temperature=1.0, top_k=8, key=k
    )
    b = model.generate_cached(
        params, prompt, max_new_tokens=6, temperature=1.0, top_k=8, key=k,
        prefill_chunk=4,
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_generate_cached_prefill_chunk_on_mesh():
    # The round-4 matrix close (VERDICT r3 #5a): long prompts on sharded
    # models — chunked prefill under a dp x tp mesh must reproduce the
    # single-device chunked path token-for-token (GSPMD shards the
    # decode_window einsums from the param shardings; the constraint pins
    # the activation batch to dp).
    from bee_code_interpreter_tpu.parallel.mesh import make_mesh

    config = cfg(n_kv_heads=2)
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 11), 0, config.vocab_size)
    want = T.Transformer(config).generate_cached(
        params, prompt, max_new_tokens=6, prefill_chunk=4
    )
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    sharded = T.shard_params(params, config, mesh)
    got = T.Transformer(config, mesh).generate_cached(
        sharded, prompt, max_new_tokens=6, prefill_chunk=4
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_prefill_chunked_fsdp_mesh_matches():
    # fsdp shards the same batch dim the constraint names; the cache and
    # last-logits must agree with the unsharded chunked prefill.
    from bee_code_interpreter_tpu.parallel.mesh import make_mesh

    config = cfg(n_kv_heads=2)
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 10), 0, config.vocab_size)
    lg_want, cache_want = T.prefill_chunked(params, prompt, config, 12, chunk=4)
    mesh = make_mesh({"fsdp": 2}, devices=jax.devices()[:2])
    lg_got, cache_got = T.prefill_chunked(
        T.shard_params(params, config, mesh), prompt, config, 12, chunk=4,
        mesh=mesh,
    )
    np.testing.assert_allclose(
        np.asarray(lg_got), np.asarray(lg_want), atol=1e-4, rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(cache_got), jax.tree.leaves(cache_want)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )


def test_chunk_size_validated():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="chunk must be >= 1"):
        T.prefill_chunked(params, prompt, config, 12, chunk=0)
