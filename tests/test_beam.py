"""Beam search: beam=1 ≡ greedy, ordering, determinism."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.beam import beam_search


def cfg(**kw):
    return dataclasses.replace(T.TransformerConfig.tiny(), dtype=jnp.float32, **kw)


def test_beam_one_equals_greedy():
    config = cfg(n_kv_heads=2)
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, config.vocab_size)
    want = T.Transformer(config).generate_cached(params, prompt, max_new_tokens=7)
    got = beam_search(params, config, prompt, max_new_tokens=7, beam_size=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_beams_sorted_and_deterministic():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0, config.vocab_size)
    seqs_a, scores_a = beam_search(
        params, config, prompt, max_new_tokens=6, beam_size=4, return_all=True
    )
    seqs_b, scores_b = beam_search(
        params, config, prompt, max_new_tokens=6, beam_size=4, return_all=True
    )
    np.testing.assert_array_equal(np.asarray(seqs_a), np.asarray(seqs_b))
    assert seqs_a.shape == (2, 4, 11)
    s = np.asarray(scores_a)
    assert (np.diff(s, axis=1) <= 1e-6).all(), s  # best-first ordering
    # every beam preserves the prompt
    np.testing.assert_array_equal(
        np.asarray(seqs_a[:, :, :5]),
        np.broadcast_to(np.asarray(prompt)[:, None, :], (2, 4, 5)),
    )


def test_beam_score_matches_rescored_sequence():
    # The reported score must equal the sum of per-step log-probs of the
    # returned sequence under the model (exact bookkeeping, no drift).
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 4), 0, config.vocab_size)
    n_new = 5
    seqs, scores = beam_search(
        params, config, prompt, max_new_tokens=n_new, beam_size=3,
        return_all=True,
    )
    best = seqs[0, 0][None, :]  # [1, total]
    logits = T.forward(params, best, config)
    lp = jax.nn.log_softmax(logits, axis=-1)
    total = 0.0
    for i in range(n_new):
        pos = 4 + i  # token at index pos predicted by logits at pos-1
        total += float(lp[0, pos - 1, int(best[0, pos])])
    np.testing.assert_allclose(float(scores[0, 0]), total, atol=1e-3, rtol=1e-4)


def test_beam_size_validated():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="beam_size must be >= 1"):
        beam_search(params, config, jnp.zeros((1, 4), jnp.int32), beam_size=0)


def test_moe_config_rejected():
    config = dataclasses.replace(cfg(), n_experts=4)
    params = T.init_params(config, jax.random.PRNGKey(0))
    with pytest.raises(NotImplementedError, match="moe_exact"):
        beam_search(params, config, jnp.zeros((1, 4), jnp.int32))


def test_moe_routing_pool_coupling_demonstrated():
    # The RECORDED JUSTIFICATION for the MoE refusal above, as an executable
    # proof rather than a docstring sentence: capacity-based MoE routes all
    # batch rows in one competing pool, so rows with IDENTICAL inputs get
    # different outputs purely by pool position once capacity is exceeded —
    # exactly what would couple sibling beams (a beam's score would depend
    # on which siblings share the batch, breaking score==rescoring).
    #
    # Construction: 16 identical decode rows all want the same top-2
    # experts; capacity_factor=0.25 gives each expert max(8, ...) = 8 slots,
    # so half the rows are dropped to the residual path while the first
    # rows route — same token, same cache, different logits.
    import numpy as np

    config = dataclasses.replace(
        cfg(), n_experts=4, moe_capacity_factor=0.25, n_kv_heads=2
    )
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0, config.vocab_size)
    _, (k_pre, v_pre) = T.forward(params, prompt, config, return_kv=True)
    tok = jnp.full((1, 1), 7, jnp.int32)

    solo_cache = T.init_decode_cache(config, 1, 8, k_pre, v_pre)
    lg_solo, _ = T.decode_step(params, tok, jnp.int32(5), solo_cache, config)

    W = 16
    pool_cache = jax.tree.map(
        lambda x: jnp.repeat(x, W, axis=1),
        T.init_decode_cache(config, 1, 8, k_pre, v_pre),
    )
    lg_pool, _ = T.decode_step(
        params, jnp.tile(tok, (W, 1)), jnp.int32(5), pool_cache, config
    )
    per_row_dev = np.asarray(
        jnp.max(jnp.abs(lg_pool - lg_solo[0]), axis=(1, 2))
    )
    # some row must diverge from its own solo decode (dropped routing) —
    # if this ever stops holding, the refusal in beam_search (and
    # speculative_generate) should be revisited
    assert per_row_dev.max() > 1e-3, per_row_dev
    # and the divergence is positional, not noise: identical inputs gave
    # unequal outputs WITHIN one batch
    row_spread = float(jnp.max(jnp.abs(lg_pool - lg_pool[:1])))
    assert row_spread > 1e-3, row_spread


def test_zero_max_new_tokens_rejected():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="max_new_tokens must be >= 1"):
        beam_search(
            params, config, jnp.zeros((1, 4), jnp.int32), max_new_tokens=0
        )


def test_moe_dropless_beam_accepted_and_beam_one_equals_greedy():
    """moe_dropless removes the sibling-beam coupling (no eviction → per-
    token independent routing): beam search accepts the config and the
    beam=1 ≡ greedy pin holds exactly like the dense case."""
    config = dataclasses.replace(
        T.TransformerConfig.tiny_moe(), moe_dropless=True,
        moe_group_size=1, dtype=jnp.float32
    )
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                config.vocab_size)
    want = T.Transformer(config).generate_cached(params, prompt,
                                                 max_new_tokens=5)
    got = beam_search(params, config, prompt, max_new_tokens=5, beam_size=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # wider beams run too (the guard is fully lifted, not special-cased)
    seqs, scores = beam_search(params, config, prompt, max_new_tokens=3,
                               beam_size=3, return_all=True)
    assert seqs.shape == (2, 3, prompt.shape[1] + 3)
    assert bool(np.all(np.diff(np.asarray(scores), axis=1) <= 1e-6))
