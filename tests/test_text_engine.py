"""Text-level serving (models/text.py): stop strings across token
boundaries, streaming with holdback, and finish-reason semantics — over a
hermetic character tokenizer (the TextEngine contract is a tokenizer
PROTOCOL: encode/decode; HF tokenizers satisfy it, tests don't need
one)."""

import dataclasses

import pytest

import jax

from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)
from bee_code_interpreter_tpu.models.text import TextEngine
from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))


class CharTokenizer:
    """chr/ord with a printable offset: hermetic, prefix-stable."""

    def encode(self, text):
        return [ord(ch) % CFG.vocab_size for ch in text]

    def decode(self, tokens):
        return "".join(chr(32 + (t % 94)) for t in tokens)


def make_text_engine(**kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_pages", 24)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages_per_seq", 8)
    return TextEngine(
        Engine(ContinuousBatcher(PARAMS, CFG, **kw)), CharTokenizer()
    )


PROMPT_TEXT = "hello tpu"


def completion(n=10, **kw):
    te = make_text_engine()
    t = te.submit(PROMPT_TEXT, n, **kw)
    te.run_to_completion()
    return te, t


def test_plain_completion_decodes_all_tokens():
    te, t = completion(8)
    assert len(te.text(t)) == 8
    assert te.finish_reason(t) == "length"


def test_stop_string_truncates_and_frees_pages():
    te_full, t_full = completion(10)
    full = te_full.text(t_full)
    stop = full[4:6]  # chars 5-6 of the greedy completion
    te, t = completion(10, stop=(stop,))
    assert te.text(t) == full[: full.find(stop)]
    assert te.finish_reason(t) == "stop"
    # the underlying request was cancelled: its pages are free again
    assert (te.engine.batcher.page_ref > 0).sum() == 0


def test_multiple_stops_earliest_wins():
    te_full, t_full = completion(10)
    full = te_full.text(t_full)
    early, late = full[2], full[6]
    te, t = completion(10, stop=(late, early))
    cut = min(full.find(early), full.find(late))
    assert te.text(t) == full[:cut]


def test_streaming_equals_text_and_respects_holdback():
    te_full, t_full = completion(10)
    full = te_full.text(t_full)
    stop = full[5:8]  # 3-char stop -> holdback 2
    te = make_text_engine()
    t = te.submit(PROMPT_TEXT, 10, stop=(stop,))
    streamed = ""
    for _ in range(40):
        streamed += te.new_text(t)
        if te.is_done(t):
            break
        # nothing emitted may ever be clawed back by the eventual stop
        assert streamed == full[: len(streamed)]
        te.step()
    streamed += te.new_text(t)
    assert streamed == te.text(t) == full[:5]


def test_stop_inside_prompt_is_not_matched():
    """Stops apply to the COMPLETION, not the prompt text."""
    te = make_text_engine()
    t = te.submit(PROMPT_TEXT, 5, stop=(PROMPT_TEXT[:3],))
    te.run_to_completion()
    # may or may not stop depending on the completion, but it must not
    # be the empty string purely because the PROMPT contained the stop
    reason = te.finish_reason(t)
    assert reason in ("stop", "length")
    if reason == "length":
        assert len(te.text(t)) == 5


def test_sampling_and_engine_kwargs_pass_through():
    te = make_text_engine()
    t = te.submit(
        PROMPT_TEXT, 6,
        sampling=SamplingParams(temperature=0.9, seed=7), priority=2,
    )
    te.run_to_completion()
    first = te.text(t)
    te2 = make_text_engine()
    t2 = te2.submit(
        PROMPT_TEXT, 6, sampling=SamplingParams(temperature=0.9, seed=7)
    )
    te2.run_to_completion()
    assert te2.text(t2) == first  # same seed -> same text


def test_validation():
    te = make_text_engine()
    with pytest.raises(ValueError, match="non-empty"):
        te.submit(PROMPT_TEXT, 5, stop=("",))
    with pytest.raises(TypeError, match="tokenizer"):
        TextEngine(te.engine, object())
    with pytest.raises(KeyError, match="unknown ticket"):
        te.text(999)
    t = te.submit(PROMPT_TEXT, 3)
    with pytest.raises(RuntimeError, match="still generating"):
        te.text(t)


def test_release_keeps_reason_drops_text():
    te, t = completion(8)
    assert te.finish_reason(t) == "length"
    te.release(t)
    assert te.finish_reason(t) == "length"  # recorded, survives release
    with pytest.raises(KeyError):
        te.text(t)
    assert t not in te._final and t not in te._emitted


def test_stop_reason_survives_release_of_cancelled_request():
    te_full, t_full = completion(10)
    full = te_full.text(t_full)
    te, t = completion(10, stop=(full[4:6],))
    assert te.finish_reason(t) == "stop"
    te.release(t)
    assert te.finish_reason(t) == "stop"


class UnstableTailTokenizer(CharTokenizer):
    """Byte-level-BPE-shaped: token 77 is a CONTINUATION — alone at the
    tail it decodes to U+FFFD; followed by any token the pair becomes
    one character. Decodes are prefix-stable except for that tail."""

    def decode(self, tokens):
        out = []
        i = 0
        while i < len(tokens):
            if tokens[i] == 77:
                if i + 1 < len(tokens):
                    out.append("@")  # the completed pair
                    i += 2
                    continue
                out.append("�")  # incomplete at the tail
                i += 1
                continue
            out.append(chr(32 + (tokens[i] % 94)))
            i += 1
        return "".join(out)


def test_streaming_holds_back_unstable_decode_tail():
    """A U+FFFD decode tail (incomplete byte-level sequence) must not be
    streamed: the stream's concatenation equals text() even though the
    tail later re-decodes to a different character."""
    te = TextEngine(
        Engine(ContinuousBatcher(PARAMS, CFG, max_batch=1, n_pages=24,
                                 page_size=4, max_pages_per_seq=8)),
        UnstableTailTokenizer(),
    )
    t = te.submit(PROMPT_TEXT, 8)
    streamed = ""
    for _ in range(40):
        streamed += te.new_text(t)
        assert "�" not in streamed  # never emit a torn character
        if te.is_done(t):
            break
        te.step()
    streamed += te.new_text(t)
    assert streamed == te.text(t)


def test_is_done_and_text_survive_release():
    """A poller on a released ticket must not spin: is_done stays True
    after release (keyed on the retained reason), and text() names the
    release instead of claiming the ticket is unknown."""
    te, t = completion(8)
    te.release(t)
    assert te.is_done(t)  # done-flag survives release
    with pytest.raises(KeyError, match="released"):
        te.text(t)
    with pytest.raises(KeyError, match="released"):
        te.new_text(t)
    assert not te.is_done(999_999)  # truly unknown stays not-done
