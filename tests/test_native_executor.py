"""Drives the native C++ executor server end-to-end over its wire contract,
including full control-plane interop (KubernetesCodeExecutor with fake kubectl
pointing pods at real native-server processes)."""

import asyncio
import json
import socket
import subprocess
import sys
import time
from pathlib import Path

import httpx
import pytest

from bee_code_interpreter_tpu.services.native_process_code_executor import (
    _free_port as free_port,
)

REPO = Path(__file__).resolve().parent.parent
EXECUTOR_DIR = REPO / "executor"
BINARY = EXECUTOR_DIR / "build" / "executor-server"


@pytest.fixture(autouse=True)
def _require_native(native_binary):
    # native_binary (shared session fixture) builds the server exactly once.
    if native_binary is None:
        pytest.skip("native toolchain unavailable")


class NativeExecutor:
    def __init__(
        self,
        workspace: Path,
        ip: str = "127.0.0.1",
        port: int | None = None,
        extra_env: dict[str, str] | None = None,
    ):
        self.ip = ip
        self.port = port or free_port()
        self.workspace = workspace
        self.proc = subprocess.Popen(
            [str(BINARY)],
            env={
                "PATH": "/usr/local/bin:/usr/bin:/bin",
                "APP_LISTEN_ADDR": f"{ip}:{self.port}",
                "APP_WORKSPACE": str(workspace),
                "APP_DISABLE_DEP_INSTALL": "1",
                "APP_PYPI_MAP": str(EXECUTOR_DIR / "pypi_map.tsv"),
                **(extra_env or {}),
            },
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        self.base = f"http://{ip}:{self.port}"
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                if httpx.get(self.base + "/healthz", timeout=1).status_code == 200:
                    return
            except httpx.HTTPError:
                time.sleep(0.05)
        raise RuntimeError("native executor did not become healthy")

    def stop(self):
        self.proc.kill()
        self.proc.wait()


@pytest.fixture
def native(tmp_path):
    server = NativeExecutor(tmp_path / "ws")
    yield server
    server.stop()


def test_healthz(native):
    body = httpx.get(native.base + "/healthz").json()
    assert body["status"] == "ok"
    # "warm" reports whether the pre-started worker finished preloading;
    # it flips true (and stays true) within the preload budget
    assert isinstance(body["warm"], bool)
    deadline = time.time() + 30
    while not httpx.get(native.base + "/healthz").json()["warm"]:
        assert time.time() < deadline, "worker never reported warm"
        time.sleep(0.1)


def strip_diagnostics(response: dict) -> dict:
    """Drop additive diagnostic fields, asserting their shape; what remains is
    the reference wire contract and is compared exactly."""
    duration = response.pop("duration_ms")
    assert isinstance(duration, (int, float)) and duration >= 0
    return response


def test_execute_basic(native):
    r = httpx.post(
        native.base + "/execute", json={"source_code": "print(21 * 2)"}
    ).json()
    assert strip_diagnostics(r) == {
        "stdout": "42\n", "stderr": "", "exit_code": 0, "files": [],
    }


def test_upload_execute_download_roundtrip(native):
    data = bytes(range(256)) * 100
    assert (
        httpx.put(native.base + "/workspace/sub/in.bin", content=data).status_code
        == 204
    )
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": "raw = open('sub/in.bin','rb').read()\n"
            "open('out.bin','wb').write(raw[::-1])"
        },
    ).json()
    assert r["exit_code"] == 0
    assert r["files"] == ["/workspace/out.bin"]
    out = httpx.get(native.base + "/workspace/out.bin")
    assert out.content == data[::-1]


def test_env_and_unicode(native):
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": "import os\nprint(os.environ['GREETING'])",
            "env": {"GREETING": "héllo ✓ wörld"},
        },
    ).json()
    assert r["stdout"] == "héllo ✓ wörld\n"


def test_timeout_kills_group(native):
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": "import subprocess, sys, time\n"
            "subprocess.Popen([sys.executable, '-c', 'import time; time.sleep(60)'])\n"
            "time.sleep(60)",
            "timeout": 1,
        },
        timeout=30,
    ).json()
    assert r["exit_code"] == -1
    assert r["stderr"] == "Execution timed out"


def test_path_escape_rejected(native):
    # raw socket: clients like httpx normalize "..", the server must not rely on that
    with socket.create_connection((native.ip, native.port)) as sock:
        sock.sendall(
            b"PUT /workspace/../../etc/evil HTTP/1.1\r\n"
            b"Host: x\r\nContent-Length: 1\r\nConnection: close\r\n\r\nx"
        )
        status = b""
        while chunk := sock.recv(4096):
            status += chunk
    assert b"400" in status.split(b"\r\n", 1)[0]
    # encoded traversal through a real client
    assert (
        httpx.put(
            native.base + "/workspace/%2e%2e/%2e%2e/etc/evil2", content=b"x"
        ).status_code
        == 400
    )


def test_download_missing_404(native):
    assert httpx.get(native.base + "/workspace/nope.txt").status_code == 404


def test_crash_propagates_exit_code(native):
    r = httpx.post(
        native.base + "/execute", json={"source_code": "raise SystemExit(9)"}
    ).json()
    assert r["exit_code"] == 9


async def test_chunked_streaming_upload(native):
    # the control plane streams uploads with an async generator => chunked
    # transfer-encoding; the native server must decode it
    async def body():
        for i in range(64):
            yield bytes([i]) * 1024

    async with httpx.AsyncClient() as client:
        resp = await client.put(native.base + "/workspace/chunked.bin", content=body())
        assert resp.status_code == 204
    r = httpx.post(
        native.base + "/execute",
        json={"source_code": "import os\nprint(os.path.getsize('chunked.bin'))"},
    ).json()
    assert r["stdout"] == f"{64 * 1024}\n"


async def test_control_plane_against_native_pods(tmp_path, storage):
    """KubernetesCodeExecutor drives real native-server 'pods' (distinct
    loopback IPs, one shared port) through the full upload/execute/download
    flow — the reference's boundary (c) (SURVEY.md §3.5) with our C++ server."""
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
        KubernetesCodeExecutor,
    )
    from tests.fakes import FakeKubectl

    port = free_port()
    servers: list[NativeExecutor] = []

    class NativeBackend:
        port_ = port

        def __init__(self):
            self.port = port
            self._next = 1

        async def start_pod(self, manifest=None) -> str:
            ip = f"127.1.1.{self._next}"
            self._next += 1
            server = await asyncio.to_thread(
                NativeExecutor, tmp_path / f"pod-{self._next}", ip, port
            )
            servers.append(server)
            return ip

    config = Config(
        executor_backend="kubernetes",
        executor_port=port,
        executor_pod_queue_target_length=1,
        tpu_hosts_per_slice=2,
    )
    executor = KubernetesCodeExecutor(
        kubectl=FakeKubectl(NativeBackend()), storage=storage, config=config
    )
    try:
        r1 = await executor.execute("open('state.json','w').write('{\"n\": 1}')")
        assert r1.exit_code == 0
        assert set(r1.files) == {"/workspace/state.json"}
        r2 = await executor.execute(
            "import json\nprint(json.load(open('state.json'))['n'] + 1)",
            files=r1.files,
        )
        assert r2.stdout == "2\n"
    finally:
        for s in servers:
            s.stop()


def test_warm_worker_traceback_matches_plain_python(native):
    # The pre-started interpreter's bootstrap frame must never appear in user
    # tracebacks — errors render exactly as `python script.py` would.
    r = httpx.post(
        native.base + "/execute",
        json={"source_code": "def boom():\n    raise ValueError('xyz')\nboom()"},
    ).json()
    assert r["exit_code"] == 1
    assert "ValueError: xyz" in r["stderr"]
    assert 'File "<string>"' not in r["stderr"]
    assert "bootstrap" not in r["stderr"]
    # frames point at the script, like plain python
    assert 'in boom' in r["stderr"]


def test_consecutive_executes_after_warm_worker_consumed(native):
    # Request 1 consumes the pre-started worker; request 2 must fall back to
    # a cold interpreter with identical semantics (sandboxes are single-use
    # in production, but the server itself must not require that).
    for expected in ("first", "second", "third"):
        r = httpx.post(
            native.base + "/execute",
            json={"source_code": f"print('{expected}')"},
        ).json()
        assert strip_diagnostics(r) == {
            "stdout": f"{expected}\n", "stderr": "", "exit_code": 0, "files": [],
        }


def test_prestart_disabled_parity(tmp_path):
    server = NativeExecutor(tmp_path / "ws", extra_env={"APP_PRESTART": "0"})
    try:
        r = httpx.post(
            server.base + "/execute",
            json={
                "source_code": "import os\nprint(os.environ['X'], 21 * 2)",
                "env": {"X": "y"},
            },
        ).json()
        assert strip_diagnostics(r) == {
            "stdout": "y 42\n", "stderr": "", "exit_code": 0, "files": [],
        }
    finally:
        server.stop()


def test_warm_worker_timeout_kill(native):
    # Timeout enforcement must hold on the pre-started worker path too
    # (process-group SIGKILL reaches grandchildren).
    t0 = time.time()
    r = httpx.post(
        native.base + "/execute",
        json={"source_code": "import time\ntime.sleep(60)", "timeout": 1.0},
        timeout=30,
    ).json()
    assert r["exit_code"] == -1
    assert r["stderr"] == "Execution timed out"
    assert time.time() - t0 < 20


def test_warm_worker_request_pythonpath(native, tmp_path):
    # Request-env PYTHONPATH must reach imports on the warm path too, even
    # though the interpreter started before the request arrived.
    lib = tmp_path / "lib"
    lib.mkdir()
    (lib / "reqmod.py").write_text("VALUE = 'from-request-path'\n")
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": "import reqmod\nprint(reqmod.VALUE)",
            "env": {"PYTHONPATH": str(lib)},
        },
    ).json()
    assert r["stdout"] == "from-request-path\n", r["stderr"]


def test_workspace_import_parity_warm_vs_cold(native):
    # `python script.py` does NOT put the workspace on sys.path (the script
    # lives in a tempdir); the warm-worker path must behave identically, so
    # `import helper` fails the same way on request 1 (warm) and 2 (cold).
    httpx.put(native.base + "/workspace/helper.py", content=b"VALUE = 1\n")
    results = [
        httpx.post(
            native.base + "/execute", json={"source_code": "import helper"}
        ).json()
        for _ in range(2)
    ]
    for r in results:
        assert r["exit_code"] == 1
        assert "ModuleNotFoundError" in r["stderr"]


def test_prestart_imports_env_reaches_worker(tmp_path):
    # APP_PRESTART_IMPORTS must actually reach the warm worker; a module with
    # an import-time side effect proves it ran at preload, and its noise is
    # muted out of the request's captured output.
    lib = tmp_path / "lib"
    lib.mkdir()
    (lib / "preloadmark.py").write_text(
        "import sys\nsys._preloaded_mark = True\nprint('preload noise')\n"
    )
    server = NativeExecutor(
        tmp_path / "ws",
        extra_env={
            "APP_PRESTART_IMPORTS": "preloadmark",
            "PYTHONPATH": str(lib),
        },
    )
    try:
        r = httpx.post(
            server.base + "/execute",
            json={
                "source_code": "import sys\n"
                "print(getattr(sys, '_preloaded_mark', False))"
            },
        ).json()
        assert r["stdout"] == "True\n", r
        assert "preload noise" not in r["stdout"]
        assert r["stderr"] == ""
    finally:
        server.stop()


def test_tpu_warm_preload_initializes_backend(tmp_path):
    # bci_tpu_warm in APP_PRESTART_IMPORTS brings the XLA backend up inside
    # the warm worker before the request arrives (CPU backend here; the TPU
    # image points it at the pod's chips). The executed code proves both that
    # the preload ran (module already in sys.modules) and that the backend
    # was initialized ahead of user code.
    server = NativeExecutor(
        tmp_path / "ws",
        extra_env={
            "APP_PYTHON": sys.executable,  # the interpreter that has jax
            "APP_PRESTART_IMPORTS": "numpy,bci_tpu_warm",
            "APP_SHIM_DIR": str(
                REPO / "bee_code_interpreter_tpu" / "runtime" / "shim"
            ),
            "HOME": str(tmp_path),
            "JAX_PLATFORMS": "cpu",
        },
    )
    try:
        r = httpx.post(
            server.base + "/execute",
            json={
                "source_code": (
                    "import sys\n"
                    "print('bci_tpu_warm' in sys.modules)\n"
                    "import jax\n"
                    "from jax._src import xla_bridge\n"
                    "print(bool(xla_bridge._backends))\n"
                    "print(jax.devices()[0].platform)"
                ),
                "timeout": 120,
            },
            timeout=130,
        ).json()
        assert r["stdout"] == "True\nTrue\ncpu\n", (r["stdout"], r["stderr"][-400:])
    finally:
        server.stop()


def test_hung_preload_falls_back_cold(tmp_path):
    # A preload that never finishes (unreachable accelerator) must not turn
    # every request into an execution timeout: the guard kills the worker at
    # the deadline and the request runs on the cold path instead.
    lib = tmp_path / "lib"
    lib.mkdir()
    (lib / "hangmod.py").write_text("import time\ntime.sleep(3600)\n")
    server = NativeExecutor(
        tmp_path / "ws",
        extra_env={
            "APP_PRESTART_IMPORTS": "hangmod",
            "APP_PRESTART_PRELOAD_TIMEOUT_S": "1",
            "PYTHONPATH": str(lib),
        },
    )
    try:
        t0 = time.time()
        r = httpx.post(
            server.base + "/execute",
            json={"source_code": "print('survived')", "timeout": 30},
            timeout=60,
        ).json()
        assert r["stdout"] == "survived\n", r
        assert r["exit_code"] == 0
        assert time.time() - t0 < 25
    finally:
        server.stop()


def test_hung_preload_mid_request_falls_back_cold(tmp_path):
    # The harder variant: the request is handed to the worker BEFORE the
    # preload guard fires. The started-byte protocol tells the server user
    # code never ran, so the cold retry is safe, bounded by the remaining
    # request budget.
    lib = tmp_path / "lib"
    lib.mkdir()
    (lib / "hangmod2.py").write_text("import time\ntime.sleep(3600)\n")
    server = NativeExecutor(
        tmp_path / "ws",
        extra_env={
            "APP_PRESTART_IMPORTS": "hangmod2",
            "APP_PRESTART_PRELOAD_TIMEOUT_S": "6",
            "PYTHONPATH": str(lib),
        },
    )
    try:
        t0 = time.time()
        r = httpx.post(
            server.base + "/execute",
            json={"source_code": "print('survived-midflight')", "timeout": 30},
            timeout=60,
        ).json()
        elapsed = time.time() - t0
        assert r["stdout"] == "survived-midflight\n", r
        assert r["exit_code"] == 0
        # waited out the guard (~6s from server start), then ran cold
        assert elapsed < 25, elapsed
    finally:
        server.stop()


def test_warm_path_request_env_optout_deproxies_numpy(tmp_path):
    # ADVICE round 1 (medium): the warm worker preloads numpy — installing the
    # reroute proxies — before the request env exists. A request opting out
    # via BCI_XLA_REROUTE=0 must still get a fully de-proxied numpy (the
    # bootstrap uninstalls after applying the request env).
    server = NativeExecutor(
        tmp_path / "ws",
        extra_env={
            "APP_PYTHON": sys.executable,
            "APP_PRESTART_IMPORTS": "numpy",
            "APP_SHIM_DIR": str(
                REPO / "bee_code_interpreter_tpu" / "runtime" / "shim"
            ),
            "HOME": str(tmp_path),
            "JAX_PLATFORMS": "cpu",
        },
    )
    try:
        r = httpx.post(
            server.base + "/execute",
            json={
                "source_code": (
                    "import sys\n"
                    "assert 'numpy' in sys.modules  # proves warm path\n"
                    "import numpy as np\n"
                    "print(bool(getattr(np, '__bci_xla_rerouted__', False)))\n"
                    "print(type(np.random.rand(2_000_000)).__name__)\n"
                ),
                "env": {"BCI_XLA_REROUTE": "0"},
                "timeout": 60,
            },
            timeout=70,
        ).json()
        assert r["stdout"] == "False\nndarray\n", (r["stdout"], r["stderr"][-500:])
        assert r["exit_code"] == 0
    finally:
        server.stop()


def test_warm_path_pythonpath_ordering_matches_cold(tmp_path):
    # ADVICE round 1 (low): a request-supplied PYTHONPATH entry must resolve
    # in the same relative position warm and cold: [script_dir, shim,
    # request paths...]. A request path shadowing a shim-visible module name
    # must NOT win over the shim on the warm path.
    req_lib = tmp_path / "reqlib"
    req_lib.mkdir()
    shim = str(REPO / "bee_code_interpreter_tpu" / "runtime" / "shim")
    probe = (
        "import sys\n"
        f"shim_i = sys.path.index({shim!r})\n"
        f"req_i = sys.path.index({str(req_lib)!r})\n"
        "print(shim_i < req_i)\n"
    )
    for prestart in ("1", "0"):
        server = NativeExecutor(
            tmp_path / f"ws-{prestart}",
            extra_env={
                "APP_PYTHON": sys.executable,
                "APP_PRESTART": prestart,
                "APP_PRESTART_IMPORTS": "numpy",
                "APP_SHIM_DIR": shim,
                "HOME": str(tmp_path),
                "JAX_PLATFORMS": "cpu",
            },
        )
        try:
            r = httpx.post(
                server.base + "/execute",
                json={
                    "source_code": probe,
                    "env": {"PYTHONPATH": str(req_lib)},
                    "timeout": 60,
                },
                timeout=70,
            ).json()
            assert r["stdout"] == "True\n", (prestart, r["stdout"], r["stderr"][-500:])
        finally:
            server.stop()


async def test_pod_group_runs_cross_process_collective(tmp_path, storage):
    """Full-stack multi-host composition (round-1 weak #7): the gang scheduler
    spawns 2 REAL native-server 'pods', the manifest env it baked in
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) is applied
    to the actual server processes, and the submitted payload brings up
    jax.distributed and runs a cross-process collective. Worker-0 stdout
    proves the 2-process world rendezvoused end-to-end through
    kubernetes_code_executor -> executor server -> sandbox -> parallel.mesh."""
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
        KubernetesCodeExecutor,
    )
    from tests.fakes import FakeKubectl

    port = free_port()
    servers: list[NativeExecutor] = []

    class DistributedNativeBackend:
        """Starts a real executor-server per 'pod', honoring the manifest's
        container env — the exact plumbing the fake-pod tests bypass."""

        def __init__(self):
            self.port = port
            self._next = 1

        async def start_pod(self, manifest=None) -> str:
            ip = f"127.1.2.{self._next}"
            self._next += 1
            manifest_env = {
                e["name"]: e["value"]
                for e in (manifest or {"spec": {"containers": [{"env": []}]}})[
                    "spec"
                ]["containers"][0]["env"]
                if not e["name"].startswith("APP_")
            }
            server = await asyncio.to_thread(
                NativeExecutor,
                tmp_path / f"dpod-{self._next}",
                ip,
                port,
                {
                    "APP_PYTHON": sys.executable,
                    "APP_PRESTART": "0",  # collectives need fresh env per run
                    "HOME": str(tmp_path),
                    "PYTHONPATH": str(REPO),
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                    **manifest_env,
                },
            )
            servers.append(server)
            return ip

    config = Config(
        executor_backend="kubernetes",
        executor_port=port,
        executor_pod_queue_target_length=1,
        tpu_hosts_per_slice=2,
        execution_timeout_s=120.0,
    )
    executor = KubernetesCodeExecutor(
        kubectl=FakeKubectl(DistributedNativeBackend()),
        storage=storage,
        config=config,
    )
    payload = (
        "import jax\n"
        "from bee_code_interpreter_tpu.parallel import initialize_distributed\n"
        "assert initialize_distributed(), 'pod-group env missing'\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "import numpy as np\n"
        "from jax.experimental import multihost_utils\n"
        "g = multihost_utils.process_allgather(np.array([jax.process_index()]))\n"
        "print('GANG', sorted(int(x) for x in np.asarray(g).ravel()))\n"
    )
    try:
        result = await executor.execute(payload)
        if "Multiprocess computations aren't implemented" in result.stderr:
            # The 2-process world DID rendezvous (initialize_distributed and
            # process_count()==2 passed before this point in the payload);
            # this jax build's CPU backend just can't run the collective math.
            pytest.skip("jax CPU backend lacks multiprocess collectives")
        assert result.exit_code == 0, result.stderr[-800:]
        # jax's CPU collective backend (gloo) logs a connection banner to
        # stdout; the line that matters proves both processes contributed.
        assert "GANG [0, 1]" in result.stdout, result.stdout
    finally:
        for s in servers:
            s.stop()


def test_guess_cli_matches_python_oracle(tmp_path):
    # The native guesser and the Python oracle must agree — including on
    # namespace packages, where first-dot truncation used to make every
    # google.* map row unreachable (ADVICE r2).
    from bee_code_interpreter_tpu.runtime.dep_guess import guess_dependencies

    sources = [
        "import numpy\nimport cv2\nfrom PIL import Image\nimport cowsay\n",
        "import google.protobuf\nfrom google.protobuf import json_format\n",
        "from google.cloud import storage, bigquery\nimport google\n",
        "from google import auth\nimport google.generativeai as genai\n",
        "import yaml, requests\nfrom bs4 import BeautifulSoup\n",
        "from google.cloud import (storage, bigquery)\n",
        "from google.cloud import (storage)\n",
        "from google.cloud import (\n    storage,\n    bigquery,\n)\n",
        # an unbalanced '(' inside a string literal must not swallow the
        # genuine import on the next line
        'print("to import, call f(x")\nimport numpy\n',
        "from numpy import(array)\n",  # no space after import
    ]
    stdlib_file = tmp_path / "stdlib_names.txt"
    stdlib_file.write_text("\n".join(sorted(sys.stdlib_module_names)) + "\n")
    for source in sources:
        out = subprocess.run(
            [str(BINARY), "--guess"],
            input=source,
            capture_output=True,
            text=True,
            timeout=30,
            env={
                "PATH": "/usr/local/bin:/usr/bin:/bin",
                "APP_PYPI_MAP": str(EXECUTOR_DIR / "pypi_map.tsv"),
                "APP_STDLIB_FILE": str(stdlib_file),
                "APP_PRESTART": "0",
                "APP_WORKSPACE": str(tmp_path / "ws"),
            },
        )
        assert out.returncode == 0, out.stderr
        native_deps = [l for l in out.stdout.splitlines() if l]
        assert native_deps == guess_dependencies(source), source


def test_warm_exit_report_flushes_unclosed_files(native):
    # The warm worker reports its exit code before interpreter finalization;
    # a module-global file handle user code never closed must still have its
    # buffered bytes on disk when the server snapshots the workspace.
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": (
                "f = open('left-open.txt', 'w')\n"
                "f.write('buffered data that only finalization would flush')\n"
            )
        },
    ).json()
    assert r["exit_code"] == 0
    assert r["files"] == ["/workspace/left-open.txt"]
    body = httpx.get(native.base + "/workspace/left-open.txt")
    assert body.text == "buffered data that only finalization would flush"


def test_stdio_closed_payload_still_bounded_by_timeout(native):
    # User code that closes its own stdout/stderr EOFs both pipes instantly;
    # the server must still enforce the execution timeout instead of blocking
    # forever on the reap (review r3 finding).
    t0 = time.time()
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": (
                "import os, time\n"
                "os.close(1)\nos.close(2)\n"
                "time.sleep(60)\n"
            ),
            "timeout": 2,
        },
        timeout=30,
    ).json()
    assert r["exit_code"] == -1
    assert r["stderr"] == "Execution timed out"
    assert time.time() - t0 < 15


def test_os_exit_payload_reports_real_code(native):
    # os._exit skips atexit (no exit-code report line); the fallback reap
    # must still return the real code promptly.
    r = httpx.post(
        native.base + "/execute",
        json={"source_code": "import os\nos._exit(5)"},
        timeout=30,
    ).json()
    assert r["exit_code"] == 5


def test_request_accelerator_scrub_native(tmp_path):
    # BCI_SCRUB_ACCELERATOR=1 drops tunnel-plugin vars in the native server
    # too — on the warm path (bootstrap scrub) and the cold path (base_env).
    probe = (
        "import os\n"
        "print(sorted(k for k in os.environ"
        " if k.startswith(('PALLAS_', 'AXON_'))))\n"
    )
    server = NativeExecutor(
        tmp_path / "ws",
        extra_env={"PALLAS_TUNNEL_TARGET": "grpc://wedged:1", "AXON_POOL_KEY": "x"},
    )
    try:
        # hermetic requests always run cold (base_env scrub) and do NOT
        # consume the pre-started worker
        for _ in range(2):
            r = httpx.post(
                server.base + "/execute",
                json={"source_code": probe, "env": {"BCI_SCRUB_ACCELERATOR": "1"}},
                timeout=60,
            ).json()
            assert r["stdout"] == "[]\n", r
        # without the opt-out the vars pass through — and this request is
        # served by the warm worker the hermetic probes left untouched
        r3 = httpx.post(
            server.base + "/execute", json={"source_code": probe}, timeout=60
        ).json()
        assert "PALLAS_TUNNEL_TARGET" in r3["stdout"], r3
    finally:
        server.stop()


def _vm_hwm_kib(pid: int) -> int:
    """Peak resident set (VmHWM) of a process, in KiB."""
    for line in Path(f"/proc/{pid}/status").read_text().splitlines():
        if line.startswith("VmHWM:"):
            return int(line.split()[1])
    raise RuntimeError("VmHWM not found")


def test_large_upload_streams_to_disk_constant_memory(native):
    """A 128 MiB PUT must not cost its size in server memory: the body
    streams to a part-file as it arrives and publishes by atomic rename
    (parity with the reference's chunked-to-disk uploads, server.rs:83-86).
    The old buffer-then-write path would push VmHWM past the body size."""
    size = 128 * 1024 * 1024
    chunk = bytes(range(256)) * 256  # 64 KiB pattern

    def body():
        sent = 0
        while sent < size:
            yield chunk
            sent += len(chunk)

    resp = httpx.put(
        native.base + "/workspace/big.bin", content=body(), timeout=120
    )
    assert resp.status_code == 204
    target = native.workspace / "big.bin"
    assert target.stat().st_size == size
    # spot-check content round-trips (first + last chunk via ranges on disk)
    with open(target, "rb") as f:
        assert f.read(len(chunk)) == chunk
        f.seek(size - len(chunk))
        assert f.read() == chunk
    # no torn part-files left behind
    assert [p.name for p in native.workspace.iterdir()] == ["big.bin"]
    hwm_mib = _vm_hwm_kib(native.proc.pid) / 1024
    assert hwm_mib < 96, (
        f"server peak RSS {hwm_mib:.0f} MiB for a 128 MiB upload — "
        "body appears to be buffered in memory, not streamed"
    )


def test_content_length_upload_also_streams(native):
    """The non-chunked (Content-Length) path streams too."""
    size = 96 * 1024 * 1024
    data = b"\xab" * size
    resp = httpx.put(
        native.base + "/workspace/len.bin", content=data, timeout=120
    )
    assert resp.status_code == 204
    assert (native.workspace / "len.bin").stat().st_size == size
    hwm_mib = _vm_hwm_kib(native.proc.pid) / 1024
    assert hwm_mib < 72, f"peak RSS {hwm_mib:.0f} MiB — not streamed"


def test_streamed_upload_overwrites_existing_file(native):
    httpx.put(native.base + "/workspace/f.txt", content=b"old contents")
    httpx.put(native.base + "/workspace/f.txt", content=b"new")
    assert (native.workspace / "f.txt").read_bytes() == b"new"


def test_guess_parity_over_the_full_map(tmp_path):
    """The C++ guesser and the Python oracle must agree on EVERY entry in
    pypi_map.tsv — one synthetic source importing all of them (dotted
    namespace keys included) swept through both implementations."""
    from bee_code_interpreter_tpu.runtime.dep_guess import (
        PYPI_MAP,
        guess_dependencies,
    )

    source = "".join(f"import {name}\n" for name in sorted(PYPI_MAP))
    stdlib_file = tmp_path / "stdlib_names.txt"
    stdlib_file.write_text("\n".join(sorted(sys.stdlib_module_names)) + "\n")
    out = subprocess.run(
        [str(BINARY), "--guess"],
        input=source,
        capture_output=True,
        text=True,
        timeout=60,
        env={
            "PATH": "/usr/local/bin:/usr/bin:/bin",
            "APP_PYPI_MAP": str(EXECUTOR_DIR / "pypi_map.tsv"),
            "APP_STDLIB_FILE": str(stdlib_file),
            "APP_PRESTART": "0",
            "APP_WORKSPACE": str(tmp_path / "ws"),
        },
    )
    assert out.returncode == 0, out.stderr
    native_deps = [l for l in out.stdout.splitlines() if l]
    oracle_deps = guess_dependencies(source)
    assert native_deps == oracle_deps
    # the sweep is not vacuous: nearly the whole map must surface (only
    # SKIP-guarded accelerator aliases drop out)
    assert len(oracle_deps) > len(PYPI_MAP) * 0.9


def test_guess_parity_on_azure_namespace(tmp_path):
    from bee_code_interpreter_tpu.runtime.dep_guess import guess_dependencies

    source = (
        "import azure\n"
        "from azure.identity import DefaultAzureCredential\n"
        "from azure.storage.blob import BlobServiceClient\n"
        "from azure.keyvault.secrets import SecretClient\n"
        "import azure.mgmt.compute\n"
        "import azure.cosmos\n"
    )
    stdlib_file = tmp_path / "stdlib_names.txt"
    stdlib_file.write_text("\n".join(sorted(sys.stdlib_module_names)) + "\n")
    out = subprocess.run(
        [str(BINARY), "--guess"],
        input=source,
        capture_output=True,
        text=True,
        timeout=30,
        env={
            "PATH": "/usr/local/bin:/usr/bin:/bin",
            "APP_PYPI_MAP": str(EXECUTOR_DIR / "pypi_map.tsv"),
            "APP_STDLIB_FILE": str(stdlib_file),
            "APP_PRESTART": "0",
            "APP_WORKSPACE": str(tmp_path / "ws"),
        },
    )
    assert out.returncode == 0, out.stderr
    native_deps = [l for l in out.stdout.splitlines() if l]
    assert native_deps == guess_dependencies(source) == [
        "azure-cosmos", "azure-identity", "azure-keyvault-secrets",
        "azure-mgmt-compute", "azure-storage-blob",
    ]


def _raw_http(native, payload: bytes, recv_bytes: int = 4096) -> bytes:
    with socket.create_connection((native.ip, native.port), timeout=10) as s:
        s.sendall(payload)
        s.settimeout(10)
        out = b""
        try:
            while len(out) < recv_bytes:
                chunk = s.recv(4096)
                if not chunk:
                    break
                out += chunk
        except (socket.timeout, ConnectionResetError, BrokenPipeError):
            pass  # dropping a hostile connection (even mid-send) is legal
        return out


def test_malformed_requests_do_not_kill_the_server(native):
    """Parser hostility battery: garbage request lines, absurd and
    non-numeric Content-Length, garbage chunk-size lines, oversized
    headers. Each must at worst drop that connection — the server (a
    detached-thread-per-connection design where an escaped exception
    would abort the whole process) stays healthy throughout."""
    cases = [
        b"NONSENSE\r\n\r\n",                                  # no method/path
        b"GET /healthz HTTP/1.1\r\nContent-Length: banana\r\n\r\n",
        b"POST /execute HTTP/1.1\r\nContent-Length: 99999999999999999999\r\n\r\n",
        b"POST /execute HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\nZZZ\r\n",
        b"PUT /workspace/x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n-5\r\n",
        b"GET /" + b"A" * (2 << 20) + b" HTTP/1.1\r\n\r\n",   # header flood
        b"POST /execute HTTP/1.1\r\nContent-Length: 10\r\n\r\nshort",  # truncated
    ]
    for payload in cases:
        _raw_http(native, payload)
        # server must still answer a well-formed request afterwards
        r = httpx.get(native.base + "/healthz", timeout=5)
        assert r.status_code == 200, payload[:40]


def test_keepalive_pipelined_requests(native):
    """Two requests on one connection (keep-alive): both answered, bytes
    carried over between requests parse correctly."""
    req = (
        b"GET /healthz HTTP/1.1\r\nHost: x\r\n\r\n"
        b"GET /healthz HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
    )
    out = _raw_http(native, req, recv_bytes=1 << 16)
    assert out.count(b"HTTP/1.1 200") == 2


def test_streamed_upload_interrupted_leaves_no_part_file(native):
    """A client that dies mid-upload must not leave a torn part-file (or a
    phantom destination) in the workspace."""
    with socket.create_connection((native.ip, native.port), timeout=10) as s:
        s.sendall(
            b"PUT /workspace/torn.bin HTTP/1.1\r\n"
            b"Content-Length: 1000000\r\n\r\n" + b"x" * 1000
        )
        # abandon the connection with 999000 bytes owed
    deadline = time.time() + 5
    while time.time() < deadline:
        leftovers = list(native.workspace.iterdir())
        if not leftovers:
            break
        time.sleep(0.1)
    assert list(native.workspace.iterdir()) == []
    assert httpx.get(native.base + "/healthz").status_code == 200
