"""Drives the native C++ executor server end-to-end over its wire contract,
including full control-plane interop (KubernetesCodeExecutor with fake kubectl
pointing pods at real native-server processes)."""

import asyncio
import json
import socket
import subprocess
import time
from pathlib import Path

import httpx
import pytest

from bee_code_interpreter_tpu.services.native_process_code_executor import (
    _free_port as free_port,
)

REPO = Path(__file__).resolve().parent.parent
EXECUTOR_DIR = REPO / "executor"
BINARY = EXECUTOR_DIR / "build" / "executor-server"


@pytest.fixture(autouse=True)
def _require_native(native_binary):
    # native_binary (shared session fixture) builds the server exactly once.
    if native_binary is None:
        pytest.skip("native toolchain unavailable")


class NativeExecutor:
    def __init__(self, workspace: Path, ip: str = "127.0.0.1", port: int | None = None):
        self.ip = ip
        self.port = port or free_port()
        self.workspace = workspace
        self.proc = subprocess.Popen(
            [str(BINARY)],
            env={
                "PATH": "/usr/local/bin:/usr/bin:/bin",
                "APP_LISTEN_ADDR": f"{ip}:{self.port}",
                "APP_WORKSPACE": str(workspace),
                "APP_DISABLE_DEP_INSTALL": "1",
                "APP_PYPI_MAP": str(EXECUTOR_DIR / "pypi_map.tsv"),
            },
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        self.base = f"http://{ip}:{self.port}"
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                if httpx.get(self.base + "/healthz", timeout=1).status_code == 200:
                    return
            except httpx.HTTPError:
                time.sleep(0.05)
        raise RuntimeError("native executor did not become healthy")

    def stop(self):
        self.proc.kill()
        self.proc.wait()


@pytest.fixture
def native(tmp_path):
    server = NativeExecutor(tmp_path / "ws")
    yield server
    server.stop()


def test_healthz(native):
    assert httpx.get(native.base + "/healthz").json() == {"status": "ok"}


def test_execute_basic(native):
    r = httpx.post(
        native.base + "/execute", json={"source_code": "print(21 * 2)"}
    ).json()
    assert r == {"stdout": "42\n", "stderr": "", "exit_code": 0, "files": []}


def test_upload_execute_download_roundtrip(native):
    data = bytes(range(256)) * 100
    assert (
        httpx.put(native.base + "/workspace/sub/in.bin", content=data).status_code
        == 204
    )
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": "raw = open('sub/in.bin','rb').read()\n"
            "open('out.bin','wb').write(raw[::-1])"
        },
    ).json()
    assert r["exit_code"] == 0
    assert r["files"] == ["/workspace/out.bin"]
    out = httpx.get(native.base + "/workspace/out.bin")
    assert out.content == data[::-1]


def test_env_and_unicode(native):
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": "import os\nprint(os.environ['GREETING'])",
            "env": {"GREETING": "héllo ✓ wörld"},
        },
    ).json()
    assert r["stdout"] == "héllo ✓ wörld\n"


def test_timeout_kills_group(native):
    r = httpx.post(
        native.base + "/execute",
        json={
            "source_code": "import subprocess, sys, time\n"
            "subprocess.Popen([sys.executable, '-c', 'import time; time.sleep(60)'])\n"
            "time.sleep(60)",
            "timeout": 1,
        },
        timeout=30,
    ).json()
    assert r["exit_code"] == -1
    assert r["stderr"] == "Execution timed out"


def test_path_escape_rejected(native):
    # raw socket: clients like httpx normalize "..", the server must not rely on that
    with socket.create_connection((native.ip, native.port)) as sock:
        sock.sendall(
            b"PUT /workspace/../../etc/evil HTTP/1.1\r\n"
            b"Host: x\r\nContent-Length: 1\r\nConnection: close\r\n\r\nx"
        )
        status = b""
        while chunk := sock.recv(4096):
            status += chunk
    assert b"400" in status.split(b"\r\n", 1)[0]
    # encoded traversal through a real client
    assert (
        httpx.put(
            native.base + "/workspace/%2e%2e/%2e%2e/etc/evil2", content=b"x"
        ).status_code
        == 400
    )


def test_download_missing_404(native):
    assert httpx.get(native.base + "/workspace/nope.txt").status_code == 404


def test_crash_propagates_exit_code(native):
    r = httpx.post(
        native.base + "/execute", json={"source_code": "raise SystemExit(9)"}
    ).json()
    assert r["exit_code"] == 9


async def test_chunked_streaming_upload(native):
    # the control plane streams uploads with an async generator => chunked
    # transfer-encoding; the native server must decode it
    async def body():
        for i in range(64):
            yield bytes([i]) * 1024

    async with httpx.AsyncClient() as client:
        resp = await client.put(native.base + "/workspace/chunked.bin", content=body())
        assert resp.status_code == 204
    r = httpx.post(
        native.base + "/execute",
        json={"source_code": "import os\nprint(os.path.getsize('chunked.bin'))"},
    ).json()
    assert r["stdout"] == f"{64 * 1024}\n"


async def test_control_plane_against_native_pods(tmp_path, storage):
    """KubernetesCodeExecutor drives real native-server 'pods' (distinct
    loopback IPs, one shared port) through the full upload/execute/download
    flow — the reference's boundary (c) (SURVEY.md §3.5) with our C++ server."""
    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
        KubernetesCodeExecutor,
    )
    from tests.fakes import FakeKubectl

    port = free_port()
    servers: list[NativeExecutor] = []

    class NativeBackend:
        port_ = port

        def __init__(self):
            self.port = port
            self._next = 1

        async def start_pod(self) -> str:
            ip = f"127.1.1.{self._next}"
            self._next += 1
            server = await asyncio.to_thread(
                NativeExecutor, tmp_path / f"pod-{self._next}", ip, port
            )
            servers.append(server)
            return ip

    config = Config(
        executor_backend="kubernetes",
        executor_port=port,
        executor_pod_queue_target_length=1,
        tpu_hosts_per_slice=2,
    )
    executor = KubernetesCodeExecutor(
        kubectl=FakeKubectl(NativeBackend()), storage=storage, config=config
    )
    try:
        r1 = await executor.execute("open('state.json','w').write('{\"n\": 1}')")
        assert r1.exit_code == 0
        assert set(r1.files) == {"/workspace/state.json"}
        r2 = await executor.execute(
            "import json\nprint(json.load(open('state.json'))['n'] + 1)",
            files=r1.files,
        )
        assert r2.stdout == "2\n"
    finally:
        for s in servers:
            s.stop()
