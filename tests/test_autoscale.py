"""Capacity observability + SLO-aware predictive pool autoscaling (ISSUE 10,
docs/autoscaling.md): the DemandTracker's per-second telemetry, the
Forecaster's EWMA+trend+peak model, the PoolAutoscaler's decision rules
(scale up early, shrink only after sustained idle, exactly-once decision
accounting), and the chaos-13 twin — a 10× arrival-rate step absorbed by
``mode=act`` but demonstrably NOT by ``mode=off``, on the real Kubernetes
executor over the in-repo fake cluster."""

import asyncio

import pytest

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import (
    DemandTracker,
    FlightRecorder,
    Forecaster,
    SloEngine,
    parse_objectives,
)
from bee_code_interpreter_tpu.resilience import (
    AdmissionController,
    PoolAutoscaler,
    PoolSupervisor,
    autoscale_snapshot,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import ChaosKubectl, FaultPlan, ManualClock
from tests.fakes import FakeExecutorPods

pytestmark = pytest.mark.chaos


@pytest.fixture
def clock():
    return ManualClock(1000.0)


# --------------------------------------------------------- demand tracker


def test_demand_tracker_windows(clock):
    d = DemandTracker(clock=clock)
    for _ in range(20):  # one second of 20 arrivals, 2 shed, 18 admitted
        d.record_arrival()
    for _ in range(2):
        d.record_shed()
    for i in range(18):
        d.record_admitted(queue_wait_s=0.05, in_flight=i + 1)
    clock.advance(1.0)
    assert d.rate_rps(10.0) == pytest.approx(2.0)  # 20 arrivals / 10s
    assert d.shed_count(60.0) == 2
    assert d.concurrency_high_water(60.0) == 18
    wait = d.queue_wait(60.0)
    assert wait["admitted"] == 18
    assert wait["avg_ms"] == pytest.approx(50.0)
    assert d.last_arrival_age_s() == pytest.approx(1.0)
    # the window actually slides: 200s later the burst is out of every view
    clock.advance(200.0)
    assert d.rate_rps(10.0) == 0.0
    assert d.concurrency_high_water(60.0) == 0


def test_demand_tracker_fleet_sink_ratio_and_spawns(clock):
    d = DemandTracker(clock=clock)
    assert d.warm_pop_ratio() == 1.0  # no checkouts: nothing was missed
    for spawn_s in (0.5, 1.0, 4.0):
        d.on_fleet_event({"state": "ready", "spawn_s": spawn_s})
    for _ in range(3):
        d.on_fleet_event({"state": "assigned", "reason": "warm_pop"})
    d.on_fleet_event({"state": "assigned", "reason": "cold_spawn"})
    assert d.warm_pop_ratio(60.0) == pytest.approx(0.75)
    assert d.spawn_latency_quantile(0.95) == pytest.approx(4.0)
    assert d.spawn_latency_quantile(0.5) == pytest.approx(1.0)
    snap = d.snapshot()
    assert snap["warm_pop_ratio_60s"] == pytest.approx(0.75)
    assert snap["spawn_samples"] == 3


# ------------------------------------------------------------- forecaster


def test_forecaster_steady_state_and_peak_envelope(clock):
    d = DemandTracker(clock=clock)
    f = Forecaster(d)
    for _ in range(20):  # 20 completed seconds at 2 rps
        d.record_arrival()
        d.record_arrival()
        clock.advance(1.0)
    fc = f.forecast()
    assert fc["level_rps"] == pytest.approx(2.0, abs=0.01)
    assert fc["trend_rps_per_s"] == pytest.approx(0.0, abs=0.01)
    assert fc["forecast_rps"] == pytest.approx(2.0, abs=0.01)
    # a 10x step registers through the peak envelope the SECOND it starts,
    # before any completed-second smoothing can see it
    for _ in range(20):
        d.record_arrival()
    assert f.forecast()["forecast_rps"] >= 20.0


def test_forecaster_trend_projects_a_ramp(clock):
    d = DemandTracker(clock=clock)
    f = Forecaster(d)
    for second in range(12):  # arrivals ramp 0,2,4,...: trend ~2 rps/s
        for _ in range(second * 2):
            d.record_arrival()
        clock.advance(1.0)
    fc = f.forecast()
    assert fc["trend_rps_per_s"] > 0.5
    assert fc["projected_rps"] > fc["level_rps"]


def test_forecast_horizon_follows_observed_spawn_p95(clock):
    d = DemandTracker(clock=clock)
    f = Forecaster(d, min_horizon_s=1.0, max_horizon_s=60.0)
    assert f.horizon_s() == 1.0  # floor before any spawn is observed
    for spawn_s in (2.0, 3.0, 8.0):
        d.on_fleet_event({"state": "ready", "spawn_s": spawn_s})
    assert f.horizon_s() == pytest.approx(8.0)
    d.on_fleet_event({"state": "ready", "spawn_s": 500.0})
    assert f.horizon_s() == 60.0  # clamped to the band


# ------------------------------------------------------------- autoscaler


class FakePool:
    """Duck-typed pool backend for decision-rule units."""

    def __init__(self, ready=2, spawning=0):
        self.pool_ready_count = ready
        self.pool_spawning_count = spawning
        self.pool_target_override = None


def make_autoscaler(clock, mode="act", **kw):
    metrics = kw.pop("metrics", Registry())
    d = DemandTracker(clock=clock)
    f = Forecaster(d)
    pool = FakePool()
    a = PoolAutoscaler(
        pool, f, d,
        mode=mode, min_size=1, max_size=16, idle_s=30.0, cooldown_s=10.0,
        base_target=2, clock=clock, metrics=metrics, **kw,
    )
    return a, d, pool, metrics


def test_scale_up_is_immediate_and_logged_exactly_once(clock):
    recorder = FlightRecorder()
    a, d, pool, metrics = make_autoscaler(clock, recorder=recorder)
    for _ in range(10):  # a 10-wide burst lands in the current second
        d.record_arrival()
        d.record_admitted(0.0, 10)
    decision = a.evaluate()
    assert decision is not None and decision["direction"] == "up"
    assert decision["to"] == 10 and decision["from"] == 2
    assert decision["applied"] is True
    assert pool.pool_target_override == 10
    assert a.evaluate() is None  # same demand: hold, not a duplicate
    # exactly once in the decision log, the wide-event stream, and the
    # counter — the acceptance's three surfaces
    assert [x["decision_id"] for x in a.decisions()] == [decision["decision_id"]]
    wide = recorder.events(kind="autoscale")
    assert [e["decision_id"] for e in wide] == [decision["decision_id"]]
    assert 'bci_autoscale_decisions_total{direction="up",reason="forecast"} 1' in (
        metrics.expose()
    )
    assert 'bci_pool_target_size 10' in metrics.expose()


def test_advise_mode_logs_but_never_actuates(clock):
    a, d, pool, metrics = make_autoscaler(clock, mode="advise")
    for _ in range(8):
        d.record_arrival()
        d.record_admitted(0.0, 8)
    decision = a.evaluate()
    assert decision is not None and decision["applied"] is False
    assert decision["mode"] == "advise"
    assert pool.pool_target_override is None  # zero actuation
    assert a.target == 8  # the recommendation is still recorded
    assert len(a.decisions()) == 1


def test_inverted_bounds_fail_at_construction(clock):
    # APP_AUTOSCALE_MIN above MAX must fail loudly where the blame is
    # local — silently widening max would scale past the operator's quota
    # cap (review finding).
    d = DemandTracker(clock=clock)
    with pytest.raises(ValueError, match="AUTOSCALE_MIN"):
        PoolAutoscaler(
            FakePool(), Forecaster(d), d, min_size=20, max_size=16,
            clock=clock,
        )


def test_static_target_above_max_raises_ceiling_not_clamped(clock):
    # The operator's configured static pool is the one size we KNOW they
    # want: a default-bounds upgrade must not report a recommendation
    # below it (review finding) — the ceiling widens (loudly) instead.
    d = DemandTracker(clock=clock)
    a = PoolAutoscaler(
        FakePool(), Forecaster(d), d, mode="advise", min_size=1,
        max_size=16, base_target=24, clock=clock,
    )
    assert a.target == 24
    assert a.snapshot()["max"] == 24


def test_off_mode_never_evaluates(clock):
    a, d, pool, _ = make_autoscaler(clock, mode="off")
    for _ in range(8):
        d.record_arrival()
    assert a.evaluate() is None
    assert a.decisions() == [] and pool.pool_target_override is None


def test_shrink_waits_for_sustained_idle_and_cooldown(clock):
    a, d, pool, _ = make_autoscaler(clock)  # idle_s=30, cooldown_s=10
    for _ in range(10):
        d.record_arrival()
        d.record_admitted(0.0, 10)
    assert a.evaluate()["to"] == 10
    # quiet, but not yet *sustained* idle: hold
    clock.advance(20.0)
    assert a.evaluate() is None
    # idle long enough, but inside the cooldown of the last decision? No —
    # 20+15 > 10s cooldown AND > 30s idle: the shrink happens, straight to
    # the clamped floor (forecast decayed, high-water window passed)
    clock.advance(60.0)
    down = a.evaluate()
    assert down is not None and down["direction"] == "down"
    assert down["reason"] == "idle" and down["to"] == 1
    assert pool.pool_target_override == 1
    # and never a second shrink inside the cooldown
    assert a.evaluate() is None


def test_slo_fast_burn_scales_up_one_notch_per_cooldown(clock):
    slo = SloEngine(parse_objectives(99.5, None), clock=clock)
    for _ in range(50):  # every request failing: the page pair fires
        slo.record(ok=False, duration_s=0.1)
    assert slo.snapshot()["fast_burn_alerting"]
    a, d, pool, _ = make_autoscaler(clock, slo=slo)
    d.record_arrival()  # trivial demand: the forecast alone would hold
    decision = a.evaluate()
    assert decision is not None
    assert decision["reason"] == "slo_burn" and decision["to"] == 3
    assert a.evaluate() is None  # next notch only after the cooldown
    clock.advance(10.0)
    assert a.evaluate()["to"] == 4


def test_autoscale_snapshot_shapes(clock):
    a, d, pool, _ = make_autoscaler(clock, mode="advise")
    f = a._forecaster
    body = autoscale_snapshot(demand=d, forecaster=f, autoscaler=a)
    assert body["mode"] == "advise" and body["target"] == 2
    assert body["demand"]["rps_10s"] == 0.0
    assert "forecast_rps" in body["forecast"]
    assert body["decisions"] == []
    # pool-less deployments: demand + forecast still answer
    body = autoscale_snapshot(demand=d, forecaster=f, autoscaler=None)
    assert body["mode"] is None and body["decisions"] == []
    assert body["demand"] is not None and body["forecast"] is not None


# ------------------------------------------- chaos 13: the 10x step (A/B)


@pytest.fixture
def faults():
    return FaultPlan()


@pytest.fixture
def pods(tmp_path, faults):
    return FakeExecutorPods(tmp_path / "pods", faults=faults)


BURST = 6  # 10x the 0.6-rps warmup trickle (arrivals per manual second)
STEP_SECONDS = 4


async def drive_surge(pods, storage, faults, clock, mode):
    """One arm of the chaos-13 A/B: warm trickle, then a 10× arrival step,
    executing for real through the Kubernetes executor over fake pods while
    the supervisor sweeps (and the autoscaler evaluates) each second.
    Returns everything the assertions need."""
    metrics = Registry()
    recorder = FlightRecorder()
    demand = DemandTracker(clock=clock, metrics=metrics)
    forecaster = Forecaster(demand)
    slo = SloEngine(parse_objectives(99.5, None), clock=clock)
    admission = AdmissionController(
        max_in_flight=32, max_queue=0, retry_after_s=0.1, metrics=metrics,
        demand=demand,
    )
    executor = KubernetesCodeExecutor(
        kubectl=ChaosKubectl(pods, faults),
        storage=storage,
        config=Config(
            executor_backend="kubernetes",
            executor_port=pods.port,
            executor_pod_queue_target_length=2,
            pod_ready_timeout_s=5,
            executor_retry_attempts=1,
            health_probe_timeout_s=0.5,
        ),
        metrics=metrics,
        ip_poll_interval_s=0.02,
    )
    executor.journal.add_sink(demand.on_fleet_event)
    autoscaler = PoolAutoscaler(
        executor, forecaster, demand,
        mode=mode, min_size=1, max_size=12, idle_s=30.0, cooldown_s=0.0,
        base_target=2, slo=slo, recorder=recorder, metrics=metrics,
        clock=clock,
    )
    supervisor = PoolSupervisor(
        executor, interval_s=60, autoscaler=autoscaler, metrics=metrics
    )

    async def one_request():
        async with admission.admit():
            t0 = clock.now
            result = await executor.execute("print(1)")
            assert result.stdout == "1\n"
            slo.record(ok=True, duration_s=clock.now - t0)

    async def settle_refills():
        # refills are kicked fire-and-forget; wait for the pool to reach
        # the CURRENT target before the next manual second fires
        for _ in range(400):
            if executor.pool_ready_count >= min(
                executor.pool_target, 12
            ) and executor.pool_spawning_count == 0:
                break
            await asyncio.sleep(0.01)

    def assigned_counts():
        warm = cold = 0
        for e in executor.journal.events():
            if e["state"] == "assigned":
                if e.get("reason") == "warm_pop":
                    warm += 1
                else:
                    cold += 1
        return warm, cold

    await executor.fill_executor_pod_queue()
    assert executor.pool_ready_count == 2

    # warm trickle: 3 manual seconds at ~0.6 rps
    for _ in range(3):
        await one_request()
        await supervisor.sweep_once()
        await settle_refills()
        clock.advance(1.0)

    # THE STEP: BURST concurrent arrivals per manual second. The per-burst
    # warm ratio comes from journal deltas (exactly this burst's checkouts);
    # the tracker publishes the same data as the windowed gauge.
    ratio_by_second = []
    for second in range(STEP_SECONDS):
        warm0, cold0 = assigned_counts()
        await asyncio.gather(*(one_request() for _ in range(BURST)))
        warm1, cold1 = assigned_counts()
        ratio_by_second.append((warm1 - warm0) / BURST)
        assert (warm1 - warm0) + (cold1 - cold0) == BURST
        await supervisor.sweep_once()
        await settle_refills()
        clock.advance(1.0)

    return {
        "executor": executor,
        "autoscaler": autoscaler,
        "recorder": recorder,
        "metrics": metrics,
        "demand": demand,
        "forecaster": forecaster,
        "slo": slo,
        "ratio_by_second": ratio_by_second,
    }


async def test_surge_act_absorbs_within_one_horizon_but_off_does_not(
    pods, storage, faults, clock, tmp_path
):
    """The acceptance A/B, asserted not narrated: under the identical 10×
    step, ``act`` recovers warm_pop_ratio ≥ 0.95 within one forecast
    horizon of the step while ``off`` never does, sheds stay inside the
    SLO error budget, and every decision lands exactly once in the
    decision log, the wide-event stream, and the counter."""
    act = await drive_surge(pods, storage, faults, clock, mode="act")
    pods_off = FakeExecutorPods(tmp_path / "pods-off", faults=faults)
    try:
        off = await drive_surge(
            pods_off, storage, faults, ManualClock(5000.0), mode="off"
        )

        # --- act: the first burst hits a 2-deep pool (cold spawns), the
        # sweep scales the pool, and every burst after one forecast horizon
        # (1 manual second here) pops warm
        horizon = act["forecaster"].horizon_s()
        assert horizon == pytest.approx(1.0)  # fake spawns are sub-second
        assert act["ratio_by_second"][0] < 0.95  # the step was a real step
        assert all(r >= 0.95 for r in act["ratio_by_second"][1:]), act[
            "ratio_by_second"
        ]
        assert act["executor"].pool_target >= BURST  # actuated
        assert act["executor"].pool_target_override is not None

        # --- off: the pool never grows, so EVERY burst keeps paying colds
        assert off["executor"].pool_target == 2
        assert off["executor"].pool_target_override is None
        assert all(r < 0.95 for r in off["ratio_by_second"]), off[
            "ratio_by_second"
        ]
        assert off["autoscaler"].decisions() == []

        # --- sheds inside the SLO error budget (availability 99.5%)
        arrivals = act["demand"].arrivals_total
        budget_requests = 0.005 * arrivals
        assert act["demand"].sheds_total <= budget_requests
        assert act["slo"].snapshot()["objectives"][0][
            "error_budget_remaining_ratio"
        ] == pytest.approx(1.0)

        # --- exactly-once decision accounting across the three surfaces
        decisions = act["autoscaler"].decisions()
        assert decisions, "the step must have produced at least one decision"
        ids = [d["decision_id"] for d in decisions]
        assert len(ids) == len(set(ids))
        wide_ids = [
            e["decision_id"]
            for e in act["recorder"].events(kind="autoscale")
        ]
        assert sorted(wide_ids) == sorted(ids)
        text = act["metrics"].expose()
        counted = sum(
            int(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith("bci_autoscale_decisions_total{")
        )
        assert counted == len(ids)
        snap = autoscale_snapshot(
            demand=act["demand"],
            forecaster=act["forecaster"],
            autoscaler=act["autoscaler"],
        )
        assert [d["decision_id"] for d in snap["decisions"]] == ids
    finally:
        await pods_off.close()
        await pods.close()


async def test_surge_advise_logs_decisions_with_zero_actuation(
    pods, storage, faults, clock
):
    """``advise`` under the same step: the pool never moves off its static
    target, but the decision log records what act WOULD have done — the
    production-trust path before anyone flips the mode."""
    try:
        result = await drive_surge(pods, storage, faults, clock, mode="advise")
        executor = result["executor"]
        assert executor.pool_target == 2  # static target untouched
        assert executor.pool_target_override is None
        assert executor.pool_ready_count <= 2
        decisions = result["autoscaler"].decisions()
        assert decisions and all(d["applied"] is False for d in decisions)
        assert all(d["mode"] == "advise" for d in decisions)
        # and the step kept paying colds — the log is how you SEE that act
        # would have fixed it
        assert all(r < 0.95 for r in result["ratio_by_second"])
    finally:
        await pods.close()


# ----------------------------------------------------- supervisor + wiring


async def test_supervisor_sweep_applies_act_target_via_refill(
    pods, storage, faults, clock
):
    """act-mode integration with the REAL supervisor refill: a burst's
    concurrency high-water raises the target, and the very next sweep
    replenishes the pool to it (not the static config length)."""
    metrics = Registry()
    demand = DemandTracker(clock=clock)
    forecaster = Forecaster(demand)
    executor = KubernetesCodeExecutor(
        kubectl=ChaosKubectl(pods, faults),
        storage=storage,
        config=Config(
            executor_backend="kubernetes",
            executor_port=pods.port,
            executor_pod_queue_target_length=1,
            pod_ready_timeout_s=5,
            executor_retry_attempts=1,
        ),
        metrics=metrics,
        ip_poll_interval_s=0.02,
    )
    executor.journal.add_sink(demand.on_fleet_event)
    autoscaler = PoolAutoscaler(
        executor, forecaster, demand, mode="act", min_size=1, max_size=4,
        base_target=1, clock=clock,
    )
    supervisor = PoolSupervisor(executor, interval_s=60, autoscaler=autoscaler)
    try:
        for _ in range(4):
            demand.record_arrival()
            demand.record_admitted(0.0, 4)
        await supervisor.sweep_once()
        for _ in range(400):
            if executor.pool_ready_count == 4:
                break
            await asyncio.sleep(0.01)
        assert executor.pool_ready_count == 4
        assert executor.pool_target == 4
    finally:
        await pods.close()


async def test_act_scale_down_trims_live_pool(pods, storage, faults, clock):
    """The shrink half of actuation (review finding): an act-mode down
    decision must reap the now-excess warm sandboxes — a scale-down that
    only stops refills would hold an idle pool at its peak size forever."""
    demand = DemandTracker(clock=clock)
    forecaster = Forecaster(demand)
    executor = KubernetesCodeExecutor(
        kubectl=ChaosKubectl(pods, faults),
        storage=storage,
        config=Config(
            executor_backend="kubernetes",
            executor_port=pods.port,
            executor_pod_queue_target_length=1,
            pod_ready_timeout_s=5,
            executor_retry_attempts=1,
        ),
        ip_poll_interval_s=0.02,
    )
    executor.journal.add_sink(demand.on_fleet_event)
    autoscaler = PoolAutoscaler(
        executor, forecaster, demand, mode="act", min_size=1, max_size=6,
        idle_s=30.0, cooldown_s=0.0, base_target=1, clock=clock,
    )
    supervisor = PoolSupervisor(executor, interval_s=60, autoscaler=autoscaler)
    try:
        # a burst scales the pool up to 5 and fills it
        for _ in range(5):
            demand.record_arrival()
            demand.record_admitted(0.0, 5)
        await supervisor.sweep_once()
        for _ in range(400):
            if executor.pool_ready_count == 5:
                break
            await asyncio.sleep(0.01)
        assert executor.pool_ready_count == 5
        # sustained idle: the down decision AND the trim land in one sweep
        clock.advance(120.0)
        await supervisor.sweep_once()
        assert autoscaler.target == 1
        assert executor.pool_ready_count == 1
        trims = [
            e for e in executor.journal.events()
            if e["state"] == "reaped" and e.get("reason") == "scaled_down"
        ]
        assert len(trims) == 4
        assert supervisor.snapshot()["trimmed"] == 4
    finally:
        await pods.close()


def test_application_context_wires_capacity_loop(tmp_path):
    """The composition root owns ONE demand tracker fed by the shared
    admission gate and the fleet journal, builds the autoscaler with the
    pool executor, and hands both edges the same snapshot builder."""
    from bee_code_interpreter_tpu.application_context import ApplicationContext

    ctx = ApplicationContext(
        Config(
            executor_backend="kubernetes",
            file_storage_path=str(tmp_path / "objects"),
            local_workspace_root=str(tmp_path / "ws"),
            disable_dep_install=True,
            autoscale_mode="advise",
        )
    )
    _ = ctx.code_executor
    assert ctx.autoscaler is not None and ctx.autoscaler.mode == "advise"
    assert ctx.admission._demand is ctx.demand
    assert ctx.supervisor._autoscaler is ctx.autoscaler
    # the journal sink is live: a checkout outcome reaches the tracker
    ctx.fleet.record("pod-x", "spawning")
    ctx.fleet.record("pod-x", "ready")
    ctx.fleet.record("pod-x", "assigned", reason="warm_pop")
    assert ctx.demand.warm_pop_ratio(60.0) == 1.0
    assert ctx.demand.spawn_latency_quantile(0.95) is not None
    body = ctx.autoscale_snapshot()
    assert body["mode"] == "advise" and body["target"] is not None
    # the bundle carries the autoscale section
    assert ctx.build_debug_bundle()["autoscale"]["mode"] == "advise"
    # and the metrics registered
    for name in (
        "bci_demand_rps",
        "bci_forecast_rps",
        "bci_pool_target_size",
        "bci_autoscale_decisions_total",
        "bci_warm_pop_ratio",
    ):
        assert name in ctx.metrics.metrics, name


# ------------------------------------------------------------- transports


async def test_http_autoscale_endpoint(local_executor, clock):
    from aiohttp.test_utils import TestClient, TestServer

    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    demand = DemandTracker(clock=clock)
    forecaster = Forecaster(demand)
    demand.record_arrival()
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        autoscale=lambda: autoscale_snapshot(
            demand=demand, forecaster=forecaster, autoscaler=None
        ),
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.get("/v1/autoscale")
        assert resp.status == 200
        body = await resp.json()
        assert body["demand"]["arrivals_total"] == 1
        assert "forecast_rps" in body["forecast"]
        assert body["decisions"] == []
    finally:
        await client.close()


async def test_http_autoscale_unwired_is_501(http_app):
    from aiohttp.test_utils import TestClient, TestServer

    client = TestClient(TestServer(http_app))
    await client.start_server()
    try:
        resp = await client.get("/v1/autoscale")
        assert resp.status == 501
    finally:
        await client.close()


async def test_grpc_get_autoscale_mirrors_http(clock):
    import json

    from bee_code_interpreter_tpu.api.grpc_server import ObservabilityServicer

    demand = DemandTracker(clock=clock)
    forecaster = Forecaster(demand)
    pool = FakePool()
    autoscaler = PoolAutoscaler(
        pool, forecaster, demand, mode="advise", base_target=2, clock=clock
    )
    servicer = ObservabilityServicer(
        autoscale=lambda: autoscale_snapshot(
            demand=demand, forecaster=forecaster, autoscaler=autoscaler
        )
    )
    reply = json.loads(await servicer.GetAutoscale(b"", None))
    assert reply["mode"] == "advise" and reply["target"] == 2
    assert reply["demand"]["arrivals_total"] == 0


# ---------------------------------------- empty-window edge cases (ISSUE 18)
# The capacity harness reads these documents straight into an actuator, so
# every accessor must stay finite and clamped when windows are empty, the
# window argument is garbage, or a writer hands in a poisoned float.


def test_demand_tracker_empty_windows_are_finite(clock):
    import math

    d = DemandTracker(clock=clock)
    assert d.warm_pop_ratio(60.0) == 1.0
    assert d.rate_rps(10.0) == 0.0
    assert d.peak_rps(60.0) == 0.0
    assert d.spawn_latency_quantile(0.95) is None
    assert d.queue_wait(60.0) == {
        "admitted": 0, "avg_ms": 0.0, "max_ms": 0.0,
    }
    snapshot = d.snapshot()
    for key, value in snapshot.items():
        if isinstance(value, float):
            assert math.isfinite(value), key


def test_demand_tracker_garbage_window_arguments(clock):
    d = DemandTracker(clock=clock)
    d.record_arrival()
    clock.advance(1.0)
    for bad in (float("nan"), float("inf"), -5.0, 0.0):
        assert d.rate_rps(bad) == 0.0
        assert d.warm_pop_ratio(bad) == 1.0
        assert d.peak_rps(bad) == 0.0
        assert d.shed_count(bad) == 0
    # -inf quantile clamps to the low end, +inf/nan to the high end.
    d.on_fleet_event({"state": "ready", "spawn_s": 1.0})
    assert d.spawn_latency_quantile(float("nan")) == 1.0
    assert d.spawn_latency_quantile(float("-inf")) == 1.0
    assert d.spawn_latency_quantile(9.0) == 1.0


def test_demand_tracker_rejects_poisoned_samples(clock):
    d = DemandTracker(clock=clock)
    for bad in (float("nan"), float("inf"), -1.0, "soon", None):
        d.on_fleet_event({"state": "ready", "spawn_s": bad})
    assert d.spawn_latency_quantile(0.95) is None
    d.on_fleet_event({"state": "ready", "spawn_s": 0.25})
    assert d.spawn_latency_quantile(0.95) == 0.25
    # A NaN queue wait keeps the admission COUNT but drops the sample.
    d.record_admitted(queue_wait_s=float("nan"), in_flight=3)
    d.record_admitted(queue_wait_s=float("inf"), in_flight=4)
    clock.advance(1.0)
    wait = d.queue_wait(60.0)
    assert wait["admitted"] == 2
    assert wait["avg_ms"] == 0.0 and wait["max_ms"] == 0.0
    assert d.concurrency_high_water(60.0) == 4


def test_forecaster_empty_demand_is_clamped_and_finite(clock):
    import math

    d = DemandTracker(clock=clock)
    f = Forecaster(d, min_horizon_s=2.0, max_horizon_s=30.0)
    assert f.horizon_s() == 2.0  # no spawn samples: the floor, not NaN
    doc = f.forecast()
    assert doc["samples"] == 0
    for key, value in doc.items():
        if isinstance(value, float):
            assert math.isfinite(value), key
    assert doc["forecast_rps"] == 0.0


def test_forecaster_inverted_horizon_band_is_normalized(clock):
    d = DemandTracker(clock=clock)
    # min > max (a config typo) must not pin horizon_s above its ceiling
    # forever — the band normalizes to [min, min].
    f = Forecaster(d, min_horizon_s=10.0, max_horizon_s=2.0)
    assert f.horizon_s() == 10.0
    d.on_fleet_event({"state": "ready", "spawn_s": 500.0})
    assert f.horizon_s() == 10.0
    # Non-finite band values fall back to defaults instead of spreading.
    f = Forecaster(
        d, min_horizon_s=float("nan"), max_horizon_s=float("inf")
    )
    assert f.horizon_s() == 60.0  # p95=500 clamped by the default ceiling


def test_autoscale_snapshot_recommendation_is_always_present(clock):
    body = autoscale_snapshot()
    rec = body["recommendation"]
    assert rec["target_replicas"] == 1 and rec["reason"] == "idle"
    demand = DemandTracker(clock=clock)
    forecaster = Forecaster(demand, min_horizon_s=1.0)
    for _ in range(40):
        demand.record_arrival()
    demand.record_admitted(queue_wait_s=0.0, in_flight=20)
    clock.advance(1.0)
    body = autoscale_snapshot(demand=demand, forecaster=forecaster)
    rec = body["recommendation"]
    # peak envelope 40 rps × 1s horizon / 8 per replica → 5 replicas.
    assert rec["target_replicas"] == 5
    assert rec["reason"] == "forecast"
    assert rec["per_replica_capacity"] == 8
