"""Prefix caching in the continuous batcher (models/serving.py).

The correctness bar is the same as for continuous batching itself: enabling
the prefix cache must not change ANY request's output, token for token —
hits only change which physical pages hold the prompt K/V and how much of
the prompt runs through the model at admission. On top of the equality
pins, these tests exercise the cache-management machinery itself:
refcounts, persistence past retirement, LRU eviction under pool pressure,
and the rollback path.

The reference has no serving stack at all (SURVEY §2); vLLM-style prefix
caching is part of this rebuild's decode family.
"""

import dataclasses

import numpy as np
import pytest

import jax

from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)
from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
PS = 4  # page size used throughout — small so prompts span several pages


def make_batcher(prefix_cache=True, **kw):
    kw.setdefault("max_batch", 3)
    kw.setdefault("n_pages", 40)
    kw.setdefault("page_size", PS)
    kw.setdefault("max_pages_per_seq", 8)
    return ContinuousBatcher(
        PARAMS, CFG, prefix_cache=prefix_cache, **kw
    )


def run_one(b, prompt, n=5, **kw):
    r = b.submit(prompt, n, **kw)
    b.run_to_completion()
    return b.result(r)


PROMPT = [5, 3, 7, 2, 9, 4, 1, 8, 6, 2]  # 10 tokens = 2 full pages + 2


def test_repeat_prompt_hits_and_output_is_unchanged():
    plain = make_batcher(prefix_cache=False)
    want = run_one(plain, PROMPT)

    b = make_batcher()
    assert run_one(b, PROMPT) == want  # miss: full admission
    assert b.prefix_stats["hits"] == 0
    assert run_one(b, PROMPT) == want  # hit: suffix-only admission
    assert b.prefix_stats["hits"] == 1
    assert b.prefix_stats["pages_reused"] == 2  # both full pages


def test_hit_persists_past_retirement_and_release():
    b = make_batcher()
    r = b.submit(PROMPT, 4)
    b.run_to_completion()
    b.result(r)
    b.release(r)
    assert len(b.evictable) > 0  # cached pages parked, not freed
    want = run_one(make_batcher(prefix_cache=False), PROMPT, 4)
    assert run_one(b, PROMPT, 4) == want
    assert b.prefix_stats["hits"] == 1


def test_diverging_prompt_shares_only_the_common_prefix():
    other = PROMPT[:8] + [9, 9, 3, 1]  # same 2 full pages, different tail
    plain = make_batcher(prefix_cache=False)
    want_a, want_b = run_one(plain, PROMPT), run_one(plain, other)

    b = make_batcher()
    assert run_one(b, PROMPT) == want_a
    assert run_one(b, other) == want_b
    assert b.prefix_stats["pages_reused"] == 2


def test_shared_pages_survive_sibling_retirement():
    """Two active rows share prefix pages; the first retiring must not
    free pages the second still reads (refcount, not ownership)."""
    plain = make_batcher(prefix_cache=False)
    w_short = run_one(plain, PROMPT, 2)
    plain2 = make_batcher(prefix_cache=False)
    w_long = run_one(plain2, PROMPT, 12)

    b = make_batcher()
    run_one(b, PROMPT, 2)  # populate the index
    r_long = b.submit(PROMPT, 12)   # hit: shares the 2 prefix pages
    r_short = b.submit(PROMPT, 2)   # hit: shares them too
    b.run_to_completion()           # short retires many steps early
    assert b.result(r_short) == w_short
    assert b.result(r_long) == w_long
    # while nothing is active the prefix pages sit in the LRU, not free
    assert (b.page_ref > 0).sum() == 0
    assert len(b.evictable) > 0


def test_eviction_under_pool_pressure():
    # pool sized so cached pages MUST be evicted to admit new prompts
    b = make_batcher(n_pages=12, max_pages_per_seq=4)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, CFG.vocab_size, 9).tolist() for _ in range(6)]
    for p in prompts:
        run_one(b, p, 3)
    assert b.prefix_stats["evictions"] > 0
    # evicted entries are really gone from the index
    assert len(b.prefix_index) == len(b.page_hash)
    live = set(b.prefix_index.values())
    assert live.isdisjoint(set(b.free_pages))
    # and the machinery still admits + decodes correctly after evictions
    want = run_one(make_batcher(prefix_cache=False), PROMPT, 3)
    assert run_one(b, PROMPT, 3) == want


def test_page_accounting_conserves_the_pool():
    b = make_batcher()
    n_total = 40 - 1  # minus the scratch page
    for prompt in (PROMPT, PROMPT, PROMPT[:8] + [1, 2, 3]):
        run_one(b, prompt, 3)
        held = (b.page_ref > 0).sum()
        assert len(b.free_pages) + len(b.evictable) + held == n_total


def test_sampled_requests_hit_deterministically():
    """Sampled requests on the hit path: same seed -> same output, every
    time. (Unlike greedy, sampled output is NOT pinned against the
    unshared path: the suffix-only admission is a different XLA program
    than the full prefill, and a temperature draw can tip on an
    ULP-different logit. The distribution is unchanged — greedy equality
    everywhere else in this file is the correctness pin.)"""
    sp = SamplingParams(temperature=0.8, top_k=5, seed=13)
    b = make_batcher()
    run_one(b, PROMPT, 2)
    first = run_one(b, PROMPT, 6, sampling=sp)
    assert b.prefix_stats["hits"] == 1
    again = run_one(b, PROMPT, 6, sampling=sp)
    assert again == first
    assert b.prefix_stats["hits"] == 2


def test_chunked_suffix_admission_matches():
    """A prefix hit combined with chunked admission: the suffix windows are
    chunk-bounded and the output still matches the unshared path."""
    long_prompt = (PROMPT * 2)[:17]  # 4 full pages + 1
    plain = make_batcher(prefix_cache=False)
    want = run_one(plain, long_prompt, 4)
    b = make_batcher()
    run_one(b, long_prompt, 4)
    assert run_one(b, long_prompt, 4, prefill_chunk=PS) == want
    assert b.prefix_stats["hits"] == 1
    assert b.prefix_stats["pages_reused"] == 4


def test_page_aligned_prompt_keeps_one_suffix_token():
    """An exactly page-aligned repeat prompt must still produce last-token
    logits: the match is capped so the final page re-runs as suffix. The
    recomputed final page then DISPLACES the original index entry
    (last-writer-wins) — the displaced page must lose its cache identity
    and return to the free list, keeping index<->page_hash a bijection."""
    aligned = PROMPT[:8]  # exactly 2 pages
    plain = make_batcher(prefix_cache=False)
    want = run_one(plain, aligned, 4)
    b = make_batcher()
    run_one(b, aligned, 4)
    assert run_one(b, aligned, 4) == want
    assert b.prefix_stats["pages_reused"] == 1  # capped at (L-1)//ps
    assert len(b.prefix_index) == len(b.page_hash)
    assert set(b.page_hash) == set(b.prefix_index.values())
    live = set(b.prefix_index.values())
    assert live.isdisjoint(set(b.free_pages))


def test_exhaustion_with_parked_prefix_pages_raises_cleanly():
    """Matched pages parked in the LRU must not count toward the
    fresh-page budget: an admission that matches them but cannot get
    enough fresh pages raises the pool-exhausted error, releases its
    acquired refs, and leaves the pool able to serve the next request."""
    # usable pages: 6 (7 minus scratch). Park the 2 PROMPT prefix pages,
    # then let an ACTIVE request hold the other 4 — a repeat PROMPT that
    # fits the pool statically (validate_request passes) must still hit
    # TRANSIENT exhaustion: its 2 matched pages leave 4 fresh needed with
    # 0 actually free.
    b = make_batcher(n_pages=7, max_pages_per_seq=8)
    run_one(b, PROMPT, 3)  # total 13 -> 4 pages; retires, 2 parked
    holder = b.submit([9, 8, 9, 8, 9], 10)  # total 15 -> 4 pages, ACTIVE
    assert len(b.free_pages) == 0 and len(b.evictable) == 2
    with pytest.raises(RuntimeError, match="page pool exhausted"):
        b.submit(PROMPT, 12)  # 6 pages <= 6 usable, but none free
    # acquired refs were released: only the holder's 4 pages are held,
    # and the 2 matched pages are parked again
    assert (b.page_ref > 0).sum() == 4
    assert len(b.evictable) == 2
    b.run_to_completion()
    b.result(holder)
    # and the pool still serves a request that fits
    want = run_one(make_batcher(prefix_cache=False), PROMPT, 3)
    assert run_one(b, PROMPT, 3) == want


def test_int8_pool_sharing_matches():
    cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    params = init_params(cfg, jax.random.PRNGKey(1))
    plain = ContinuousBatcher(
        params, cfg, max_batch=2, n_pages=40, page_size=PS,
        max_pages_per_seq=8, prefix_cache=False,
    )
    want = run_one(plain, PROMPT, 5)
    b = ContinuousBatcher(
        params, cfg, max_batch=2, n_pages=40, page_size=PS,
        max_pages_per_seq=8, prefix_cache=True,
    )
    assert run_one(b, PROMPT, 5) == want
    assert run_one(b, PROMPT, 5) == want
    assert b.prefix_stats["hits"] == 1


def test_speculative_serving_with_prefix_cache_matches():
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(draft_cfg, jax.random.PRNGKey(2))

    def batcher(prefix_cache):
        return ContinuousBatcher(
            PARAMS, CFG, max_batch=2, n_pages=40, page_size=PS,
            max_pages_per_seq=8, draft_params=draft,
            draft_config=draft_cfg, gamma=3, prefix_cache=prefix_cache,
        )

    want = run_one(batcher(False), PROMPT, 6)
    b = batcher(True)
    assert run_one(b, PROMPT, 6) == want
    assert run_one(b, PROMPT, 6) == want  # hit path, drafts replay suffix
    assert b.prefix_stats["hits"] == 1


def test_moe_config_refuses_prefix_cache():
    cfg = TransformerConfig.tiny_moe()
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(NotImplementedError, match="routing pools"):
        ContinuousBatcher(params, cfg, prefix_cache=True)


def test_short_prompt_never_shares():
    b = make_batcher()
    short = PROMPT[:3]  # under one page: nothing indexable
    want = run_one(make_batcher(prefix_cache=False), short, 3)
    assert run_one(b, short, 3) == want
    assert run_one(b, short, 3) == want
    assert b.prefix_stats["hits"] == 0
    assert not b.prefix_index
